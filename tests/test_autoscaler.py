"""Autonomous fleet control plane (ISSUE 18): autoscaler, predictive
admission, canaried rollout.

The load-bearing contracts:

- predictive admission computes an HONEST ``Retry-After`` — backlog
  ahead of the request's priority class divided by the measured fleet
  service rate — and proactively sheds classes whose predicted wait
  exceeds their bound (batch first, high last);
- the autoscaler's ``decide()`` is a PURE hysteresis/cooldown state
  machine: sustained burn/utilization scales up, sustained calm drains
  the least-loaded replica, a flapping signal (``scale_flap`` fault)
  moves nothing, and a recorded signal trace replays to byte-identical
  decisions with no fleet and no clock;
- ``/fleet/metrics`` advertises per-replica scrape age and EXCLUDES
  stale bodies from the aggregate; slo_report and the signal extractor
  treat stale replicas as missing, never as healthy-at-last-scrape;
- the canary judge reuses slo_report's burn gate and perf_gate's
  regression slack, refuses to promote on thin evidence, and the
  controller always rolls back to the exact previous argv/env;
- the judge's QUALITY axis (obs/quality.py): a latency-flat canary
  whose PSI drift or constraint-validity delta exceeds budget rolls
  back anyway; absent telemetry (None) never gates — quality is
  opt-in, not fail-closed;
- ``Fleet.scale_down`` drains the least-loaded replica by the router's
  score and RELEASES its supervision lease; ``scale_up`` mints fresh
  slots with fresh restart budgets.

Quick tier: injectable clocks/transports, canned expositions, fake
fleets. Slow tier: diurnal trace replay + SIGKILL mid-scale-down over
a real fleet (zero failed requests, compile pin), a deliberately
perf-regressed canary (``canary_regress`` fault) auto-rolling back
unattended with zero failed requests, and a SILENTLY-drifted canary
(``quality_drift`` fault: finite logits, flat latency) convicted by
the fingerprint axis alone — same zero-loss bar.
"""

import importlib.util
import json
import math
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from differential_transformer_replication_tpu.config import (
    AutoscalerConfig,
    RouterConfig,
)
from differential_transformer_replication_tpu.obs.registry import (
    Registry,
    parse_exposition,
)
from differential_transformer_replication_tpu.serving import admission
from differential_transformer_replication_tpu.serving.retry import (
    http_post_json_with_retries,
)
from differential_transformer_replication_tpu.serving.router import (
    DRAINING,
    UP,
    Router,
    serve_router,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolve __module__ via here
    spec.loader.exec_module(mod)
    return mod


autoscaler = _load_tool("autoscaler")
slo_report = _load_tool("slo_report")


def _cfg(**kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_backoff_s", 0.05)
    kw.setdefault("probe_backoff_max_s", 0.4)
    kw.setdefault("retry_base_s", 0.001)
    kw.setdefault("retry_cap_s", 0.01)
    kw.setdefault("wait_for_replica_s", 0.0)
    return RouterConfig(**kw)


def _scfg(**kw):
    return AutoscalerConfig(**kw)


class _Events:
    """Recording event sink (obs/events.py surface)."""

    def __init__(self):
        self.rows = []

    def emit(self, event, **kw):
        self.rows.append((event, kw))

    def names(self):
        return [e for e, _ in self.rows]

    def flush(self):
        pass

    def close(self):
        pass


def _stepper(step=1.0):
    """Injectable monotonic clock: advances ``step`` per call."""
    t = {"v": 0.0}

    def now():
        t["v"] += step
        return t["v"]

    return now


def _engine_expo(high=0, normal=0, batch=0, running=0, completed=0):
    """One replica's /metrics body, admission-relevant gauges only."""
    return (
        f'serving_queue_depth_by_class{{priority="high"}} {high}\n'
        f'serving_queue_depth_by_class{{priority="normal"}} {normal}\n'
        f'serving_queue_depth_by_class{{priority="batch"}} {batch}\n'
        f"serving_queue_depth {high + normal + batch}\n"
        f"serving_slot_occupancy {running}\n"
        f"serving_requests_completed_total {completed}\n"
    )


def _hist_expo(good, total, extra=""):
    """A fleet body whose TTFT histogram has ``good`` fast requests
    out of ``total`` (cumulative, as scrapes are)."""
    return (
        f'serving_ttft_seconds_bucket{{le="0.5"}} {good}\n'
        f'serving_ttft_seconds_bucket{{le="+Inf"}} {total}\n'
        f"serving_ttft_seconds_count {total}\n" + extra
    )


# -- admission math (the Retry-After oracle) -----------------------------


class TestAdmissionMath:
    def test_backlog_ahead_ranks_by_priority(self):
        queued = {"high": 1.0, "normal": 3.0, "batch": 2.0}
        assert admission.backlog_ahead(queued, 4.0, "high") == 5.0
        assert admission.backlog_ahead(queued, 4.0, "normal") == 8.0
        assert admission.backlog_ahead(queued, 4.0, "batch") == 10.0
        # unknown classes rank as normal; negative gauges clamp
        assert admission.backlog_ahead(queued, 4.0, "weird") == 8.0
        assert admission.backlog_ahead({"normal": -5.0}, -1.0, "normal") \
            == 0.0

    def test_predicted_wait(self):
        assert admission.predicted_wait_s(10.0, 2.0) == 5.0
        assert admission.predicted_wait_s(10.0, None) is None
        assert admission.predicted_wait_s(10.0, 0.0) is None
        assert admission.predicted_wait_s(-3.0, 2.0) == 0.0

    def test_honest_retry_after_floor_cap_fallback(self):
        # unmeasured fleet: static fallback (still floored at 1)
        assert admission.honest_retry_after(None, 3.0, 30.0) == 3.0
        assert admission.honest_retry_after(None, 0.2, 30.0) == 1.0
        # measured: floored at 1 s, capped at cap_s
        assert admission.honest_retry_after(0.1, 3.0, 30.0) == 1.0
        assert admission.honest_retry_after(12.5, 3.0, 30.0) == 12.5
        assert admission.honest_retry_after(1000.0, 3.0, 30.0) == 30.0


class TestAdmissionController:
    def _fed(self, **cfg_kw):
        ac = admission.AdmissionController(_cfg(**cfg_kw))
        ac.observe_replica("r0", _engine_expo(completed=0), now=0.0)
        ac.observe_replica(
            "r0",
            _engine_expo(high=1, normal=3, batch=2, running=4,
                         completed=20),
            now=10.0,
        )
        return ac

    def test_rate_and_predicted_wait_from_expositions(self):
        ac = self._fed()
        # 20 completions over 10 s -> 2/s (first measured rate seeds
        # the EWMA directly)
        assert ac.service_rate() == pytest.approx(2.0)
        assert ac.predicted_wait("normal") == pytest.approx(4.0)
        assert ac.predicted_wait("high") == pytest.approx(2.5)
        assert ac.predicted_wait("batch") == pytest.approx(5.0)
        assert ac.retry_after_s("normal") == pytest.approx(4.0)

    def test_admit_sheds_by_class_bound(self):
        ac = self._fed(admission_wait_bound_s=2.0)
        # normal bound 2.0 < wait 4.0 -> shed with the honest header
        d = ac.admit("normal")
        assert not d.admitted
        assert d.retry_after_s == pytest.approx(4.0)
        assert "normal" in d.reason
        # high tolerates 2x the bound: wait 2.5 <= 4.0 -> admitted
        assert ac.admit("high").admitted
        # batch tolerates half: wait 5.0 > 1.0 -> shed first
        assert not ac.admit("batch").admitted

    def test_unmeasured_fleet_admits(self):
        ac = admission.AdmissionController(
            _cfg(admission_wait_bound_s=0.5)
        )
        ac.observe_replica(
            "r0", _engine_expo(normal=50, running=8), now=0.0
        )
        d = ac.admit("normal")  # no rate yet: not evidence to shed on
        assert d.admitted and d.predicted_wait_s is None

    def test_restart_safe_counter_and_forget(self):
        ac = self._fed()
        # replica relaunch: completed counter goes 20 -> 5; the delta
        # contributes zero, never a negative rate
        ac.observe_replica("r0", _engine_expo(completed=5), now=20.0)
        rate = ac.service_rate()
        assert rate is not None and 0.0 <= rate < 2.0
        # scaled-away replica leaves the backlog model entirely
        ac.forget_replica("r0")
        assert ac.predicted_wait("batch") == pytest.approx(0.0)


# -- router integration: proactive shed, canary split, membership --------


class TestRouterAdmission:
    def _router(self, n=1, cfg=None):
        return Router(
            [f"http://127.0.0.1:{19000 + i}" for i in range(n)],
            cfg or _cfg(), rng=random.Random(0),
        )

    def _feed(self, router):
        router.admission.observe_replica(
            "r", _engine_expo(completed=0), now=0.0
        )
        router.admission.observe_replica(
            "r", _engine_expo(normal=8, running=2, completed=10),
            now=10.0,
        )  # rate 1/s; wait: normal 10 s, high 2 s

    def test_proactive_shed_with_honest_retry_after(self):
        router = self._router(cfg=_cfg(admission_wait_bound_s=2.5))
        self._feed(router)
        status, body, headers = router.handle_generate(
            {"prompt_ids": [1], "priority": "normal"}
        )
        assert status == 503
        assert body["code"] == "admission_shed"
        assert "trace_id" in body
        assert headers["Retry-After"] == "10"
        reg = router.registry.render()
        assert 'router_admission_shed_total{priority="normal"} 1' in reg

    def test_admitted_class_sheds_honest_on_no_replica(self):
        router = self._router(cfg=_cfg(admission_wait_bound_s=2.5))
        self._feed(router)
        # high's bound is 5 s > its 2 s wait: admitted past the gate,
        # but nothing is eligible -> no_replica shed STILL carries the
        # honest per-class header, not the static default
        status, body, headers = router.handle_generate(
            {"prompt_ids": [1], "priority": "high"}
        )
        assert status == 503
        assert body["code"] == "no_replica"
        assert headers["Retry-After"] == "2"

    def test_admission_off_restores_static_header(self):
        router = self._router(
            cfg=_cfg(admission_predictive=False, shed_retry_after_s=7.0)
        )
        assert router.admission is None
        status, body, headers = router.handle_generate(
            {"prompt_ids": [1]}
        )
        assert status == 503 and body["code"] == "no_replica"
        assert headers["Retry-After"] == "7"


class TestRouterCanary:
    def _up_router(self, n=2):
        router = Router(
            [f"http://127.0.0.1:{19000 + i}" for i in range(n)],
            _cfg(), rng=random.Random(0),
        )
        for r in router.replicas:
            r.note_probe_success(True, "healthy", {}, now=0.0)
        return router

    def test_split_fraction_and_pool_exclusion(self):
        router = self._up_router()
        a, b = router.replicas
        router.set_canary(b.url, 0.25)
        assert router.canary() == (b.url, 0.25)
        picks = [router.pick() for _ in range(2000)]
        frac = sum(1 for p in picks if p is b) / len(picks)
        # the canary's share is the configured fraction, NOT
        # fraction + a p2c share; everything else goes to the pool
        assert 0.18 < frac < 0.32
        assert all(p is a or p is b for p in picks)

    def test_new_pins_avoid_canary_and_clear_restores(self):
        router = self._up_router()
        a, b = router.replicas
        router.set_canary(b.url, 0.4)
        for i in range(20):
            assert router.pick(session_id=f"s{i}") is a
        router.set_canary(None)
        assert router.canary() == (None, 0.0)
        picks = [router.pick() for _ in range(200)]
        assert any(p is b for p in picks)  # back in the pool

    def test_canary_serves_when_alone(self):
        router = self._up_router()
        a, b = router.replicas
        router.set_canary(b.url, 0.1)
        with a.lock:
            a.state = DRAINING
        # serving beats shedding: the canary takes 100% when it is the
        # only eligible replica
        assert router.pick() is b

    def test_set_canary_validation(self):
        router = self._up_router()
        a, _b = router.replicas
        with pytest.raises(ValueError):
            router.set_canary("http://127.0.0.1:9999", 0.5)
        with pytest.raises(ValueError):
            router.set_canary(a.url, 0.0)
        with pytest.raises(ValueError):
            router.set_canary(a.url, 1.0)


class TestRouterMembership:
    def test_add_remove_replica_lifecycle(self):
        events = _Events()
        router = Router(
            ["http://127.0.0.1:19000", "http://127.0.0.1:19001"],
            _cfg(), rng=random.Random(0), events=events,
        )
        a, b = router.replicas
        rep = router.add_replica("http://127.0.0.1:19002")
        assert len(router.replicas) == 3
        with pytest.raises(ValueError):
            router.add_replica("http://127.0.0.1:19002")
        # pin a session to the new replica, then remove it: the pin
        # must not dangle
        rep.note_probe_success(True, "healthy", {}, now=0.0)
        assert router.pick(session_id="sess") is rep
        removed = router.remove_replica(rep.url)
        assert removed is rep
        assert len(router.replicas) == 2
        assert "sess" not in router._affinity
        # removing the canary clears the designation
        router.set_canary(b.url, 0.5)
        router.remove_replica(b.url)
        assert router.canary() == (None, 0.0)
        # the fleet never shrinks to zero through the router
        with pytest.raises(ValueError):
            router.remove_replica(a.url)
        assert router.remove_replica("http://127.0.0.1:9999") is None
        for name in ("replica_added", "replica_removed",
                     "canary_traffic_split"):
            assert name in events.names()

    def test_replicas_gauge_tracks_membership(self):
        router = Router(
            ["http://127.0.0.1:19000", "http://127.0.0.1:19001"],
            _cfg(), rng=random.Random(0),
        )
        router.add_replica("http://127.0.0.1:19002")
        assert "router_replicas 3" in router.registry.render()
        router.remove_replica("http://127.0.0.1:19002")
        assert "router_replicas 2" in router.registry.render()


# -- /fleet/metrics staleness (satellite c) ------------------------------


class TestFleetMetricsStaleness:
    def _queue_by_replica(self, text):
        _, samples = parse_exposition(text)
        return {
            labels["replica"]: v
            for n, labels, v in samples
            if n == "serving_queue_depth" and "replica" in labels
        }

    def test_stale_bodies_excluded_and_age_advertised(self):
        router = Router(
            ["http://127.0.0.1:19000", "http://127.0.0.1:19001"],
            _cfg(metrics_max_age_s=10.0), rng=random.Random(0),
        )
        r0, r1 = router.replicas
        with r0.lock:
            r0.metrics_text = "serving_queue_depth 5\n"  # no stamp
        with r1.lock:
            r1.metrics_text = "serving_queue_depth 7\n"
            r1.metrics_t = 100.0
        text = router.fleet_metrics(now=250.0)
        # the stale body is EXCLUDED from the aggregate, and its age
        # is advertised so downstream judges see it as missing
        per = self._queue_by_replica(text)
        assert per.get(r0.name) == 5.0  # unstamped: back-compat, kept
        assert r1.name not in per
        _, samples = parse_exposition(text)
        ages = {
            labels["replica"]: v for n, labels, v in samples
            if n == "fleet_scrape_age_seconds"
        }
        assert ages == {r1.name: pytest.approx(150.0)}
        # the unstamped replica advertises NO age (unknowable), so
        # downstream age gates never misjudge it
        # a fresh stamp re-admits the body
        with r1.lock:
            r1.metrics_t = 245.0
        per = self._queue_by_replica(router.fleet_metrics(now=250.0))
        assert per.get(r1.name) == 7.0

    def test_max_age_zero_disables_exclusion(self):
        router = Router(
            ["http://127.0.0.1:19000"], _cfg(metrics_max_age_s=0.0),
            rng=random.Random(0),
        )
        (r0,) = router.replicas
        with r0.lock:
            r0.metrics_text = "serving_queue_depth 3\n"
            r0.metrics_t = 0.0
        per = self._queue_by_replica(router.fleet_metrics(now=9999.0))
        assert per.get(r0.name) == 3.0


class TestSloReportStaleness:
    def _args(self, max_scrape_age=5.0):
        return SimpleNamespace(
            ttft=1.0, itl=0.25, target=0.99, availability_target=0.999,
            priority_class=None, max_scrape_age=max_scrape_age,
            max_burn=1.0, require_traffic=False,
        )

    TEXT = (
        'fleet_scrape_age_seconds{replica="a"} 100\n'
        'fleet_scrape_age_seconds{replica="b"} 1\n'
        'slo_burn_rate{objective="ttft",replica="a"} 5.0\n'
        'slo_burn_rate{objective="ttft",replica="b"} 0.2\n'
    )

    def test_stale_replicas_listed_and_gauges_dropped(self):
        rep = slo_report.report_from_exposition(self.TEXT, self._args())
        assert rep["stale_replicas"] == ["a"]
        assert rep["scrape_age_seconds"] == {"a": 100, "b": 1}
        live = rep["server_reported_burn_rates"]
        assert "ttft@b" in live and "ttft@a" not in live
        violations = slo_report.check(rep, self._args())
        assert violations and "stale" in violations[0]

    def test_age_gate_off_by_default(self):
        rep = slo_report.report_from_exposition(
            self.TEXT, self._args(max_scrape_age=0.0)
        )
        assert "stale_replicas" not in rep
        assert "ttft@a" in rep["server_reported_burn_rates"]
        assert not slo_report.check(rep, self._args(max_scrape_age=0.0))


# -- signal extraction ---------------------------------------------------


class TestSignalExtractor:
    def test_windowed_burn_and_util_scores(self):
        cfg = _scfg(ttft_threshold_s=0.5, slo_target=0.9,
                    stale_after_s=5.0)
        ex = autoscaler.SignalExtractor(cfg)
        gauges = (
            'serving_slots{replica="a"} 4\n'
            'serving_slot_occupancy{replica="a"} 2\n'
            'serving_queue_depth{replica="a"} 8\n'
            'serving_kv_utilization{replica="a"} 0.3\n'
            'fleet_replica_up{replica="a",state="up"} 1\n'
        )
        sig1 = ex.extract(_hist_expo(10, 10, gauges))
        assert sig1.ok and sig1.burn == pytest.approx(0.0)
        assert sig1.util == pytest.approx(1.0)  # queue 8 / 4 slots
        assert sig1.queue_depth == 8.0
        assert sig1.replicas_up == 1 and sig1.stale_replicas == 0
        # next poll: 10 new requests, all slow -> window err 1.0,
        # burn 1.0 / (1 - 0.9) = 10
        sig2 = ex.extract(_hist_expo(10, 20, gauges))
        assert sig2.burn == pytest.approx(10.0)

    def test_stale_replica_dropped_from_util(self):
        cfg = _scfg(stale_after_s=5.0)
        ex = autoscaler.SignalExtractor(cfg)
        sig = ex.extract(
            'serving_slots{replica="a"} 4\n'
            'serving_queue_depth{replica="a"} 8\n'
            'fleet_scrape_age_seconds{replica="a"} 10\n'
        )
        assert sig.stale_replicas == 1
        assert sig.util == 0.0 and sig.queue_depth == 0.0

    def test_shrinking_fleet_resets_window(self):
        ex = autoscaler.SignalExtractor(_scfg())
        ex.extract(_hist_expo(0, 30))
        # a replica left the aggregate: cumulative counts step BACK;
        # the window resets instead of reporting negative traffic
        sig = ex.extract(_hist_expo(0, 5))
        assert sig.burn is None

    def test_replica_utils_pressure_sources(self):
        utils = autoscaler._replica_utils({
            "serving_slots": 4.0,
            "serving_slot_occupancy": 2.0,
            "serving_queue_depth": 1.0,
            "serving_kv_utilization": 0.7,
            "serving_kv_pages_total": 100.0,
            "serving_kv_pages_free": 25.0,
            "serving_host_tier_budget_bytes": 1000.0,
            "serving_host_tier_bytes": 900.0,
        })
        assert utils == pytest.approx([0.5, 0.25, 0.7, 0.75, 0.9])


# -- the decision state machine ------------------------------------------


HIGH = dict(ok=True, burn=5.0, util=0.2)
LOW = dict(ok=True, burn=0.0, util=0.0)


def _sig(**kw):
    return autoscaler.Signals(**kw)


class TestAutoscalerDecide:
    def _scaler(self, **kw):
        cfg = _scfg(
            min_replicas=1, max_replicas=3, scale_up_burn=1.0,
            scale_down_burn=0.5, scale_up_sustain=3,
            scale_down_sustain=4, cooldown_up_s=5.0,
            cooldown_down_s=10.0, util_high=0.85, util_low=0.3, **kw,
        )
        return autoscaler.Autoscaler(cfg, initial_replicas=1)

    def test_hysteresis_needs_sustained_pressure(self):
        sc = self._scaler()
        assert sc.decide(_sig(**HIGH), 0.0).action == "hold"
        assert sc.decide(_sig(**HIGH), 1.0).action == "hold"
        d = sc.decide(_sig(**HIGH), 2.0)
        assert d.action == "up" and d.target == 2

    def test_cooldown_gates_consecutive_scale_ups(self):
        sc = self._scaler()
        for t in range(3):
            sc.decide(_sig(**HIGH), float(t))  # up at t=2
        assert sc.current == 2
        for t in (3.0, 4.0, 5.0, 6.0):
            d = sc.decide(_sig(**HIGH), t)
            assert d.action == "hold"
        assert "cooldown" in d.reason
        d = sc.decide(_sig(**HIGH), 7.0)  # 5 s since t=2: allowed
        assert d.action == "up" and d.target == 3

    def test_bounds_hold_at_max_and_min(self):
        sc = self._scaler()
        for t in range(3):
            sc.decide(_sig(**HIGH), float(t))
        for t in (7.0, 8.0, 9.0):
            sc.decide(_sig(**HIGH), t)  # second up at t=9
        assert sc.current == 3
        for t in (15.0, 16.0, 17.0):
            d = sc.decide(_sig(**HIGH), t)
        assert d.action == "hold" and "max_replicas" in d.reason
        # calm: down twice (cooldown-gated), then pinned at min
        t = 30.0
        downs = 0
        for _ in range(40):
            d = sc.decide(_sig(**LOW), t)
            downs += d.action == "down"
            t += 1.0
        assert downs == 2 and sc.current == 1
        assert "min_replicas" in d.reason

    def test_util_alone_triggers_and_burn_none_is_calm(self):
        sc = self._scaler()
        for t in range(3):
            d = sc.decide(_sig(ok=True, burn=None, util=0.95), float(t))
        assert d.action == "up"  # util pressure, no latency traffic
        sc2 = self._scaler()
        sc2.current = 2
        t = 0.0
        for _ in range(4):
            d = sc2.decide(_sig(ok=True, burn=None, util=0.0), t)
            t += 1.0
        assert d.action == "down"  # no traffic at all reads as calm

    def test_interleaved_signal_resets_streak(self):
        sc = self._scaler()
        sc.decide(_sig(**HIGH), 0.0)
        sc.decide(_sig(**HIGH), 1.0)
        sc.decide(_sig(ok=True, burn=0.7, util=0.5), 2.0)  # neither
        d = sc.decide(_sig(**HIGH), 3.0)
        assert d.action == "hold" and sc.current == 1

    def test_poll_failure_holds_and_freezes_streaks(self):
        sc = self._scaler()
        sc.decide(_sig(**HIGH), 0.0)
        sc.decide(_sig(**HIGH), 1.0)
        d = sc.decide(_sig(ok=False), 2.0)
        assert d.action == "hold" and "poll failed" in d.reason
        # the streak FROZE (a blackhole is not evidence of calm):
        # the next high tick completes the sustain
        d = sc.decide(_sig(**HIGH), 3.0)
        assert d.action == "up" and d.target == 2


class TestAutoscalerTick:
    def test_flap_fault_absorbed_by_hysteresis(self):
        faults.arm("scale_flap@0-19")
        events = _Events()
        sc = autoscaler.Autoscaler(
            _scfg(min_replicas=1, max_replicas=4, scale_up_sustain=2,
                  scale_down_sustain=2),
            poll=lambda: "", events=events, now_fn=_stepper(),
            initial_replicas=2,
        )
        decisions = [sc.tick() for _ in range(20)]
        # the injected oscillation (saturated <-> idle every tick)
        # never sustains either way: the fleet does not move
        assert all(d.action == "hold" for d in decisions)
        assert sc.current == 2
        assert events.names().count("autoscaler_decision") == 20

    def test_tick_records_and_replay_is_bit_identical(self, tmp_path):
        record = tmp_path / "scaler.jsonl"
        bodies = [
            _hist_expo(0, 10), _hist_expo(0, 20), _hist_expo(0, 30),
        ] + [_hist_expo(10 * k, 30 + 10 * k) for k in range(1, 9)]
        it = iter(bodies)
        cfg = _scfg(
            min_replicas=1, max_replicas=4, scale_up_sustain=2,
            scale_down_sustain=3, cooldown_up_s=1.0,
            cooldown_down_s=2.0, ttft_threshold_s=0.5, slo_target=0.9,
        )
        registry = Registry()
        sc = autoscaler.Autoscaler(
            cfg, poll=lambda: next(it), registry=registry,
            now_fn=_stepper(), record_path=str(record),
            initial_replicas=1,
        )
        live = [sc.tick() for _ in range(len(bodies))]
        sc.close()
        actions = [d.action for d in live]
        assert "up" in actions and "down" in actions
        rows = [
            json.loads(line)
            for line in record.read_text().splitlines() if line
        ]
        assert len(rows) == len(bodies)
        # the reproducibility contract: the recorded signal trace
        # replays through the pure state machine to BYTE-identical
        # decisions — no fleet, no clock, no poller
        replayed = autoscaler.replay(rows, cfg, initial_replicas=1)
        assert [d.to_row() for d in replayed] \
            == [row["decision"] for row in rows]
        reg = registry.render()
        assert "autoscaler_replicas_target" in reg
        assert 'autoscaler_decisions_total{action="up"} 1' in reg
        assert "autoscaler_burn_observed" in reg

    def test_actuation_failure_reverts_target(self):
        class _Failing:
            def replicas(self):
                return 1

            def scale_up(self, n=1):
                raise RuntimeError("SIGKILL mid-scale")

            def scale_down(self):
                raise RuntimeError("nope")

        events = _Events()
        sc = autoscaler.Autoscaler(
            _scfg(scale_up_sustain=1), poll=lambda: _hist_expo(0, 10),
            actuator=_Failing(), events=events, now_fn=_stepper(),
        )
        ex = autoscaler.SignalExtractor(sc.cfg)
        del ex
        d = sc.tick()
        assert d.action == "up" and d.target == 2
        # the scale never took: the target reverts so the state
        # machine must re-earn the decision next window
        assert sc.current == 1
        assert "autoscaler_scale_failed" in events.names()


class TestFleetActuator:
    def test_scale_paths_wire_fleet_and_router(self):
        calls = []

        class _F:
            replicas = [1, 2]

            def scale_up(self, n=1):
                calls.append(("fleet_up", n))
                return ["http://127.0.0.1:19007"]

            def scale_down(self, score_of=None):
                # the canary must be invisible to victim selection
                assert score_of("http://c") is None
                assert score_of("http://a") == 0.25
                calls.append(("fleet_down",))
                return "http://a"

        class _R:
            replicas = [
                SimpleNamespace(url="http://a", score=lambda: 0.25),
                SimpleNamespace(url="http://c", score=lambda: 0.0),
            ]

            def canary(self):
                return "http://c", 0.3

            def add_replica(self, url):
                calls.append(("router_add", url))

            def remove_replica(self, url):
                calls.append(("router_remove", url))

        act = autoscaler.FleetActuator(_F(), _R())
        assert act.replicas() == 2
        act.scale_up()
        act.scale_down()
        assert calls == [
            ("fleet_up", 1),
            ("router_add", "http://127.0.0.1:19007"),
            ("fleet_down",),
            ("router_remove", "http://a"),
        ]


# -- canary judgment -----------------------------------------------------


def _stats(**kw):
    base = {"count": 20.0, "error_ratio": 0.0, "burn_rate": 0.0,
            "target": 0.99, "p95_ttft_s": 0.5}
    base.update(kw)
    return base


class TestCanaryJudge:
    CFG = AutoscalerConfig(canary_min_requests=8, canary_max_burn=1.0,
                           canary_max_regress=0.5)

    def test_histogram_quantile(self):
        assert autoscaler.histogram_quantile([], [], 0, 0.95) is None
        assert autoscaler.histogram_quantile(
            [0.1, 0.5, 1.0], [5, 9, 10], 10, 0.5
        ) == 0.1
        assert autoscaler.histogram_quantile(
            [0.1, 0.5, 1.0], [5, 9, 10], 10, 0.95
        ) == 1.0
        assert autoscaler.histogram_quantile(
            [0.1], [1], 10, 0.95
        ) == math.inf

    def test_window_stats_deltas_and_restart_clamp(self):
        before = _hist_expo(5, 5)
        after = _hist_expo(14, 15)
        ws = autoscaler.window_stats([(before, after)], 0.5, 0.9)
        assert ws["count"] == 10.0
        assert ws["error_ratio"] == pytest.approx(0.1)
        assert ws["burn_rate"] == pytest.approx(1.0)
        assert ws["p95_ttft_s"] == math.inf  # the slow one is beyond
        # restarted replica: counters stepped back -> empty window,
        # never negative counts
        ws = autoscaler.window_stats([(after, before)], 0.5, 0.9)
        assert ws["count"] == 0.0 and ws["burn_rate"] is None
        ws = autoscaler.window_stats([("", "")], 0.5, 0.9)
        assert ws["count"] == 0.0

    def test_window_stats_extracts_quality_signals(self):
        """The quality keys ride the same scrape pairs: windowed
        entropy/margin means from the histogram _sum/_count deltas,
        worst (max) drift gauge and worst (min) validity rate from the
        AFTER bodies (gauges are levels, not counters)."""
        extra_b = (
            "serving_token_entropy_sum 10.0\n"
            "serving_token_entropy_count 4\n"
            "serving_logit_margin_sum 2.0\n"
            "serving_logit_margin_count 4\n"
            "serving_quality_drift 0.05\n"
            "serving_constraint_validity_rate 1.0\n"
        )
        extra_a = (
            "serving_token_entropy_sum 55.0\n"
            "serving_token_entropy_count 14\n"
            "serving_logit_margin_sum 4.0\n"
            "serving_logit_margin_count 14\n"
            "serving_quality_drift 0.42\n"
            "serving_constraint_validity_rate 0.9\n"
        )
        ws = autoscaler.window_stats(
            [(_hist_expo(5, 5, extra=extra_b),
              _hist_expo(14, 15, extra=extra_a))],
            0.5, 0.9,
        )
        assert ws["entropy_mean"] == pytest.approx(4.5)  # (55-10)/(14-4)
        assert ws["margin_mean"] == pytest.approx(0.2)
        assert ws["drift"] == pytest.approx(0.42)  # the after level
        assert ws["validity"] == pytest.approx(0.9)
        # no telemetry -> every quality key is None (gates pass open)
        ws = autoscaler.window_stats(
            [(_hist_expo(5, 5), _hist_expo(14, 15))], 0.5, 0.9
        )
        assert ws["entropy_mean"] is None and ws["margin_mean"] is None
        assert ws["drift"] is None and ws["validity"] is None
        # restart clamp: counts stepped backwards -> no mean, never
        # negative; drift still reads the after level
        ws = autoscaler.window_stats(
            [(_hist_expo(5, 5, extra=extra_a),
              _hist_expo(14, 15, extra=extra_b))],
            0.5, 0.9,
        )
        assert ws["entropy_mean"] is None
        assert ws["drift"] == pytest.approx(0.05)

    def test_window_stats_worst_drift_across_replicas(self):
        drifted = _hist_expo(9, 10, extra="serving_quality_drift 0.6\n")
        calm = _hist_expo(10, 10, extra="serving_quality_drift 0.01\n")
        inf_body = _hist_expo(
            10, 10, extra="serving_quality_drift +Inf\n"
        )
        ws = autoscaler.window_stats(
            [(_hist_expo(0, 0), calm), (_hist_expo(0, 0), drifted)],
            0.5, 0.9,
        )
        assert ws["drift"] == pytest.approx(0.6)
        # inf = incompatible fingerprint ladder: kept, so the judge
        # convicts rather than silently passing garbage bins
        ws = autoscaler.window_stats(
            [(_hist_expo(0, 0), calm), (_hist_expo(0, 0), inf_body)],
            0.5, 0.9,
        )
        assert ws["drift"] == math.inf

    def test_thin_evidence_rolls_back(self):
        verdict, reason = autoscaler.judge_canary(
            _stats(count=3.0), _stats(), self.CFG
        )
        assert verdict == "rollback" and "inconclusive" in reason

    def test_burn_violation_rolls_back(self):
        verdict, reason = autoscaler.judge_canary(
            _stats(burn_rate=5.0, error_ratio=0.05), _stats(), self.CFG
        )
        assert verdict == "rollback" and "burn rate" in reason

    def test_p95_regression_rolls_back(self):
        # control 0.5 s, 50% slack -> 0.75 s allowed; canary 1.0 s
        verdict, reason = autoscaler.judge_canary(
            _stats(p95_ttft_s=1.0), _stats(p95_ttft_s=0.5), self.CFG
        )
        assert verdict == "rollback" and "p95" in reason
        # within slack: promoted
        verdict, _ = autoscaler.judge_canary(
            _stats(p95_ttft_s=0.7), _stats(p95_ttft_s=0.5), self.CFG
        )
        assert verdict == "promote"

    def test_unbounded_canary_p95_rolls_back(self):
        verdict, reason = autoscaler.judge_canary(
            _stats(p95_ttft_s=math.inf), _stats(p95_ttft_s=0.5),
            self.CFG,
        )
        assert verdict == "rollback" and "histogram range" in reason

    def test_idle_control_skips_regression_gate(self):
        verdict, _ = autoscaler.judge_canary(
            _stats(p95_ttft_s=2.0), _stats(p95_ttft_s=None, count=0.0),
            self.CFG,
        )
        assert verdict == "promote"

    # -- the quality axis (obs/quality.py) ---------------------------

    def test_quality_drift_rolls_back_despite_flat_latency(self):
        verdict, reason = autoscaler.judge_canary(
            _stats(drift=0.30), _stats(), self.CFG
        )
        assert verdict == "rollback"
        assert "quality drift" in reason
        assert "latency alone would have promoted" in reason

    def test_quality_drift_inside_budget_promotes(self):
        for drift in (None, 0.0, 0.24, float("nan")):
            verdict, _ = autoscaler.judge_canary(
                _stats(drift=drift), _stats(), self.CFG
            )
            assert verdict == "promote", drift

    def test_quality_drift_inf_rolls_back(self):
        # inf = incompatible fingerprint ladder (drift_score contract):
        # a fingerprint that cannot be compared must not promote
        verdict, reason = autoscaler.judge_canary(
            _stats(drift=math.inf), _stats(), self.CFG
        )
        assert verdict == "rollback" and "quality drift" in reason

    def test_quality_drift_gate_off_at_zero_budget(self):
        cfg = AutoscalerConfig(
            canary_min_requests=8, canary_max_burn=1.0,
            canary_max_regress=0.5, canary_max_drift=0.0,
        )
        verdict, _ = autoscaler.judge_canary(
            _stats(drift=5.0), _stats(), cfg
        )
        assert verdict == "promote"

    def test_validity_delta_rolls_back(self):
        verdict, reason = autoscaler.judge_canary(
            _stats(validity=0.90), _stats(validity=1.0), self.CFG
        )
        assert verdict == "rollback"
        assert "constraint validity" in reason
        # within the 0.05 delta budget: promoted
        verdict, _ = autoscaler.judge_canary(
            _stats(validity=0.96), _stats(validity=1.0), self.CFG
        )
        assert verdict == "promote"

    def test_validity_baseline_defaults_to_perfect(self):
        # control without constrained traffic (validity None): the
        # canary is held to 1.0, not excused
        verdict, reason = autoscaler.judge_canary(
            _stats(validity=0.90), _stats(validity=None), self.CFG
        )
        assert verdict == "rollback" and "1.000" in reason
        verdict, _ = autoscaler.judge_canary(
            _stats(validity=0.97), _stats(validity=None), self.CFG
        )
        assert verdict == "promote"

    def test_validity_gate_off_at_zero_budget(self):
        cfg = AutoscalerConfig(
            canary_min_requests=8, canary_max_burn=1.0,
            canary_max_regress=0.5, canary_max_validity_delta=0.0,
        )
        verdict, _ = autoscaler.judge_canary(
            _stats(validity=0.1), _stats(validity=1.0), cfg
        )
        assert verdict == "promote"

    def test_latency_gates_rule_before_quality(self):
        # a canary that is BOTH slow and drifted is convicted on the
        # burn gate first — quality is the tiebreaker, not the lead
        verdict, reason = autoscaler.judge_canary(
            _stats(burn_rate=5.0, error_ratio=0.05, drift=0.9),
            _stats(), self.CFG,
        )
        assert verdict == "rollback" and "burn rate" in reason


class _FakeCanaryFleet:
    def __init__(self):
        self.replicas = [
            SimpleNamespace(index=0, url="http://c0"),
            SimpleNamespace(index=1, url="http://c1"),
        ]
        self.relaunches = []

    def relaunch_replica(self, index, server_args=None, extra_env=None,
                         argv=None, env=None, ready_check=None):
        self.relaunches.append({
            "index": index, "server_args": server_args,
            "extra_env": extra_env, "argv": argv, "env": env,
        })
        return ["old", "argv"], {"OLD": "1"}


class _FakeCanaryRouter:
    def __init__(self):
        self.calls = []
        self.replicas = []

    def set_canary(self, url, fraction=0.0):
        self.calls.append((url, fraction))


class TestCanaryController:
    def _run(self, canary_after, control_after):
        fleet = _FakeCanaryFleet()
        router = _FakeCanaryRouter()
        events = _Events()
        phase = {"v": "before"}
        expos = {
            ("http://c1", "before"): _hist_expo(0, 0),
            ("http://c1", "after"): canary_after,
            ("http://c0", "before"): _hist_expo(0, 0),
            ("http://c0", "after"): control_after,
        }
        cc = autoscaler.CanaryController(
            fleet, router,
            _scfg(canary_fraction=0.25, canary_window_s=0.5,
                  canary_min_requests=8, ttft_threshold_s=0.5,
                  slo_target=0.9),
            events=events,
            sleep_fn=lambda s: phase.__setitem__("v", "after"),
            fetch=lambda u: expos[(u, phase["v"])],
        )
        record = cc.run(server_args=["--model", "new"], index=1)
        return record, fleet, router, events

    def test_regressed_canary_rolls_back_to_old_argv(self):
        record, fleet, router, events = self._run(
            canary_after=_hist_expo(0, 20),     # 20 reqs, all slow
            control_after=_hist_expo(20, 20),   # 20 reqs, all fast
        )
        assert record["verdict"] == "rollback"
        assert len(fleet.relaunches) == 2
        assert fleet.relaunches[0]["server_args"] == ["--model", "new"]
        # the rollback relaunch passes back EXACTLY what the first
        # relaunch returned
        assert fleet.relaunches[1]["argv"] == ["old", "argv"]
        assert fleet.relaunches[1]["env"] == {"OLD": "1"}
        # the split always clears, promoted or not
        assert router.calls == [("http://c1", 0.25), (None, 0.0)]
        names = events.names()
        assert names.index("canary_started") \
            < names.index("canary_judged") \
            < names.index("canary_rolled_back")

    def test_healthy_canary_promotes_without_relaunch(self):
        record, fleet, router, events = self._run(
            canary_after=_hist_expo(20, 20),
            control_after=_hist_expo(20, 20),
        )
        assert record["verdict"] == "promote"
        assert len(fleet.relaunches) == 1  # no rollback relaunch
        assert router.calls == [("http://c1", 0.25), (None, 0.0)]
        assert "canary_promoted" in events.names()


# -- fault points (satellite b) ------------------------------------------


class TestControlPlaneFaults:
    def test_scale_flap_is_a_tick_window(self):
        faults.arm("scale_flap@2-4")
        assert not faults.scale_flap_at(1)
        assert all(faults.scale_flap_at(t) for t in (2, 3, 4))
        assert not faults.scale_flap_at(5)
        # NOT one-shot: the window persists across queries
        assert faults.scale_flap_at(3)

    def test_router_stale_metrics_consumes_n(self):
        faults.arm("router_stale_metrics@2")
        assert faults.consume("router_stale_metrics")
        assert faults.consume("router_stale_metrics")
        assert not faults.consume("router_stale_metrics")

    def test_canary_regress_is_persistent(self, monkeypatch):
        monkeypatch.setenv(faults.CANARY_REGRESS_ENV_VAR, "0.02")
        faults.arm("canary_regress")
        assert faults.canary_regress_armed()
        for _ in range(2):  # persistent: fires every iteration
            t0 = time.perf_counter()
            faults.serve_fire(0)
            assert time.perf_counter() - t0 >= 0.015
        assert faults.canary_regress_armed()

    def test_stale_metrics_fault_freezes_probe_body(self):
        faults.arm("router_stale_metrics@1000000")
        replies = {
            "/ready": (200, json.dumps(
                {"ready": True, "status": "healthy"}
            ).encode()),
            "/metrics": (200, b"serving_queue_depth 1\n"),
        }
        router = Router(
            ["http://127.0.0.1:19000"], _cfg(metrics_max_age_s=5.0),
            rng=random.Random(0),
        )
        router._http_get = lambda url, timeout: replies[
            "/" + url.rsplit("/", 1)[1]
        ]
        (rep,) = router.replicas
        router.probe(rep, now=0.0)
        # the blackholed scrape never lands: no body, no stamp
        assert rep.metrics_text == "" and rep.metrics_t is None
        faults.reset()
        router.probe(rep, now=1.0)
        assert rep.metrics_text and rep.metrics_t == 1.0


# -- fleet scale surface (satellite d) -----------------------------------


def _load_fleet():
    spec = importlib.util.spec_from_file_location(
        "fleet", os.path.join(TOOLS, "fleet.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestFleetScaling:
    def test_scale_down_drains_least_loaded_and_releases_lease(self):
        fleet_mod = _load_fleet()
        fleet = fleet_mod.Fleet(3, ports=[28100, 28101, 28102])
        scores = {
            fleet.replicas[0].url: 2.0,
            fleet.replicas[1].url: 0.5,
            fleet.replicas[2].url: 1.0,
        }
        fleet._relaunch_at[1] = 999.0  # pretend a relaunch is pending
        url = fleet.scale_down(score_of=scores.get)
        # least-loaded by the router's score, and its supervision
        # lease (pending relaunch) is RELEASED with the slot
        assert url.endswith(":28101")
        assert [r.index for r in fleet.replicas] == [0, 2]
        assert 1 not in fleet._relaunch_at
        # no scores at all: fall back to the highest index
        assert fleet.scale_down().endswith(":28102")
        with pytest.raises(ValueError):
            fleet.scale_down()  # never below one replica

    def test_scale_down_explicit_index(self):
        fleet_mod = _load_fleet()
        fleet = fleet_mod.Fleet(2, ports=[28105, 28106])
        assert fleet.scale_down(index=0).endswith(":28105")
        with pytest.raises(ValueError):
            fleet.scale_down(index=99)

    def test_scale_up_mints_fresh_slots(self):
        fleet_mod = _load_fleet()
        fleet = fleet_mod.Fleet(
            2, ports=[28110, 28111],
            server_args=["--event-log", "ev-{replica}.jsonl"],
        )
        launched = []
        fleet._launch = launched.append
        urls = fleet.scale_up(2, wait_ready=False)
        assert len(urls) == 2 and len(fleet.replicas) == 4
        assert [r.index for r in launched] == [2, 3]
        r2 = launched[0]
        assert r2.restarts == 0 and not r2.gave_up  # fresh budget
        assert "ev-2.jsonl" in r2.argv  # per-replica templating holds
        # indices are never reused: a scar on slot 3 cannot haunt a
        # future scale-up
        fleet.scale_down(index=3)
        assert fleet.scale_up(1, wait_ready=False)
        assert fleet.replicas[-1].index == 4

    def test_relaunch_replica_overrides_and_restores(self):
        fleet_mod = _load_fleet()
        fleet = fleet_mod.Fleet(
            1, ports=[28120], server_args=["--model", "base"]
        )
        fleet._restart_one = lambda r, ready_check=None: None
        old = fleet.relaunch_replica(
            0, server_args=["--model", "canary"],
            extra_env={"DTX_FAULTS": "canary_regress"},
        )
        r = fleet.replicas[0]
        assert "canary" in r.argv and "base" not in r.argv
        assert r.env["DTX_FAULTS"] == "canary_regress"
        assert "base" in old[0] and old[1] is None
        # rollback: pass back exactly what relaunch returned
        fleet.relaunch_replica(0, argv=old[0], env=old[1])
        assert r.argv == old[0] and r.env is None
        with pytest.raises(ValueError):
            fleet.relaunch_replica(99)


# -- serve_bench trace replay schedules (satellite a) --------------------


class TestTraceSchedules:
    @pytest.fixture(scope="class")
    def sb(self):
        return _load_tool("serve_bench")

    def test_diurnal_schedule_shape(self, sb):
        sched = sb.make_diurnal_schedule(60.0, 1.0, 5.0)
        assert sched == sorted(sched)
        assert all(0 < t < 60.0 for t in sched)
        assert len(sched) >= 60  # at least the low rate throughout
        # the peak half carries more arrivals than the edges
        mid = sum(1 for t in sched if 20.0 <= t < 40.0)
        edges = sum(1 for t in sched if t < 10.0 or t >= 50.0)
        assert mid > edges
        with pytest.raises(ValueError):
            sb.make_diurnal_schedule(0.0, 1.0, 5.0)
        with pytest.raises(ValueError):
            sb.make_diurnal_schedule(10.0, 5.0, 1.0)

    def test_trace_spec_parsing(self, sb, tmp_path):
        assert sb.load_trace_schedule("diurnal:60:1:5") \
            == sb.make_diurnal_schedule(60.0, 1.0, 5.0)
        with pytest.raises(SystemExit):
            sb.load_trace_schedule("diurnal:60:1")
        trace = tmp_path / "trace.jsonl"
        trace.write_text(
            '{"t": 2.0}\n{"t": 1.0, "x": 9}\nnot json\n{"no_t": 3}\n'
        )
        assert sb.load_trace_schedule(str(trace)) == [1.0, 2.0]
        empty = tmp_path / "empty.jsonl"
        empty.write_text("\n")
        with pytest.raises(SystemExit):
            sb.load_trace_schedule(str(empty))


# -- chaos (slow tier) ---------------------------------------------------


def _chaos_fleet(fleet_mod, n=2):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return fleet_mod.Fleet(
        n,
        server_args=["--num-slots", "2", "--prefill-chunk", "16",
                     "--prefill-budget", "32", "--drain-timeout", "60",
                     "--max-queue-len", "0"],
        env=env, max_restarts=3, backoff_base=0.2, backoff_max=2.0,
        ready_timeout_s=240.0,
    )


def _warm_ladder(url):
    for n in (1, 2, 4, 8, 16):
        status, body, _ = http_post_json_with_retries(
            url + "/generate",
            {"prompt_ids": [1] * n, "max_new_tokens": 2,
             "temperature": 0.0, "seed": 0},
            timeout=240, max_retries=2,
        )
        assert status == 200, (url, n, body)


def _chaos_router_cfg():
    return RouterConfig(
        probe_interval_s=0.05, probe_backoff_s=0.05,
        probe_backoff_max_s=0.5, eject_after=2, readmit_after=2,
        max_attempts=4, retry_base_s=0.02, retry_cap_s=0.2,
        default_deadline_s=120.0, wait_for_replica_s=5.0,
    )


@pytest.mark.slow
def test_chaos_trace_replay_sigkill_mid_scale_down_zero_loss(tmp_path):
    """Acceptance pin: a diurnal load trace replays through the router
    while the fleet scales 2->3->2, with the scale-down victim
    SIGKILLed MID-DRAIN — zero failed client requests (every arrival
    in the bench's out JSON served, none shed), the replica-hours and
    burn timelines land in the bench record, and every surviving
    replica's decode compile count stays pinned at 1 (scaling added no
    new shapes)."""
    fleet_mod = _load_fleet()
    fleet = _chaos_fleet(fleet_mod, 2)
    router = None
    httpd = None
    bench = None
    out = tmp_path / "trace_bench.jsonl"
    try:
        fleet.start()
        for url in fleet.urls:
            _warm_ladder(url)
        router = Router(fleet.urls, _chaos_router_cfg()).start()
        httpd = serve_router(router, port=0)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        actuator = autoscaler.FleetActuator(fleet, router)

        # scale 2 -> 3 BEFORE the trace: the chaos under load is the
        # scale-DOWN (the drain path is what must be zero-loss)
        (new_url,) = actuator.scale_up()
        by_url = {r.url: r for r in router.replicas}
        deadline = time.time() + 240
        while time.time() < deadline and not by_url[new_url].eligible():
            time.sleep(0.05)
        assert by_url[new_url].eligible(), "router never admitted"
        _warm_ladder(new_url)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        bench = subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "serve_bench.py"),
             "--trace", "diurnal:24:1:3", "--trace-window", "6",
             "--ttft-slo", "60", "--slo-target", "0.9",
             "--target", f"http://127.0.0.1:{port}/generate",
             "--clients", "4", "--new-tokens", "2", "--min-prompt", "4",
             "--max-prompt", "16", "--prefill-chunk", "16",
             "--vocab-size", "32", "--max-retries", "3", "--seed", "0",
             "--out", str(out)],
            env=env,
        )
        time.sleep(4.0)  # let the trace ramp onto the 3-wide fleet

        # scale 3 -> 2 under load, and SIGKILL the draining victim
        down_url = []
        th = threading.Thread(
            target=lambda: down_url.append(actuator.scale_down())
        )
        th.start()
        kill_deadline = time.time() + 30
        victim = None
        while victim is None and time.time() < kill_deadline:
            victim = next(
                (r for r in fleet.replicas if r.expected_exit), None
            )
            time.sleep(0.02)
        assert victim is not None, "scale_down never picked a victim"
        time.sleep(0.2)  # let the drain actually begin
        if victim.alive():  # SIGKILL mid-drain (uncatchable)
            victim.proc.send_signal(fleet_mod.signal.SIGKILL)
        th.join(120)
        assert not th.is_alive(), "scale_down hung"
        assert down_url and down_url[0] == victim.url
        assert len(fleet.replicas) == 2
        assert len(router.replicas) == 2
        assert victim.index not in [r.index for r in fleet.replicas]

        assert bench.wait(timeout=300) == 0
        rec = json.loads(out.read_text().splitlines()[-1])
        assert rec["metric"] == "serving_trace_replay"
        # ZERO failed client requests through the whole dance
        assert rec["shed"] == 0, rec
        assert rec["served"] == rec["offered"] > 0
        assert rec["violating_windows"] == 0
        assert rec["replica_seconds"] > 0
        assert len(rec["burn_timeline"]) == len(rec["windows"]) > 0
        assert any(n >= 2 for _, n in rec["replica_timeline"])

        # compile pin: scaling + the kill added no decode shapes
        for url in fleet.urls:
            _warm_ladder(url)
            with urllib.request.urlopen(url + "/health",
                                        timeout=30) as r:
                health = json.load(r)
            assert health["compiles"]["decode"] == 1, (url, health)
    finally:
        if bench is not None and bench.poll() is None:
            bench.kill()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()


@pytest.mark.slow
def test_chaos_canary_regress_auto_rollback_zero_loss():
    """Acceptance pin: a deliberately perf-regressed canary (the
    ``canary_regress`` fault armed via env on ONE relaunched replica)
    is judged and auto-rolled-back UNATTENDED — verdict rollback, the
    replica comes back on its original argv/env, and zero client
    requests fail across the relaunch/split/rollback dance."""
    fleet_mod = _load_fleet()
    fleet = _chaos_fleet(fleet_mod, 2)
    router = None
    httpd = None
    try:
        fleet.start()
        for url in fleet.urls:
            _warm_ladder(url)
        router = Router(fleet.urls, _chaos_router_cfg()).start()
        httpd = serve_router(router, port=0)
        gen_url = (
            f"http://127.0.0.1:{httpd.server_address[1]}/generate"
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        results = []
        results_lock = threading.Lock()
        stop = threading.Event()

        def client(wid):
            k = 0
            while not stop.is_set():
                k += 1
                req = urllib.request.Request(
                    gen_url,
                    data=json.dumps({
                        "prompt_ids": [1 + (wid + k) % 7] * (1 + k % 12),
                        "max_new_tokens": 2, "temperature": 0.0,
                        "seed": wid * 1000 + k, "timeout": 60,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=90) as r:
                        rec = (r.status, json.load(r))
                except urllib.error.HTTPError as e:
                    rec = (e.code, json.loads(e.read() or b"{}"))
                except OSError as e:
                    rec = (-1, {"error": repr(e)})
                with results_lock:
                    results.append(rec)

        workers = [
            threading.Thread(target=client, args=(w,)) for w in range(6)
        ]
        for w in workers:
            w.start()
        try:
            time.sleep(1.0)
            original_argv = list(fleet.replicas[1].argv)
            # the stall (every engine iteration sleeps 0.75 s) puts
            # every canary TTFT far past the 0.5 s objective while
            # still letting several requests finish inside the window
            # — the judge must convict on the BURN gate, not on thin
            # evidence
            cc = autoscaler.CanaryController(
                fleet, router,
                AutoscalerConfig(
                    canary_fraction=0.5, canary_window_s=12.0,
                    canary_min_requests=2, ttft_threshold_s=0.5,
                    slo_target=0.9, canary_max_burn=1.0,
                ),
            )
            record = cc.run(
                index=1,
                extra_env={"DTX_FAULTS": "canary_regress",
                           "DTX_CANARY_REGRESS_S": "0.75"},
            )
            time.sleep(1.0)  # serve a little while fully healed
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=180)
                assert not w.is_alive(), "client hung"

        # the regressed canary rolled back, unattended, convicted by
        # the burn gate on real evidence
        assert record["verdict"] == "rollback", record
        assert record["canary"]["count"] >= 2, record
        assert record["canary"]["burn_rate"] > 1.0, record
        # ...onto its ORIGINAL command line, faults gone
        assert fleet.replicas[1].argv == original_argv
        env1 = fleet.replicas[1].env or {}
        assert "DTX_FAULTS" not in env1
        # the split is off and the fleet is whole
        assert router.canary() == (None, 0.0)
        assert len(fleet.replicas) == 2
        # ZERO failed client requests through relaunch + rollback
        bad = [(s, b) for s, b in results if s != 200]
        assert not bad, f"{len(bad)} failed requests, first: {bad[:3]}"
        assert len(results) >= 10
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()


@pytest.mark.slow
def test_chaos_canary_quality_drift_auto_rollback_zero_loss(tmp_path):
    """Acceptance pin for the quality axis: a canary whose params are
    SILENTLY perturbed (``quality_drift`` fault — finite logits,
    greedy tokens unchanged on the control family, latency flat) is
    convicted by the PSI drift score against a recorded fingerprint
    and auto-rolled-back with ZERO failed client requests. Every
    latency gate is given generous slack, so the quality gate is the
    only one that can convict — a burn- or p95-triggered rollback
    would fail the reason assertion."""
    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        ServingConfig,
    )
    from differential_transformer_replication_tpu.models import init_model
    from differential_transformer_replication_tpu.obs.quality import (
        save_fingerprint,
    )
    from differential_transformer_replication_tpu.serving import (
        ServingEngine,
    )

    # record the reference fingerprint from an engine bit-matching the
    # server's random-init demo model (serving/server.py), driving the
    # same greedy traffic shape the chaos clients will send
    model_cfg = ModelConfig(
        model="control", vocab_size=512, n_embd=64, n_head=2,
        n_layer=2, block_size=128, compute_dtype="float32",
    )
    rec_eng = ServingEngine(
        init_model(jax.random.PRNGKey(0), model_cfg), model_cfg,
        ServingConfig(num_slots=2, prefill_chunk=16, prefill_budget=32,
                      quality_telemetry=True),
    )
    rec_eng.generate(
        [[1 + (w + k) % 7] * (1 + k % 12)
         for w in range(6) for k in range(1, 8)],
        max_new_tokens=2, temperature=0.0,
    )
    assert rec_eng.quality_stats()["tokens_observed"] >= 32
    fp = str(tmp_path / "quality_fp.json")
    save_fingerprint(fp, rec_eng.quality_fingerprint(
        meta={"model": "control", "source": "chaos-test"}
    ))

    fleet_mod = _load_fleet()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    fleet = fleet_mod.Fleet(
        2,
        server_args=["--num-slots", "2", "--prefill-chunk", "16",
                     "--prefill-budget", "32", "--drain-timeout", "60",
                     "--max-queue-len", "0",
                     "--quality-telemetry", "--quality-fingerprint", fp],
        env=env, max_restarts=3, backoff_base=0.2, backoff_max=2.0,
        ready_timeout_s=240.0,
    )
    router = None
    httpd = None
    try:
        fleet.start()
        for url in fleet.urls:
            _warm_ladder(url)
        router = Router(fleet.urls, _chaos_router_cfg()).start()
        httpd = serve_router(router, port=0)
        gen_url = (
            f"http://127.0.0.1:{httpd.server_address[1]}/generate"
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        results = []
        results_lock = threading.Lock()
        stop = threading.Event()

        def client(wid):
            k = 0
            while not stop.is_set():
                k += 1
                req = urllib.request.Request(
                    gen_url,
                    data=json.dumps({
                        "prompt_ids": [1 + (wid + k) % 7] * (1 + k % 12),
                        "max_new_tokens": 2, "temperature": 0.0,
                        "seed": wid * 1000 + k, "timeout": 60,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=90) as r:
                        rec = (r.status, json.load(r))
                except urllib.error.HTTPError as e:
                    rec = (e.code, json.loads(e.read() or b"{}"))
                except OSError as e:
                    rec = (-1, {"error": repr(e)})
                with results_lock:
                    results.append(rec)

        workers = [
            threading.Thread(target=client, args=(w,)) for w in range(6)
        ]
        for w in workers:
            w.start()
        try:
            time.sleep(1.0)
            original_argv = list(fleet.replicas[1].argv)
            # quality_drift@2 perturbs the canary's params two engine
            # iterations after it comes back: every request still
            # succeeds (finite logits, greedy argmax unchanged on
            # control), and the latency gates below are slack enough
            # that only the fingerprint's PSI score can convict
            cc = autoscaler.CanaryController(
                fleet, router,
                AutoscalerConfig(
                    canary_fraction=0.5, canary_window_s=15.0,
                    canary_min_requests=2, ttft_threshold_s=30.0,
                    slo_target=0.9, canary_max_burn=1000.0,
                    canary_max_regress=100.0, canary_max_drift=0.25,
                ),
            )
            record = cc.run(
                index=1,
                extra_env={"DTX_FAULTS": "quality_drift@2"},
            )
            time.sleep(1.0)  # serve a little while fully healed
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=180)
                assert not w.is_alive(), "client hung"

        # convicted on the QUALITY axis, not on any latency gate
        assert record["verdict"] == "rollback", record
        assert "quality drift" in record["reason"], record
        assert record["canary"]["drift"] > 0.25, record
        assert record["canary"]["count"] >= 2, record
        # latency stayed inside the (generous) judge slack: the burn
        # gate saw a healthy canary
        assert (record["canary"]["burn_rate"] or 0.0) <= 1000.0
        # ...rolled back onto its ORIGINAL command line, faults gone
        assert fleet.replicas[1].argv == original_argv
        env1 = fleet.replicas[1].env or {}
        assert "DTX_FAULTS" not in env1
        assert router.canary() == (None, 0.0)
        assert len(fleet.replicas) == 2
        # ZERO failed client requests through the whole dance
        bad = [(s, b) for s, b in results if s != 200]
        assert not bad, f"{len(bad)} failed requests, first: {bad[:3]}"
        assert len(results) >= 10
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()
