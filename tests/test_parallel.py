"""Sharding/mesh tests on the virtual 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``) — the stand-in for multi-chip
ICI (SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.parallel import (
    batch_sharding,
    create_mesh,
    make_param_specs,
    make_sharded_train_step,
    state_sharding,
)
from differential_transformer_replication_tpu.parallel.dp_step import (
    create_sharded_train_state,
)
from differential_transformer_replication_tpu.train import (
    create_train_state,
    make_train_step,
)

# vocab/width chosen divisible by the tensor axis
TINY_MODEL = dict(vocab_size=128, n_embd=32, n_head=2, n_layer=2, block_size=16,
                  dropout=0.0, compute_dtype="float32")


def make_cfg(model="diff", mesh=MeshConfig(), **kw):
    defaults = dict(
        vocab_size=128, learning_rate=1e-2, min_lr=1e-3, warmup_iters=2,
        max_iters=100, control_head_multiplier=1,
    )
    return TrainConfig(
        model=ModelConfig(model=model, **TINY_MODEL),
        mesh=mesh,
        **{**defaults, **kw},
    )


def make_batch(key, n_micro=1, batch=8, t=16, vocab=128):
    x = jax.random.randint(key, (n_micro, batch, t), 0, vocab)
    return {"x": x, "y": jnp.roll(x, -1, axis=-1)}


class TestMesh:
    def test_create_mesh_shapes(self):
        mesh = create_mesh(MeshConfig(data=2, fsdp=1, tensor=2, sequence=2))
        assert mesh.shape == {
            "pipeline": 1, "data": 2, "fsdp": 1, "tensor": 2, "sequence": 2,
        }

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            create_mesh(MeshConfig(data=16))

    def test_smaller_mesh_uses_device_prefix(self):
        mesh = create_mesh(MeshConfig(data=2, tensor=2))
        assert mesh.devices.size == 4


class TestParamSpecs:
    def test_specs_cover_tree_and_key_rules(self):
        cfg = ModelConfig(model="diff", **TINY_MODEL)
        from differential_transformer_replication_tpu.models import init_model

        params = init_model(jax.random.PRNGKey(0), cfg)
        specs = make_param_specs(params)
        assert specs["tok_emb"] == P("tensor", "fsdp")
        attn = specs["blocks"][0]["attn"]
        assert attn["wq"] == P(None, "fsdp", "tensor", None)
        assert attn["wv"] == P("fsdp", "tensor", None)
        assert attn["lambda_q"] == P(None, "tensor", None)
        assert attn["gn"]["w"] == P("tensor")
        assert attn["out"]["w"] == P("tensor", "fsdp")
        ffn = specs["blocks"][0]["ffn"]
        assert ffn["gate"]["w"] == P("fsdp", "tensor")
        assert ffn["out"]["w"] == P("tensor", "fsdp")
        assert specs["lm_head"]["w"] == P("fsdp", "tensor")
        assert specs["blocks"][0]["ln1"]["w"] == P()


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=8),
        MeshConfig(data=4, tensor=2),
        MeshConfig(data=2, fsdp=2, tensor=2),
    ],
    ids=["dp8", "dp4tp2", "dp2fsdp2tp2"],
)
class TestShardedStep:
    def test_sharded_matches_single_device(self, mesh_cfg):
        """The sharded step must be numerically equivalent to the
        single-device step — same params after one update."""
        cfg = make_cfg(mesh=mesh_cfg)
        mesh = create_mesh(mesh_cfg)

        state_single = create_train_state(jax.random.PRNGKey(0), cfg)
        batch = make_batch(jax.random.PRNGKey(1))

        step_single = make_train_step(cfg)
        s1, m1 = step_single(state_single, batch)

        state_sharded = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step_sharded = make_sharded_train_step(cfg, mesh, state_sharded)
        s2, m2 = step_sharded(state_sharded, batch)

        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        for a, b in zip(
            jax.tree_util.tree_leaves(s1["params"]),
            jax.tree_util.tree_leaves(s2["params"]),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=1e-5
            )

    def test_params_actually_sharded(self, mesh_cfg):
        """Params must be distributed, not replicated, whenever a non-data
        axis exists."""
        cfg = make_cfg(mesh=mesh_cfg)
        mesh = create_mesh(mesh_cfg)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        wq = state["params"]["blocks"][0]["attn"]["wq"]
        n_shards = len({s.device for s in wq.addressable_shards})
        assert n_shards == 8  # all devices hold a piece (or a replica)
        if mesh_cfg.tensor > 1:
            shard_shape = wq.addressable_shards[0].data.shape
            assert shard_shape[2] == wq.shape[2] // mesh_cfg.tensor


class TestShardedTraining:
    def test_loss_decreases_sharded(self):
        """Several sharded steps on dp4 x tp2: loss must decrease — the
        psum-by-partitioner gradient path is live end to end."""
        mesh_cfg = MeshConfig(data=4, tensor=2)
        cfg = make_cfg(mesh=mesh_cfg)
        mesh = create_mesh(mesh_cfg)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        batch = make_batch(jax.random.PRNGKey(2))
        first = None
        for _ in range(30):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first - 0.5

    def test_all_model_families_compile_sharded(self):
        mesh_cfg = MeshConfig(data=2, tensor=2, sequence=2)
        mesh = create_mesh(mesh_cfg)
        for kind in ("control", "diff", "ndiff"):
            cfg = make_cfg(model=kind, mesh=mesh_cfg)
            state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
            step = make_sharded_train_step(cfg, mesh, state)
            _, metrics = step(state, make_batch(jax.random.PRNGKey(3)))
            assert np.isfinite(float(metrics["loss"])), kind

    def test_batch_sharding_spec(self):
        mesh = create_mesh(MeshConfig(data=4, fsdp=2))
        sh = batch_sharding(mesh)
        # batch on data+fsdp, sequence dim on the context-parallel axis
        assert sh.spec == P(None, ("data", "fsdp"), "sequence")


class TestShardFlash:
    def test_shard_flash_op_matches_single_device(self):
        """The shard_map-wrapped flash kernel (parallel/shard_flash.py) on a
        dp4 x tp2 mesh must equal the plain single-device kernel — batch and
        head sharding are embarrassingly parallel, so this is pure slicing."""
        from differential_transformer_replication_tpu.ops.flash import (
            flash_diff_attention,
        )
        from differential_transformer_replication_tpu.parallel.shard_flash import (
            shard_flash_diff_attention,
        )

        mesh = create_mesh(MeshConfig(data=4, tensor=2))
        B, T, H, d = 8, 16, 4, 8
        ks_ = jax.random.split(jax.random.PRNGKey(7), 6)
        q1, k1, q2, k2 = (
            jax.random.normal(k, (B, T, H, d), jnp.float32) for k in ks_[:4]
        )
        v = jax.random.normal(ks_[4], (B, T, H, 2 * d), jnp.float32)
        lam = jax.random.uniform(ks_[5], (H,), jnp.float32, 0.1, 0.7)

        ref = flash_diff_attention(q1, k1, q2, k2, v, lam)
        out = shard_flash_diff_attention(q1, k1, q2, k2, v, lam, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_pallas_sharded_step_matches_single_device(self):
        """Full train step with attention_impl='pallas' on a dp2 x fsdp2 x
        tp2 mesh == the single-device pallas step (VERDICT r1 item 2: the
        north-star 'fused Pallas on v4-8' composition)."""
        mesh_cfg = MeshConfig(data=2, fsdp=2, tensor=2)
        model = ModelConfig(
            model="diff", vocab_size=128, n_embd=32, n_head=2, n_layer=2,
            block_size=16, compute_dtype="float32", attention_impl="pallas",
        )
        cfg = make_cfg(mesh=mesh_cfg)
        cfg = TrainConfig(
            model=model, mesh=mesh_cfg, vocab_size=128, learning_rate=1e-2,
            min_lr=1e-3, warmup_iters=2, max_iters=100,
            control_head_multiplier=1,
        )
        batch = make_batch(jax.random.PRNGKey(1))

        state_single = create_train_state(jax.random.PRNGKey(0), cfg)
        s1, m1 = make_train_step(cfg)(state_single, batch)

        mesh = create_mesh(mesh_cfg)
        state_sharded = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state_sharded)
        s2, m2 = step(state_sharded, batch)

        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        for a, b in zip(
            jax.tree_util.tree_leaves(s1["params"]),
            jax.tree_util.tree_leaves(s2["params"]),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=1e-5
            )

    def test_pallas_allowed_with_sequence_axis(self):
        """With a >1 sequence axis the ring path handles attention, so the
        pallas setting is inert and the step builds."""
        from differential_transformer_replication_tpu.parallel import (
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )

        mesh_cfg = MeshConfig(data=2, sequence=2)
        model = ModelConfig(
            model="diff", vocab_size=64, n_embd=32, n_head=2, n_layer=1,
            block_size=16, compute_dtype="float32", attention_impl="pallas",
        )
        cfg = TrainConfig(
            model=model, mesh=mesh_cfg, vocab_size=64, micro_batch_size=4,
            control_head_multiplier=1,
        )
        mesh = create_mesh(mesh_cfg)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        x = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0, 64)
        _, metrics = step(state, {"x": x, "y": jnp.roll(x, -1, -1)})
        assert jnp.isfinite(float(metrics["loss"]))
