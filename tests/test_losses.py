"""Fused chunked linear+cross-entropy tests (ops/losses.py).

The op must be numerically the dense path (models/common.py:apply_tail +
cross_entropy_loss) — same value, same gradients — while never
materializing full (B*T, V) logits. The dense path itself replicates the
reference's flattened F.cross_entropy (control.py:153-159).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.ops.losses import (
    fused_linear_cross_entropy,
)
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_train_step,
)


def dense_loss(h, w, b, t):
    logits = (h @ w + b).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.mean(jnp.take_along_axis(logp, t[..., None], -1)[..., 0])


class TestFusedLinearCrossEntropy:
    def _data(self, B=2, T=37, E=16, V=53):
        h = jax.random.normal(jax.random.PRNGKey(0), (B, T, E), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (E, V)) * 0.1
        b = jax.random.normal(jax.random.PRNGKey(2), (V,)) * 0.1
        t = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, V)
        return h, w, b, t

    @pytest.mark.parametrize("chunk", [16, 64, 1024])
    def test_value_matches_dense(self, chunk):
        # 74 positions: chunk=16 exercises tail padding, 1024 a single chunk
        h, w, b, t = self._data()
        ref = dense_loss(h, w, b, t)
        got = fused_linear_cross_entropy(h, w, b, t, chunk)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)

    def test_grads_match_dense(self):
        h, w, b, t = self._data()
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(h, w, b, t)
        gf = jax.grad(
            lambda h, w, b: fused_linear_cross_entropy(h, w, b, t, 16),
            argnums=(0, 1, 2),
        )(h, w, b)
        for a, c in zip(gd, gf):
            np.testing.assert_allclose(np.asarray(c), np.asarray(a), atol=1e-6)

    def test_no_bias(self):
        h, w, b, t = self._data()
        ref = dense_loss(h, w, jnp.zeros_like(b), t)
        got = fused_linear_cross_entropy(h, w, None, t, 32)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
        g = jax.grad(lambda h, w: fused_linear_cross_entropy(h, w, None, t, 32),
                     argnums=(0, 1))(h, w)
        assert all(bool(jnp.all(jnp.isfinite(x))) for x in g)

    def test_under_jit(self):
        h, w, b, t = self._data()
        ref = dense_loss(h, w, b, t)
        got = jax.jit(lambda h: fused_linear_cross_entropy(h, w, b, t, 16))(h)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)


class TestModelLossChunk:
    @pytest.mark.parametrize("family", ["control", "diff", "ndiff"])
    def test_forward_loss_matches_dense(self, family):
        m = ModelConfig(model=family, vocab_size=64, n_embd=32, n_head=2,
                        n_layer=2, block_size=16, compute_dtype="float32",
                        n_terms=3)
        params = init_model(jax.random.PRNGKey(0), m)
        x = jax.random.randint(jax.random.PRNGKey(1), (3, 16), 0, 64)
        y = jnp.roll(x, -1, -1)
        logits, ref = model_forward(params, x, m, targets=y)
        assert logits is not None
        mc = m.replace(loss_chunk=8)
        logits_f, got = model_forward(params, x, mc, targets=y)
        assert logits_f is None  # by design: logits never materialized
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
        # no targets -> logits still available (generate path unchanged)
        logits2, loss2 = model_forward(params, x, mc)
        assert logits2 is not None and loss2 is None

    def test_train_step_matches_dense(self):
        m = ModelConfig(model="diff", vocab_size=64, n_embd=32, n_head=2,
                        n_layer=2, block_size=16, compute_dtype="float32")
        base = TrainConfig(model=m, vocab_size=64, micro_batch_size=4,
                           control_head_multiplier=1, learning_rate=1e-2,
                           warmup_iters=0, max_iters=100)
        fused = base.replace(model=m.replace(loss_chunk=8))
        x = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0, 64)
        batch = {"x": x, "y": jnp.roll(x, -1, -1)}
        s_d = create_train_state(jax.random.PRNGKey(0), base)
        s_f = create_train_state(jax.random.PRNGKey(0), fused)
        step_d = make_train_step(base)
        step_f = make_train_step(fused)
        for _ in range(3):
            s_d, m_d = step_d(s_d, batch, None)
            s_f, m_f = step_f(s_f, batch, None)
        np.testing.assert_allclose(float(m_f["loss"]), float(m_d["loss"]), rtol=1e-5)
        for a, c in zip(jax.tree_util.tree_leaves(s_d["params"]),
                        jax.tree_util.tree_leaves(s_f["params"])):
            np.testing.assert_allclose(np.asarray(c), np.asarray(a), atol=5e-5)
