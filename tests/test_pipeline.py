"""Pipeline-parallelism tests (parallel/pipeline.py) on the virtual
8-device CPU mesh.

The reference has no pipeline (or any working distributed) machinery
(SURVEY.md section 2.3), so these tests pin OUR guarantee: the GPipe
schedule over the ``pipeline`` mesh axis is numerically the single-device
model — forward loss, gradients, and whole optimizer steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.parallel.mesh import create_mesh
from differential_transformer_replication_tpu.parallel.pipeline import (
    create_pipeline_train_state,
    make_pipeline_eval_step,
    make_pipeline_loss,
    make_pipeline_train_step,
    pipeline_state_sharding,
    stack_blocks,
    unstack_blocks,
)
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_train_step,
)


def tiny_model(family: str, n_layer: int = 4) -> ModelConfig:
    return ModelConfig(
        model=family,
        vocab_size=64,
        n_embd=32,
        n_head=2,
        n_layer=n_layer,
        block_size=16,
        dropout=0.0,
        compute_dtype="float32",
        n_terms=3,
    )


def microbatches(key, m: ModelConfig, n_micro: int = 6, batch: int = 4):
    x = jax.random.randint(key, (n_micro, batch, m.block_size), 0, m.vocab_size)
    return x, jnp.roll(x, -1, axis=-1)


def reference_mean_loss(params, x, y, m):
    return jnp.mean(
        jnp.stack(
            [model_forward(params, x[i], m, targets=y[i])[1] for i in range(x.shape[0])]
        )
    )


class TestPipelineParity:
    @pytest.mark.parametrize("family", ["control", "diff", "ndiff"])
    def test_loss_matches_single_device(self, family):
        m = tiny_model(family)
        mesh = create_mesh(MeshConfig(pipeline=4, data=2))
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref = reference_mean_loss(params, x, y, m)
        got = make_pipeline_loss(m, mesh)(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_grads_match_single_device(self):
        m = tiny_model("diff")
        mesh = create_mesh(MeshConfig(pipeline=4, data=2))
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref_grads = stack_blocks(
            jax.grad(lambda p: reference_mean_loss(p, x, y, m))(params)
        )
        pipe_grads = jax.grad(make_pipeline_loss(m, mesh))(stack_blocks(params), x, y)
        for r, p in zip(
            jax.tree_util.tree_leaves(ref_grads),
            jax.tree_util.tree_leaves(pipe_grads),
        ):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r), atol=2e-5)

    def test_pipeline_only_mesh(self):
        # all 8 devices as stages, no data axis
        m = tiny_model("diff", n_layer=8)
        mesh = create_mesh(MeshConfig(pipeline=8))
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m, n_micro=8, batch=2)
        ref = reference_mean_loss(params, x, y, m)
        got = make_pipeline_loss(m, mesh)(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_traced_layer_index_matches_static_schedule(self):
        # the lambda-init schedule is the only consumer of the traced layer
        # index: a 1-layer-per-stage split must still see layers 1..4
        m = tiny_model("diff")
        mesh = create_mesh(MeshConfig(pipeline=4))
        params = init_model(jax.random.PRNGKey(0), m)
        # make lambdas matter: non-zero lambda vectors
        for blk in params["blocks"]:
            blk["attn"]["lambda_q"] = (
                jnp.ones_like(blk["attn"]["lambda_q"]) * 0.3
            )
        x, y = microbatches(jax.random.PRNGKey(1), m, n_micro=4)
        ref = reference_mean_loss(params, x, y, m)
        got = make_pipeline_loss(m, mesh)(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_loss_chunk_matches(self):
        # the fused chunked lm-head loss (ops/losses.py, custom_vjp)
        # composes with the GPipe shard_map schedule
        m = tiny_model("diff").replace(loss_chunk=8)
        mesh = create_mesh(MeshConfig(pipeline=4, data=2))
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref = reference_mean_loss(params, x, y, m)
        loss_f = make_pipeline_loss(m, mesh)
        got = loss_f(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
        g = jax.grad(loss_f)(stack_blocks(params), x, y)
        assert all(
            bool(jnp.all(jnp.isfinite(l))) for l in jax.tree_util.tree_leaves(g)
        )

    def test_remat_matches(self):
        m = tiny_model("diff").replace(remat=True)
        mesh = create_mesh(MeshConfig(pipeline=4, data=2))
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref = reference_mean_loss(params, x, y, m)
        loss_f = make_pipeline_loss(m, mesh)
        got = loss_f(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
        # gradient path compiles and is finite under remat
        g = jax.grad(loss_f)(stack_blocks(params), x, y)
        assert all(
            bool(jnp.all(jnp.isfinite(l))) for l in jax.tree_util.tree_leaves(g)
        )


# mesh configurations test_step_matches_single_device_step runs under:
# pp x dp, and pp x tp x dp (pipeline x tensor composition).
# test_dropout_through_pipeline has its own list (it needs pipeline=2 so
# 4 microbatches still cover the stages).
_STEP_MESHES = [
    pytest.param(MeshConfig(pipeline=4, data=2), id="pp4xdp2"),
    pytest.param(MeshConfig(pipeline=2, tensor=2, data=2), id="pp2xtp2xdp2"),
    pytest.param(MeshConfig(pipeline=2, sequence=2, data=2), id="pp2xsp2xdp2"),
]


class TestPipelineTrainStep:
    def _cfg(self, pipeline=4, data=2, n_micro=6, mesh=None):
        m = tiny_model("diff")
        return TrainConfig(
            model=m,
            mesh=mesh if mesh is not None else MeshConfig(
                pipeline=pipeline, data=data
            ),
            vocab_size=m.vocab_size,
            micro_batch_size=4,
            grad_acc_steps=n_micro,
            control_head_multiplier=1,
            learning_rate=1e-2,
            warmup_iters=0,
            max_iters=100,
        )

    @pytest.mark.parametrize("mesh_cfg", _STEP_MESHES)
    def test_step_matches_single_device_step(self, mesh_cfg):
        cfg = self._cfg(mesh=mesh_cfg)
        mesh = create_mesh(cfg.mesh)
        x, y = microbatches(jax.random.PRNGKey(1), cfg.model)
        batch = {"x": x, "y": y}

        single = create_train_state(jax.random.PRNGKey(0), cfg)
        single_step = make_train_step(cfg)

        pipe = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        pipe_step = make_pipeline_train_step(cfg, mesh, pipe)

        for _ in range(2):
            single, sm = single_step(single, batch, None)
            pipe, pm = pipe_step(pipe, batch, None)
        # the step-2 loss is computed on params after one update — a wrong
        # pipeline update would move it
        np.testing.assert_allclose(float(pm["loss"]), float(sm["loss"]), rtol=1e-5)
        np.testing.assert_allclose(
            float(pm["grad_norm"]), float(sm["grad_norm"]), rtol=1e-4
        )
        # params: Adam's first steps are sign-like (m/sqrt(v) ~ sign(g)), so
        # fp32-level grad noise produces O(1e-4) param wiggle; a real
        # schedule/update bug would show at the lr=1e-2 scale
        ref_params = stack_blocks(single["params"])
        for r, p in zip(
            jax.tree_util.tree_leaves(ref_params),
            jax.tree_util.tree_leaves(pipe["params"]),
        ):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r), atol=5e-4)

    def test_state_is_stage_sharded(self):
        cfg = self._cfg()
        mesh = create_mesh(cfg.mesh)
        state = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        wq = state["params"]["blocks"]["attn"]["wq"]
        spec = wq.sharding.spec
        assert spec[0] == "pipeline", f"blocks not stage-sharded: {spec}"
        # each device holds n_layer / P layers
        shard = wq.addressable_shards[0]
        assert shard.data.shape[0] == cfg.model.n_layer // cfg.mesh.pipeline

    def test_eval_step(self):
        cfg = self._cfg()
        mesh = create_mesh(cfg.mesh)
        state = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        eval_step = make_pipeline_eval_step(cfg, mesh)
        x, y = microbatches(jax.random.PRNGKey(1), cfg.model, n_micro=1)
        got = eval_step(state["params"], x[0], y[0])
        params = unstack_blocks(
            jax.tree_util.tree_map(np.asarray, jax.device_get(state["params"])),
            cfg.model.n_layer,
        )
        _, ref = model_forward(params, x[0], cfg.model, targets=y[0])
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_eval_many_stream_matches_per_batch(self):
        """Feeding K eval batches as one microbatch stream (bubble
        amortized (P-1)/(K+P-1), VERDICT r1 item 7) must equal the mean of
        per-batch pipeline evals."""
        from differential_transformer_replication_tpu.parallel.pipeline import (
            make_pipeline_eval_many,
        )

        cfg = self._cfg()
        mesh = create_mesh(cfg.mesh)
        state = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        eval_step = make_pipeline_eval_step(cfg, mesh)
        eval_many = make_pipeline_eval_many(cfg, mesh)
        K = 4
        x, y = microbatches(jax.random.PRNGKey(3), cfg.model, n_micro=K)
        got = float(eval_many(state["params"], x, y))
        singles = [float(eval_step(state["params"], x[k], y[k])) for k in range(K)]
        np.testing.assert_allclose(got, np.mean(singles), rtol=1e-5)

    def test_stack_unstack_roundtrip(self):
        m = tiny_model("ndiff")
        params = init_model(jax.random.PRNGKey(0), m)
        back = unstack_blocks(stack_blocks(params), m.n_layer)
        for a, b in zip(
            jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_checkpoint_crosses_layouts(self, tmp_path):
        # pipeline-trained checkpoint loads into a single-device run (the
        # on-disk format is canonical list-of-blocks) and back into a
        # pipeline run
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        cfg = self._cfg()
        mesh = create_mesh(cfg.mesh)
        pipe = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, pipe, 1.23, cfg)

        # into the canonical single-device layout
        single_target = jax.device_get(create_train_state(jax.random.PRNGKey(1), cfg))
        single, best = load_checkpoint(path, cfg, single_target)
        assert best == 1.23
        ref = stack_blocks(single["params"])
        for r, p in zip(
            jax.tree_util.tree_leaves(ref),
            jax.tree_util.tree_leaves(jax.device_get(pipe["params"])),
        ):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(p))

        # and back into a stacked pipeline target
        pipe_target = jax.device_get(
            create_pipeline_train_state(jax.random.PRNGKey(2), cfg, mesh)
        )
        restored, _ = load_checkpoint(path, cfg, pipe_target)
        for r, p in zip(
            jax.tree_util.tree_leaves(restored["params"]),
            jax.tree_util.tree_leaves(jax.device_get(pipe["params"])),
        ):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(p))

    @pytest.mark.parametrize(
        "mesh_cfg",
        [
            pytest.param(MeshConfig(pipeline=2, data=2), id="pp2xdp2"),
            pytest.param(
                MeshConfig(pipeline=2, tensor=2, data=2), id="pp2xtp2xdp2"
            ),
        ],
    )
    def test_dropout_through_pipeline(self, mesh_cfg):
        """Dropout is live on the pipeline path: rng threads through the
        GPipe schedule per (shard, microbatch, layer). Deterministic per
        key, varying across keys, inert without one."""
        m = tiny_model("diff").replace(dropout=0.3)
        mesh = create_mesh(mesh_cfg)
        loss_f = make_pipeline_loss(m, mesh)
        params = stack_blocks(init_model(jax.random.PRNGKey(0), m))
        x = jax.random.randint(
            jax.random.PRNGKey(1), (4, 4, m.block_size), 0, m.vocab_size
        )
        y = jnp.roll(x, -1, -1)
        la = float(loss_f(params, x, y, jax.random.PRNGKey(2)))
        lb = float(loss_f(params, x, y, jax.random.PRNGKey(2)))
        lc = float(loss_f(params, x, y, jax.random.PRNGKey(3)))
        l0 = float(loss_f(params, x, y))
        lref = float(
            make_pipeline_loss(m.replace(dropout=0.0), mesh)(params, x, y)
        )
        assert la == lb and np.isfinite(la)
        assert la != lc  # different key, different masks
        assert l0 == lref  # no key => eval semantics == dropout-free model
        # grads flow through the dropped maps
        g = jax.grad(lambda p: loss_f(p, x, y, jax.random.PRNGKey(2)))(params)
        gn = float(
            jnp.sqrt(sum(jnp.sum(a ** 2) for a in jax.tree_util.tree_leaves(g)))
        )
        assert np.isfinite(gn) and gn > 0

    def test_rejects_bad_configs(self):
        m = tiny_model("diff", n_layer=3)  # not divisible by 2
        mesh = create_mesh(MeshConfig(pipeline=2, data=2))
        with pytest.raises(ValueError, match="not divisible"):
            make_pipeline_loss(m, mesh)
        with pytest.raises(ValueError, match="pipeline axis"):
            make_pipeline_loss(tiny_model("diff"), create_mesh(MeshConfig(data=2)))


class TestPipelineTensorComposition:
    """Pipeline x tensor / x sequence parallelism (VERDICT r2 weak item
    6): the GPipe schedule is manual over data/fsdp/pipeline while
    ``tensor`` and ``sequence`` stay GSPMD auto axes — matmuls/loss shard
    with the Megatron specs (parallel/sharding.py), activations shard
    their T dim. Parity against the single-device model is the
    guarantee."""

    def _mesh(self, **kw):
        return create_mesh(MeshConfig(**kw))

    # every family under each auto-axis composition: tp (Megatron
    # matmul sharding), sp (GSPMD-SP T-sharding — control/ndiff exercise
    # RoPE over a T-sharded activation), and tp x sp together (data=1:
    # an 8-device ceiling, not a restriction; the data pmean composes
    # with each auto axis in the dp2 meshes here and in _STEP_MESHES)
    @pytest.mark.parametrize("family", ["control", "diff", "ndiff"])
    @pytest.mark.parametrize(
        "mesh_kw",
        [
            pytest.param(dict(pipeline=2, tensor=2, data=2), id="pp2xtp2xdp2"),
            pytest.param(
                dict(pipeline=2, sequence=2, data=2), id="pp2xsp2xdp2"
            ),
            pytest.param(
                dict(pipeline=2, tensor=2, sequence=2), id="pp2xtp2xsp2"
            ),
        ],
    )
    def test_loss_matches_single_device(self, family, mesh_kw):
        m = tiny_model(family)
        mesh = self._mesh(**mesh_kw)
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref = reference_mean_loss(params, x, y, m)
        got = make_pipeline_loss(m, mesh)(stack_blocks(params), x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_sequence_grads_match_single_device(self):
        m = tiny_model("diff")
        mesh = self._mesh(pipeline=2, sequence=2, data=2)
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref_grads = stack_blocks(
            jax.grad(lambda p: reference_mean_loss(p, x, y, m))(params)
        )
        pipe_grads = jax.grad(make_pipeline_loss(m, mesh))(stack_blocks(params), x, y)
        for r, p in zip(
            jax.tree_util.tree_leaves(ref_grads),
            jax.tree_util.tree_leaves(pipe_grads),
        ):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r), atol=2e-5)

    def test_grads_match_single_device(self):
        # n_head == tensor axis: every tensor shard holds exactly one
        # head, the evenly head-sharded production configuration
        m = tiny_model("diff").replace(n_head=4)
        mesh = self._mesh(pipeline=2, tensor=4)
        params = init_model(jax.random.PRNGKey(0), m)
        x, y = microbatches(jax.random.PRNGKey(1), m)
        ref_grads = stack_blocks(
            jax.grad(lambda p: reference_mean_loss(p, x, y, m))(params)
        )
        pipe_grads = jax.grad(make_pipeline_loss(m, mesh))(stack_blocks(params), x, y)
        for r, p in zip(
            jax.tree_util.tree_leaves(ref_grads),
            jax.tree_util.tree_leaves(pipe_grads),
        ):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r), atol=2e-5)

    def test_state_is_stage_and_tensor_sharded(self):
        m = tiny_model("diff")
        cfg = TrainConfig(
            model=m,
            mesh=MeshConfig(pipeline=2, tensor=2, data=2),
            vocab_size=m.vocab_size,
            micro_batch_size=4,
            grad_acc_steps=4,
            control_head_multiplier=1,
            max_iters=100,
        )
        mesh = create_mesh(cfg.mesh)
        state = create_pipeline_train_state(jax.random.PRNGKey(0), cfg, mesh)
        wq = state["params"]["blocks"]["attn"]["wq"]
        spec = tuple(wq.sharding.spec)
        assert spec[0] == "pipeline", spec
        assert "tensor" in spec, f"wq not tensor-sharded under pp x tp: {spec}"
        # the head axis of the stacked (L, S, E, H, d) wq is split over tp
        shard = wq.addressable_shards[0]
        assert shard.data.shape[0] == m.n_layer // cfg.mesh.pipeline
        assert shard.data.shape[-2] == m.n_head // cfg.mesh.tensor

    # train-step and dropout parity under pp x tp run as the
    # pp2xtp2xdp2 parametrization of TestPipelineTrainStep's
    # test_step_matches_single_device_step / test_dropout_through_pipeline


def test_sequence_impl_inert_under_pipeline_warns():
    """pipeline x sequence runs GSPMD-SP dense attention; the configured
    ring/ulysses schedule cannot nest inside the pipeline's shard_map and
    is IGNORED — the config must say so out loud rather than silently
    running something else (parallel/pipeline.py:_check_pipeline_cfg)."""
    m = tiny_model("diff")
    mesh = create_mesh(MeshConfig(pipeline=2, sequence=2, data=2))
    with pytest.warns(UserWarning, match="GSPMD-SP only"):
        make_pipeline_loss(m, mesh)
