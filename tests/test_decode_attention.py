"""Fused Pallas decode attention + int8 KV cache (ops/decode_attention.py,
models/decode.py pool path, serving wiring).

The load-bearing contracts:

- the fused single-query kernel matches the XLA twin on identical inputs
  for all three combine families, staggered positions, both KV dtypes;
- float-KV greedy decoding through the pallas impl is BIT-IDENTICAL to
  the XLA impl, via ``generate_cached`` AND through the serving engine
  (mixed-length prompts, slot reuse);
- the int8 path is exact between impls on the same quantized cache and
  tolerance-close to the float path; ``quantize_kv`` round-trips within
  half a scale step;
- the engine's zero-recompile pin (decode compiles exactly once) holds
  with the kernel and quantized cache on, across staggered mixed-length
  requests and ring rollover;
- int8 roughly halves KV bytes per slot, asserted via the new
  ``serving_kv_cache_bytes_per_slot`` gauge;
- per-channel int8 weight quantization round-trips within bounds and
  keeps greedy decoding tolerance-close.
"""

import json
import subprocess
import sys
from functools import lru_cache
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.models.decode import (
    forward_decode_pool,
    init_cache,
    kv_store_dtype,
)
from differential_transformer_replication_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
    dequantize_kv,
    quantize_kv,
    quantize_params_int8,
)
from differential_transformer_replication_tpu.serving import ServingEngine

REPO = Path(__file__).resolve().parents[1]
FAMILIES = ("control", "diff", "ndiff")


def _cfg(kind, **kw):
    base = dict(
        model=kind, vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


@lru_cache(maxsize=None)
def _setup(kind, **kw):
    cfg = _cfg(kind, **kw)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _greedy(params, cfg, prompt, n, **kw):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0, **kw,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------


def _rand_case(S, B, H, M, d, dv, kv_dtype, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    qs = jax.random.normal(ks[0], (S, B, H, d), jnp.float32)
    k = jax.random.normal(ks[1], (S, B, H, M, d), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, M, dv), jnp.float32)
    # staggered positions incl. a partially-filled row and a full ring
    pos = jnp.asarray(
        [(7 * b + 3) % M if b % 2 else M - 1 for b in range(B)], jnp.int32
    )
    coeffs = jax.random.uniform(
        ks[3], (S, H), jnp.float32, minval=-1.0, maxval=1.0
    )
    scales = None
    if kv_dtype == "int8":
        k, ksc = quantize_kv(k)
        v, vsc = quantize_kv(v)
        scales = (ksc, vsc)
    return qs, k, v, pos, coeffs, scales


@pytest.mark.parametrize("kind", FAMILIES)
@pytest.mark.parametrize("kv", ["float", "int8"])
def test_kernel_matches_xla_reference(kind, kv):
    """The fused kernel and the materialized-softmax twin agree to fp32
    tile-accumulation noise on identical inputs — per family (S=1/2/N
    combine), staggered per-row positions, both KV dtypes."""
    S = {"control": 1, "diff": 2, "ndiff": 4}[kind]
    qs, k, v, pos, coeffs, scales = _rand_case(
        S, B=5, H=2, M=32, d=16, dv=16 if kind == "control" else 32,
        kv_dtype=kv,
    )
    if scales is None:
        fused = decode_attention(qs, k, v, pos, coeffs)
        ref = decode_attention_reference(qs, k, v, pos, coeffs)
    else:
        ksc, vsc = scales
        fused = decode_attention(
            qs, k, v, pos, coeffs, k_scale=ksc, v_scale=vsc
        )
        ref = decode_attention_reference(
            qs, dequantize_kv(k, ksc, qs.dtype),
            dequantize_kv(v, vsc, qs.dtype), pos, coeffs,
        )
    assert fused.shape == ref.shape
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_kernel_respects_ring_visibility():
    """A row at position p must ignore cache slots > p: poison the
    invisible tail with huge values and require the output unchanged."""
    qs, k, v, pos, coeffs, _ = _rand_case(
        1, B=1, H=1, M=16, d=8, dv=8, kv_dtype="float"
    )
    pos = jnp.asarray([5], jnp.int32)
    base = decode_attention(qs, k, v, pos, coeffs)
    k_poison = k.at[:, :, :, 6:, :].set(1e4)
    v_poison = v.at[:, :, 6:, :].set(1e4)
    poisoned = decode_attention(qs, k_poison, v_poison, pos, coeffs)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(poisoned))


def test_quantize_kv_roundtrip_bounds():
    """Symmetric per-vector int8: |dequant - x| <= scale/2 elementwise,
    scales carry the vector shape, all-zero vectors stay NaN-free."""
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 4, 5, 16)) * 7.5
    x = x.at[0, 0, 0].set(0.0)  # all-zero vector must not 0/0
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8
    assert scale.shape == x.shape[:-1]
    back = dequantize_kv(q, scale, jnp.float32)
    assert bool(jnp.isfinite(back).all())
    err = jnp.abs(back - x)
    bound = scale[..., None] * 0.5 + 1e-6
    assert bool((err <= bound).all())
    np.testing.assert_array_equal(np.asarray(back[0, 0, 0]), 0.0)


# ---------------------------------------------------------------------------
# generate_cached parity (pallas vs xla impls)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", FAMILIES)
def test_generate_cached_greedy_bit_parity(kind):
    """Float-KV greedy decoding is bit-identical between the pallas pool
    path and the XLA chunk path for every family (acceptance pin)."""
    cfg, params = _setup(kind)
    prompt = _prompts([9], cfg.vocab_size)[0]
    ref = _greedy(params, cfg, prompt, 8)
    pal = _greedy(
        params, cfg.replace(decode_attention_impl="pallas"), prompt, 8
    )
    assert pal == ref


def test_generate_cached_bf16_greedy_bit_parity():
    """The bf16 storage path ("bf16 stays bit-identical"): same pin at
    bfloat16 compute + forced bf16 KV storage."""
    cfg, params = _setup("control", compute_dtype="bfloat16",
                         kv_cache_dtype="bf16")
    prompt = _prompts([9], cfg.vocab_size)[0]
    ref = _greedy(params, cfg, prompt, 8)
    pal = _greedy(
        params, cfg.replace(decode_attention_impl="pallas"), prompt, 8
    )
    assert pal == ref


@pytest.mark.parametrize("kind", FAMILIES)
def test_generate_cached_int8_parity(kind):
    """int8 KV: both impls read the SAME quantized cache, so greedy
    decoding is bit-identical between them; vs the float cache the
    error is tolerance-bounded — teacher-forced logits stay within the
    quantization noise and greedy trajectories agree for a long prefix
    before (possibly) forking. Token-level agreement AFTER a fork is
    meaningless (a forked sequence diverges everywhere by construction),
    so the gate is (logits tolerance, fork index), not a match
    fraction."""
    from differential_transformer_replication_tpu.models.decode import (
        forward_chunk,
    )

    cfg, params = _setup(kind)
    prompt = _prompts([9], cfg.vocab_size)[0]
    i8 = cfg.replace(kv_cache_dtype="int8")
    ref_i8 = _greedy(params, i8, prompt, 16)
    pal_i8 = _greedy(
        params, i8.replace(decode_attention_impl="pallas"), prompt, 16
    )
    assert pal_i8 == ref_i8
    ref_f = _greedy(params, cfg, prompt, 16)
    first_div = next(
        (i for i, (a, b) in enumerate(zip(ref_i8, ref_f)) if a != b), 16
    )
    assert first_div >= 8, (
        f"int8 forked from float too early: {first_div}"
    )
    ids = jnp.asarray([prompt], jnp.int32)
    l_f, _ = forward_chunk(params, ids, 0, init_cache(cfg, 1), cfg)
    l_q, _ = forward_chunk(params, ids, 0, init_cache(i8, 1), i8)
    np.testing.assert_allclose(
        np.asarray(l_q), np.asarray(l_f), atol=2e-2
    )


def test_ring_rollover_parity_quantized():
    """pos > block_size: the quantized ring cache must roll correctly —
    pallas+int8 bit-matches xla+int8 while the window slides, and the
    fused run stays finite past several rollovers."""
    cfg, params = _setup("control", block_size=16)
    prompt = _prompts([10], cfg.vocab_size)[0]
    n = 30  # 10 + 30 = 2.5x the ring
    i8 = cfg.replace(kv_cache_dtype="int8")
    ref = _greedy(params, i8, prompt, n)
    pal = _greedy(
        params, i8.replace(decode_attention_impl="pallas"), prompt, n
    )
    assert pal == ref
    # and the float pallas path matches the float XLA path out there too
    assert _greedy(
        params, cfg.replace(decode_attention_impl="pallas"), prompt, n
    ) == _greedy(params, cfg, prompt, n)


# ---------------------------------------------------------------------------
# serving engine parity + pins
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", FAMILIES)
def test_engine_greedy_parity_pallas(kind):
    """Mixed-length prompts through a 2-slot pool with the fused kernel
    on produce exactly the tokens the XLA ``generate_cached`` produces —
    the serving-side half of the acceptance pin (slot reuse, queueing,
    per-row positions included)."""
    cfg, params = _setup(kind)
    prompts = _prompts([3, 9, 14, 6, 11], cfg.vocab_size)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=4, prefill_budget=6,
                      decode_attention_impl="pallas"),
    )
    assert eng.cfg.decode_attention_impl == "pallas"  # override applied
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _greedy(params, cfg, p, 8)
        assert o.finish_reason == "length"


def test_engine_int8_matches_generate_cached_int8():
    """The engine's pallas+int8 decode bit-matches per-request
    ``generate_cached`` under the same quantized-cache config."""
    cfg, params = _setup("diff")
    i8 = cfg.replace(kv_cache_dtype="int8",
                     decode_attention_impl="pallas")
    prompts = _prompts([5, 12, 8], cfg.vocab_size, seed=4)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=4, prefill_budget=8,
                      decode_attention_impl="pallas",
                      kv_cache_dtype="int8"),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _greedy(params, i8, p, 8)


def test_engine_decode_compile_pin_pallas_int8():
    """THE zero-recompile pin with the kernel + quantized cache on:
    staggered mixed-length requests (continuous batch composition
    changes every few iterations) compile the decode closure exactly
    once; ring rollover (max_seq_len > block_size) adds no shapes."""
    cfg, params = _setup("control", block_size=16)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=3, prefill_chunk=4, prefill_budget=8,
                      max_seq_len=40,
                      decode_attention_impl="pallas",
                      kv_cache_dtype="int8"),
    )
    prompts = _prompts([3, 9, 14, 6, 11, 5], cfg.vocab_size, seed=7)
    done = []
    for i, p in enumerate(prompts):
        # stagger submissions between steps so batch composition churns
        eng.submit(p, max_new_tokens=4 + (i % 3) * 6, temperature=0.0)
        done.extend(eng.step())
    while eng.has_work():
        done.extend(eng.step())
    assert len(done) == len(prompts)
    stats = eng.compile_stats()
    assert stats["decode"] == 1, f"decode recompiled: {stats}"


def test_engine_kv_cache_bytes_gauge_halves_with_int8():
    """The capacity-win assertion: int8 storage (values + fp32 scale
    planes) costs about half the bf16 bytes per slot at real head
    widths, reported through the new gauge; the dtype identity gauge
    names what is active."""
    # d=64 so the fp32 scale plane overhead (4/d) stays small, as at
    # the recipe widths (d=96/128)
    cfg, params = _setup("control", n_embd=128)
    sizes = {}
    for kv in ("bf16", "int8"):
        eng = ServingEngine(
            params, cfg,
            ServingConfig(num_slots=4, kv_cache_dtype=kv),
        )
        g = eng.registry.gauge(
            "serving_kv_cache_bytes_per_slot",
            "HBM bytes of pooled KV-cache state per slot "
            "(includes int8 scale planes when quantized).",
        )
        sizes[kv] = g.value
        # gauge agrees with the actual device buffers
        expect = sum(
            leaf.nbytes for layer in eng.cache for leaf in layer.values()
        ) // 4
        assert sizes[kv] == expect
        dt = eng.registry.gauge(
            "serving_kv_cache_dtype",
            "Active KV-cache storage dtype (constant 1; the identity "
            "rides the label).",
            labelnames=("dtype",),
        )
        assert dt.labels(dtype=kv_store_dtype(eng.cfg)).value == 1
    assert sizes["int8"] <= 0.55 * sizes["bf16"], sizes
    assert sizes["int8"] >= 0.5 * sizes["bf16"]  # scales are not free


def test_forward_decode_pool_matches_per_row_positions():
    """Direct pool-path check: rows at DIFFERENT positions produce the
    same logits as separate forward_chunk calls at those positions."""
    from differential_transformer_replication_tpu.models.decode import (
        forward_chunk,
    )

    cfg, params = _setup("control")
    pal = cfg.replace(decode_attention_impl="pallas")
    B = 3
    rng = np.random.default_rng(9)
    # build per-row caches by prefilling different-length prefixes
    lens = [4, 7, 11]
    pool = init_cache(pal, B)
    toks = np.zeros((B,), np.int32)
    for b, L in enumerate(lens):
        ids = rng.integers(0, cfg.vocab_size, size=L + 1)
        row = init_cache(pal, 1)
        _, row = forward_chunk(
            params, jnp.asarray(ids[None, :L], jnp.int32), 0, row, pal
        )
        for pl_, rl in zip(pool, row):
            for key in pl_:
                axis = 1 if key.startswith("k") else 0
                idx = (slice(None), b) if axis else b
                src = rl[key][:, 0] if axis else rl[key][0]
                pl_[key] = pl_[key].at[idx].set(src)
        toks[b] = ids[L]
    pos = jnp.asarray(lens, jnp.int32)
    logits, _ = jax.jit(forward_decode_pool, static_argnums=(4,))(
        params, jnp.asarray(toks), pos, pool, pal
    )
    for b, L in enumerate(lens):
        rng2 = np.random.default_rng(9)  # regenerate the same ids
        ids = [rng2.integers(0, cfg.vocab_size, size=l + 1)
               for l in lens][b]
        row = init_cache(pal, 1)
        _, row = forward_chunk(
            params, jnp.asarray(ids[None, :L], jnp.int32), 0, row, pal
        )
        ref, _ = forward_chunk(
            params, jnp.asarray([[ids[L]]], jnp.int32), L, row, pal
        )
        np.testing.assert_allclose(
            np.asarray(logits[b]), np.asarray(ref[0, -1]),
            atol=1e-5, rtol=1e-5,
        )


# ---------------------------------------------------------------------------
# int8 weight quantization (load_params_for_inference satellite)
# ---------------------------------------------------------------------------


def test_quantize_params_roundtrip_and_selectivity():
    cfg, params = _setup("diff", n_embd=64)
    q = quantize_params_int8(params)
    # matmul weights changed but stay within half a scale step per
    # output channel; everything else is untouched
    blk = params["blocks"][0]["attn"]
    qblk = q["blocks"][0]["attn"]
    for key in ("wq", "wk", "wv"):
        w, wq = np.asarray(blk[key]), np.asarray(qblk[key])
        assert not np.array_equal(w, wq)
        amax = np.max(np.abs(w), axis=-3, keepdims=True)
        assert np.all(np.abs(w - wq) <= amax / 127.0 * 0.5 + 1e-7)
    w, wq = (np.asarray(params["lm_head"]["w"]),
             np.asarray(q["lm_head"]["w"]))
    amax = np.max(np.abs(w), axis=0, keepdims=True)
    assert np.all(np.abs(w - wq) <= amax / 127.0 * 0.5 + 1e-7)
    np.testing.assert_array_equal(
        np.asarray(params["tok_emb"]), np.asarray(q["tok_emb"])
    )
    np.testing.assert_array_equal(
        np.asarray(params["blocks"][0]["ln1"]["w"]),
        np.asarray(q["blocks"][0]["ln1"]["w"]),
    )
    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]["b"]), np.asarray(q["lm_head"]["b"])
    )


def test_quantized_weights_greedy_tolerance():
    """The --quantize-weights accuracy gate: per-channel int8 weights
    keep greedy decoding near-identical on a small model."""
    cfg, params = _setup("control", n_embd=128)
    q = quantize_params_int8(params)
    prompt = _prompts([9], cfg.vocab_size)[0]
    a = _greedy(params, cfg, prompt, 32)
    b = _greedy(q, cfg, prompt, 32)
    agree = np.mean([x == y for x, y in zip(a, b)])
    assert agree >= 0.9, f"int8 weights drifted too far: {agree}"


def test_load_params_for_inference_quantize_wiring(tmp_path):
    from differential_transformer_replication_tpu.config import TrainConfig
    from differential_transformer_replication_tpu.train.checkpoint import (
        load_params_for_inference,
        save_checkpoint,
    )
    from differential_transformer_replication_tpu.train.step import (
        create_train_state,
    )

    tcfg = TrainConfig(
        model=_cfg("control", vocab_size=31),
        vocab_size=31, control_head_multiplier=1,
    )
    state = create_train_state(jax.random.PRNGKey(0), tcfg)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state, 1.0, tcfg)
    plain, _, _ = load_params_for_inference(path)
    quant, _, _ = load_params_for_inference(path, quantize="int8")
    assert not np.array_equal(
        np.asarray(plain["lm_head"]["w"]), np.asarray(quant["lm_head"]["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(plain["tok_emb"]), np.asarray(quant["tok_emb"])
    )
    with pytest.raises(ValueError, match="quantization"):
        load_params_for_inference(path, quantize="fp4")


# ---------------------------------------------------------------------------
# config validation + CLI gates
# ---------------------------------------------------------------------------


def test_config_validation():
    with pytest.raises(ValueError, match="decode_attention_impl"):
        _cfg("control", decode_attention_impl="triton")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        _cfg("control", kv_cache_dtype="fp8")
    with pytest.raises(ValueError, match="decode_attention_impl"):
        ServingConfig(decode_attention_impl="triton")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ServingConfig(kv_cache_dtype="fp8")


def test_decode_attn_sweep_smoke():
    """The sweep's --smoke is the tier-1 parity gate for the kernel at
    tiny interpret-mode shapes (one JSON line per case)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "decode_attn_sweep.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines()
             if ln.startswith("{")]
    assert len(lines) == 12  # 3 families x 2 dtypes x 2 impls
    assert {ln["impl"] for ln in lines} == {"pallas", "xla"}
    assert all(ln["max_abs_diff"] < 1e-5 for ln in lines)


def test_serve_bench_smoke_fused_int8():
    """serve_bench --smoke with the fused kernel + int8 cache selected:
    completes failure-free, reports the impl/dtype in its JSON line, and
    keeps the measured window recompile-free."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "serve_bench.py"),
         "--smoke", "--decode-attention-impl", "pallas",
         "--kv-cache-dtype", "int8"],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = json.loads(out.stdout.splitlines()[0])
    assert line["decode_attention_impl"] == "pallas"
    assert line["kv_cache_dtype"] == "int8"
    assert line["failed"] == 0
    assert line["compiles_in_window"] == 0
