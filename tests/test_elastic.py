"""Elastic mesh-shape resume tests (train/checkpoint.py +
train/trainer.py): checkpoints are host-canonical, so a resume onto a
*different* mesh shape — the normal outcome of a preemption returning
fewer devices — must reshard exactly (optimizer state included), the
epoch-sampler fast-forward must come from the checkpoint's recorded
consumed-window count (exact across batch-size changes), and every
impossible case must be a typed ElasticResumeError, not a deep flax
shape traceback. Runs on the conftest-forced 8-device CPU mesh.
"""

import json
import os

import jax
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.train import (
    ElasticResumeError,
    elastic_resume_info,
    train,
)

TINY_MODEL = dict(vocab_size=256, n_embd=32, n_head=2, n_layer=2,
                  block_size=16, dropout=0.0, compute_dtype="float32")


def tiny_cfg(tmp_path, name, mesh=None, **kw):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    defaults = dict(
        vocab_size=256, dataset="synthetic", num_train_samples=200,
        micro_batch_size=8, grad_acc_steps=1, max_iters=6,
        eval_interval=100, eval_iters=2, log_interval=5,
        learning_rate=3e-3, min_lr=3e-4, warmup_iters=5,
        control_head_multiplier=1,
        tokenizer_dir=str(tmp_path / "tokenizer"),
        checkpoint_path=str(d / "best"),
        last_checkpoint_path=str(d / "last"),
        metrics_path=str(d / "metrics.jsonl"),
        seed=7,
    )
    model_kw = kw.pop("model_kw", {})
    return TrainConfig(
        model=ModelConfig(model="diff", **{**TINY_MODEL, **model_kw}),
        mesh=mesh or MeshConfig(),
        **{**defaults, **kw},
    )


def _state_bytes(cfg):
    return open(
        os.path.join(cfg.resolved_last_checkpoint_path(),
                     "state.msgpack"), "rb",
    ).read()


def _losses(cfg):
    return [
        json.loads(l)["loss"] for l in open(cfg.metrics_path)
        if '"loss"' in l
    ]


@pytest.fixture(scope="module")
def dp8_checkpoint(tmp_path_factory):
    """One dp=8 seed segment shared by the resume tests: 6 iters, a
    host-canonical rescue checkpoint at the end."""
    tmp = tmp_path_factory.mktemp("elastic_seed")
    cfg = tiny_cfg(tmp, "seed8", mesh=MeshConfig(data=8))
    train(cfg)
    return tmp, cfg


class TestElasticResumeInfo:
    def _meta(self, cfg, iter_num=6, consumed=None):
        meta = {"iter_num": iter_num, "config": cfg.to_dict()}
        if consumed is not None:
            meta["consumed_windows"] = consumed
        return meta

    def test_same_config_is_not_elastic(self, tmp_path):
        cfg = tiny_cfg(tmp_path, "a")
        info = elastic_resume_info(self._meta(cfg, consumed=48), cfg)
        assert info == {
            "elastic": False, "batch_changed": False, "exact": True,
            "saved_mesh": {"pipeline": 1, "data": 1, "fsdp": 1,
                           "tensor": 1, "sequence": 1},
            "consumed_windows": 48,
        }

    def test_mesh_change_flagged_elastic_and_allowed(self, tmp_path):
        saved = tiny_cfg(tmp_path, "b", mesh=MeshConfig(data=8))
        new = tiny_cfg(tmp_path, "b2", mesh=MeshConfig(fsdp=4))
        info = elastic_resume_info(self._meta(saved, consumed=48), new)
        assert info["elastic"] and info["exact"]
        assert info["saved_mesh"]["data"] == 8

    def test_shape_mismatch_is_typed_error(self, tmp_path):
        saved = tiny_cfg(tmp_path, "c")
        for field, val in (("n_embd", 64), ("n_layer", 4),
                           ("block_size", 32)):
            new = tiny_cfg(tmp_path, f"c_{field}",
                           model_kw={field: val})
            with pytest.raises(ElasticResumeError, match=field):
                elastic_resume_info(self._meta(saved), new)

    def test_vocab_mismatch_is_typed_error(self, tmp_path):
        saved = tiny_cfg(tmp_path, "d")
        new = tiny_cfg(tmp_path, "d2", vocab_size=512,
                       model_kw={"vocab_size": 512})
        with pytest.raises(ElasticResumeError, match="vocab_size"):
            elastic_resume_info(self._meta(saved), new)

    def test_batch_change_exact_from_consumed_windows(self, tmp_path):
        """grad_acc x micro changed 8 -> 16: the recorded 48 consumed
        windows divide the new global batch, so the permutation
        position is exact — 3 new-size steps in, not 6."""
        saved = tiny_cfg(tmp_path, "e", micro_batch_size=8)
        new = tiny_cfg(tmp_path, "e2", micro_batch_size=16)
        info = elastic_resume_info(self._meta(saved, consumed=48), new)
        assert info["batch_changed"] and info["exact"]
        assert info["consumed_windows"] == 48

    def test_legacy_meta_derives_consumed_from_saved_batch_math(
        self, tmp_path
    ):
        """Pre-consumed_windows checkpoints still resume exactly under
        a changed batch: the SAVING run's batch math is in its config."""
        saved = tiny_cfg(tmp_path, "f", micro_batch_size=8)
        new = tiny_cfg(tmp_path, "f2", micro_batch_size=16)
        info = elastic_resume_info(self._meta(saved, iter_num=6), new)
        assert info["consumed_windows"] == 48  # 6 iters x 8 windows

    def test_mid_accumulation_boundary_is_typed_error(self, tmp_path):
        """48 consumed windows under a new global batch of 5: the data
        position lands mid-accumulation — exactness is impossible."""
        saved = tiny_cfg(tmp_path, "g", micro_batch_size=8)
        new = tiny_cfg(tmp_path, "g2", micro_batch_size=5)
        with pytest.raises(ElasticResumeError, match="mid-accumulation"):
            elastic_resume_info(self._meta(saved, consumed=48), new)

    def test_allow_inexact_resume_escape_hatch(self, tmp_path):
        saved = tiny_cfg(tmp_path, "h", micro_batch_size=8)
        new = tiny_cfg(tmp_path, "h2", micro_batch_size=5,
                       allow_inexact_resume=True)
        info = elastic_resume_info(self._meta(saved, consumed=48), new)
        assert not info["exact"]
        assert info["consumed_windows"] == 48

    def test_meta_without_batch_math_degrades_to_current_math(
        self, tmp_path
    ):
        """A meta recording neither consumed_windows nor its batch math
        cannot even DETECT a batch change — it degrades to the
        pre-elastic behavior (derive position with the current math),
        which is correct for every checkpoint this repo ever wrote
        (cfg.to_dict() always records the batch fields)."""
        saved = tiny_cfg(tmp_path, "i", micro_batch_size=8)
        meta = self._meta(saved)
        meta["config"].pop("grad_acc_steps")
        meta["config"].pop("micro_batch_size")
        new = tiny_cfg(tmp_path, "i2", micro_batch_size=16)
        info = elastic_resume_info(meta, new)
        assert not info["batch_changed"] and info["exact"]
        assert info["consumed_windows"] is None


class TestElasticResumeEndToEnd:
    """dp 8 -> {4, 1} and dp -> fsdp resumes of one shared dp=8
    checkpoint on the forced-8-device CPU mesh. Same-mesh resumed runs
    are bit-identical (resharding is deterministic); cross-width runs
    agree to float tolerance (the gradient psum's reduction order
    legitimately differs with the shard count — 'bit-equal where batch
    math allows')."""

    def _resume(self, tmp, base_cfg, name, mesh, **kw):
        cfg = tiny_cfg(
            tmp, name, mesh=mesh, max_iters=12,
            resume_from=base_cfg.resolved_last_checkpoint_path(), **kw,
        )
        state = train(cfg)
        return cfg, state

    def test_dp8_to_dp4_reshards_and_is_deterministic(
        self, dp8_checkpoint, capsys
    ):
        tmp, seed_cfg = dp8_checkpoint
        cfg_a, state_a = self._resume(tmp, seed_cfg, "dp4_a",
                                      MeshConfig(data=4))
        out = capsys.readouterr().out
        assert "[elastic] resuming" in out and "exact" in out
        assert int(jax.device_get(state_a["step"])) == 12
        cfg_b, _ = self._resume(tmp, seed_cfg, "dp4_b", MeshConfig(data=4))
        # resharding 8->4 is lossless and deterministic: two elastic
        # resumes of the same checkpoint are byte-identical, optimizer
        # moments included (the state.msgpack carries them)
        assert _state_bytes(cfg_a) == _state_bytes(cfg_b)
        # and the final checkpoint records the exact consumed count
        meta = json.load(open(os.path.join(
            cfg_a.resolved_last_checkpoint_path(), "meta.json")))
        assert meta["consumed_windows"] == 12 * 8

    def test_dp8_to_single_device_and_fsdp_agree(self, dp8_checkpoint):
        tmp, seed_cfg = dp8_checkpoint
        cfg_dp4, _ = self._resume(tmp, seed_cfg, "x_dp4",
                                  MeshConfig(data=4))
        cfg_dp1, _ = self._resume(tmp, seed_cfg, "x_dp1", MeshConfig())
        cfg_fsdp, _ = self._resume(tmp, seed_cfg, "x_fsdp",
                                   MeshConfig(fsdp=4))
        # identical loss TRAJECTORIES to float tolerance across dp 4 /
        # dp 1 / fsdp 4 — same data order (consumed-window
        # fast-forward), same batch math, different reduction orders
        la, lb, lc = (_losses(c) for c in (cfg_dp4, cfg_dp1, cfg_fsdp))
        np.testing.assert_allclose(la, lb, rtol=1e-5)
        np.testing.assert_allclose(la, lc, rtol=1e-5)

    def test_batch_size_change_resumes_exactly(self, dp8_checkpoint):
        """Global batch 8 -> 16 across the resume: runs, and the final
        checkpoint's consumed count advances under the NEW batch math
        from the checkpoint's recorded base (48 + 6 x 16), proving the
        sampler anchor came from consumed windows, not step count."""
        tmp, seed_cfg = dp8_checkpoint
        cfg, state = self._resume(tmp, seed_cfg, "bigger_batch",
                                  MeshConfig(data=4),
                                  micro_batch_size=16)
        assert int(jax.device_get(state["step"])) == 12
        meta = json.load(open(os.path.join(
            cfg.resolved_last_checkpoint_path(), "meta.json")))
        assert meta["consumed_windows"] == 48 + 6 * 16

    def test_mid_accumulation_resume_raises_in_trainer(
        self, dp8_checkpoint
    ):
        """The typed error surfaces from train() itself (before any
        device work), and --allow-inexact-resume lets the same config
        through."""
        tmp, seed_cfg = dp8_checkpoint
        cfg = tiny_cfg(
            tmp, "inexact", mesh=MeshConfig(data=4), max_iters=8,
            micro_batch_size=20, grad_acc_steps=1,
            resume_from=seed_cfg.resolved_last_checkpoint_path(),
        )
        with pytest.raises(ElasticResumeError, match="mid-accumulation"):
            train(cfg)
        state = train(cfg.replace(allow_inexact_resume=True))
        assert int(jax.device_get(state["step"])) == 8

    def test_shape_mismatch_raises_before_flax_error(
        self, dp8_checkpoint
    ):
        tmp, seed_cfg = dp8_checkpoint
        cfg = tiny_cfg(
            tmp, "misshape", mesh=MeshConfig(data=4),
            model_kw={"n_embd": 64},
            resume_from=seed_cfg.resolved_last_checkpoint_path(),
        )
        with pytest.raises(ElasticResumeError, match="n_embd"):
            train(cfg)
