"""Throughput counter and profiler-window tests (SURVEY.md section 5.1)."""

import time

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.utils import (
    ProfilerWindow,
    Throughput,
    trace,
)


def test_throughput_first_call_is_none():
    t = Throughput()
    assert t.update(100) is None


def test_throughput_rate():
    t = Throughput()
    t.update(0)
    time.sleep(0.05)
    rate = t.update(500)
    assert rate is not None and 1000 < rate < 11000  # ~10k tok/s nominal


def test_trace_context_manager_captures(tmp_path):
    d = str(tmp_path / "trace")
    with trace(d):
        _ = jnp.sum(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    assert (tmp_path / "trace").exists()


def test_profiler_window_disabled_is_noop():
    w = ProfilerWindow(None, start=10)
    for i in range(20):
        w.step(i)
    w.close()
    assert not w.active


def test_profiler_window_normal_capture(tmp_path):
    d = str(tmp_path / "p1")
    w = ProfilerWindow(d, start=2, n_steps=2)
    x = jnp.ones((8, 8))
    for i in range(1, 6):
        x = x + 1
        w.step(i, sync=x)
    assert not w.active  # stopped at start+n_steps
    assert (tmp_path / "p1").exists()
    w.close()  # idempotent


def test_profiler_window_resume_past_start_never_stops_unstarted():
    """Resuming at an iteration inside/past the window must not call
    stop_trace without a matching start."""
    w = ProfilerWindow("/tmp/never-used-profile-dir", start=10, n_steps=5)
    for i in range(12, 20):  # resumed past start
        w.step(i)
    w.close()
    assert not w.active


def test_profiler_window_early_exit_finalizes(tmp_path):
    d = str(tmp_path / "p2")
    w = ProfilerWindow(d, start=1, n_steps=100)
    w.step(1)
    assert w.active
    w.close(sync=jnp.ones(()))  # loop ended inside the window
    assert not w.active
    assert (tmp_path / "p2").exists()
