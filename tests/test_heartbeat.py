"""Multi-host liveness mesh tests (parallel/heartbeat.py): the monitor
state machine with fake peers and a fake clock, the shared-filesystem
transport's torn-file tolerance, the heartbeat_silence fault point, and
the coordinated-abort wiring into the watchdog.
"""

import json
import os
import threading
import time

import pytest

from differential_transformer_replication_tpu.parallel.heartbeat import (
    FileHeartbeatTransport,
    Heartbeat,
    MemoryTransport,
)
from differential_transformer_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class _Gauge:
    def __init__(self):
        self.values = {}

    def set(self, value, **labels):
        self.values[labels["peer"]] = value


def _mesh(n=3, index=0, interval=1.0, timeout=5.0, **kw):
    """A Heartbeat with NO running threads (start=False): tests drive
    publish_once / check_peers synchronously against a fake clock."""
    transport = kw.pop("transport", MemoryTransport())
    clock = kw.pop("clock", FakeClock())
    dead = []
    hb = Heartbeat(
        transport, process_index=index, num_processes=n,
        interval_s=interval, timeout_s=timeout,
        iter_supplier=kw.pop("iter_supplier", lambda: 7),
        on_dead=lambda p, age: dead.append((p, age)),
        clock=clock, start=False, **kw,
    )
    return hb, transport, clock, dead


def _beat(transport, peer, seq, iter_num=0):
    transport.publish({"process_index": peer, "iter": iter_num,
                       "seq": seq, "ts": 0.0})


class TestMonitor:
    def test_beating_peers_stay_alive(self):
        hb, tr, clock, dead = _mesh(n=3, index=0, timeout=5.0)
        for t in range(20):
            clock.t = float(t)
            _beat(tr, 1, seq=t)
            _beat(tr, 2, seq=t)
            hb.check_peers()
        assert dead == []
        assert max(hb.peer_ages().values()) <= 1.0

    def test_silent_peer_fires_on_dead_once(self):
        hb, tr, clock, dead = _mesh(n=3, index=0, timeout=5.0)
        for t in range(3):
            clock.t = float(t)
            _beat(tr, 1, seq=t)
            _beat(tr, 2, seq=t)
            hb.check_peers()
        # peer 2 goes silent (its record stays frozen at seq=2)
        for t in range(3, 12):
            clock.t = float(t)
            _beat(tr, 1, seq=t)
            hb.check_peers()
        assert len(dead) == 1
        peer, age = dead[0]
        assert peer == 2 and age > 5.0
        # peer 1 never flagged; the dead peer is not re-reported
        clock.t = 20.0
        _beat(tr, 1, seq=20)
        hb.check_peers()
        assert len(dead) == 1

    def test_grace_from_start_not_from_epoch(self):
        """A peer that has never published gets a full timeout of grace
        from monitor START — a slow bring-up (compiling) must not be an
        instant death sentence."""
        hb, tr, clock, dead = _mesh(n=2, index=0, timeout=5.0)
        clock.t = 4.0
        hb.check_peers()
        assert dead == []
        clock.t = 6.0
        hb.check_peers()
        assert [p for p, _ in dead] == [1]

    def test_staleness_judged_by_local_clock_not_record_ts(self):
        """Clock-skew immunity: a peer whose embedded wall-clock ts is
        absurdly old is still alive as long as its record keeps
        CHANGING."""
        hb, tr, clock, dead = _mesh(n=2, index=0, timeout=3.0)
        for t in range(10):
            clock.t = float(t)
            tr.publish({"process_index": 1, "iter": t, "seq": t,
                        "ts": -1e9})  # skewed wall clock
            hb.check_peers()
        assert dead == []

    def test_age_gauge_exported_per_peer(self):
        gauge = _Gauge()
        hb, tr, clock, dead = _mesh(n=3, index=1, timeout=10.0,
                                    age_gauge=gauge)
        clock.t = 1.0
        _beat(tr, 0, seq=1)
        hb.check_peers()
        clock.t = 4.0
        hb.check_peers()
        assert gauge.values["0"] == pytest.approx(3.0)
        assert gauge.values["2"] == pytest.approx(4.0)  # never seen

    def test_timeout_must_exceed_interval(self):
        with pytest.raises(ValueError, match="must exceed"):
            Heartbeat(MemoryTransport(), 0, 2, interval_s=2.0,
                      timeout_s=1.0, iter_supplier=lambda: 0,
                      start=False)


class TestPublisher:
    def test_publish_carries_iter_and_monotonic_seq(self):
        it = {"i": 3}
        hb, tr, clock, _ = _mesh(n=1, index=0,
                                 iter_supplier=lambda: it["i"])
        hb.publish_once()
        it["i"] = 9
        hb.publish_once()
        rec = tr.read()[0]
        assert rec["iter"] == 9 and rec["seq"] == 2

    def test_heartbeat_silence_fault_mutes_this_process(self):
        faults.arm("heartbeat_silence@1")
        hb0, tr, _, _ = _mesh(n=2, index=0, transport=MemoryTransport())
        hb1, _, _, _ = _mesh(n=2, index=1, transport=tr)
        hb0.publish_once()
        hb1.publish_once()
        assert 0 in tr.read()
        assert 1 not in tr.read()  # muted — and stays muted
        hb1.publish_once()
        assert 1 not in tr.read()

    def test_silenced_peer_detected_dead_by_the_others(self):
        """End-to-end through the fault point: process 1 publishes,
        then goes silent (heartbeat_silence); process 0's monitor sees
        its age grow past the timeout and fires on_dead — the
        coordinated-abort trigger."""
        tr = MemoryTransport()
        clock = FakeClock()
        hb0, _, _, dead = _mesh(n=2, index=0, transport=tr, clock=clock,
                                timeout=3.0)
        hb1, _, _, _ = _mesh(n=2, index=1, transport=tr, clock=clock)
        hb1.publish_once()
        clock.t = 1.0
        hb0.check_peers()
        assert dead == []
        faults.arm("heartbeat_silence@1")
        for t in range(2, 8):
            clock.t = float(t)
            hb1.publish_once()  # muted: the record never changes
            hb0.check_peers()
        assert [p for p, _ in dead] == [1]


class TestFileTransport:
    def test_roundtrip_and_overwrite(self, tmp_path):
        tr = FileHeartbeatTransport(str(tmp_path / "hb"))
        tr.publish({"process_index": 0, "iter": 1, "seq": 1, "ts": 0.0})
        tr.publish({"process_index": 3, "iter": 5, "seq": 9, "ts": 0.0})
        tr.publish({"process_index": 0, "iter": 2, "seq": 2, "ts": 0.0})
        recs = tr.read()
        assert recs[0]["seq"] == 2 and recs[3]["seq"] == 9
        assert sorted(os.listdir(tmp_path / "hb")) == [
            "hb-0.json", "hb-3.json"
        ]

    def test_torn_and_foreign_files_skipped(self, tmp_path):
        d = tmp_path / "hb"
        tr = FileHeartbeatTransport(str(d))
        tr.publish({"process_index": 1, "iter": 1, "seq": 1, "ts": 0.0})
        (d / "hb-2.json").write_text('{"process_index": 2, "se')  # torn
        (d / "hb-x.json").write_text("not json at all")
        (d / "notes.txt").write_text("unrelated")
        recs = tr.read()
        assert list(recs) == [1]

    def test_missing_directory_reads_empty(self, tmp_path):
        tr = FileHeartbeatTransport(str(tmp_path / "hb"))
        os.rmdir(tmp_path / "hb")
        assert tr.read() == {}


def test_threaded_end_to_end_silent_peer_trips_watchdog(tmp_path):
    """Real threads, real clock, file transport: two heartbeat meshes
    share a directory; one process dies (its publisher stops) and the
    survivor's monitor trips the injected watchdog within the timeout.
    Small intervals keep this well under a second of steady state."""
    from differential_transformer_replication_tpu.train.watchdog import (
        StepWatchdog,
    )

    d = str(tmp_path / "hb")
    tripped = threading.Event()
    exits = []

    def exit_fn(code):
        exits.append(code)
        tripped.set()

    wd = StepWatchdog(0.0, report_path=str(tmp_path / "hang.json"),
                      exit_fn=exit_fn)
    survivor = Heartbeat(
        FileHeartbeatTransport(d), process_index=0, num_processes=2,
        interval_s=0.05, timeout_s=0.4,
        iter_supplier=lambda: 1,
        on_dead=lambda p, age: wd.trip(
            f"peer process {p} heartbeat silent for {age:.1f}s"
        ),
    )
    dying = Heartbeat(
        FileHeartbeatTransport(d), process_index=1, num_processes=2,
        interval_s=0.05, timeout_s=0.4, iter_supplier=lambda: 1,
    )
    try:
        time.sleep(0.2)
        assert not tripped.is_set()  # both beating: no false positive
        dying.close()  # the "process" dies; its file freezes
        assert tripped.wait(timeout=5.0)
        report = json.load(open(tmp_path / "hang.json"))
        assert "peer process 1" in report["reason"]
    finally:
        survivor.close()
        dying.close()
        wd.close()
