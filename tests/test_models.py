"""Model-level tests: shapes, init statistics, parity quirks, causality,
loss sanity, generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import (
    generate,
    init_model,
    model_forward,
    param_count,
)

TINY = dict(vocab_size=97, n_embd=32, n_head=2, n_layer=2, block_size=16,
            dropout=0.0, compute_dtype="float32")


def tiny_cfg(model, **kw):
    return ModelConfig(model=model, **{**TINY, **kw})


@pytest.fixture(params=["control", "diff", "ndiff"])
def model_kind(request):
    return request.param


class TestInitAndShapes:
    def test_forward_shapes_and_loss(self, model_kind):
        cfg = tiny_cfg(model_kind)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (3, 10), 0, cfg.vocab_size)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (3, 10), 0, cfg.vocab_size)
        logits, loss = model_forward(params, idx, cfg, targets=tgt)
        assert logits.shape == (3, 10, cfg.vocab_size)
        assert loss.shape == ()
        assert np.isfinite(float(loss))
        # random init, uniform-ish prediction: loss near log(V)
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0

    def test_no_targets_no_loss(self, model_kind):
        cfg = tiny_cfg(model_kind)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jnp.zeros((1, 5), jnp.int32)
        logits, loss = model_forward(params, idx, cfg)
        assert loss is None

    def test_init_statistics(self, model_kind):
        """All projection weights ~ N(0, 0.02) (control.py:132-138); biases,
        lambda params zero; norm weights one."""
        cfg = tiny_cfg(model_kind, n_embd=64, n_layer=4)
        params = init_model(jax.random.PRNGKey(0), cfg)
        w = np.asarray(params["blocks"][0]["attn"]["wq"]).ravel()
        assert abs(w.std() - 0.02) < 0.005
        assert abs(w.mean()) < 0.01
        np.testing.assert_array_equal(np.asarray(params["blocks"][0]["ffn"]["out"]["b"]), 0.0)
        np.testing.assert_array_equal(np.asarray(params["blocks"][0]["ln1"]["w"]), 1.0)
        if model_kind in ("diff", "ndiff"):
            np.testing.assert_array_equal(np.asarray(params["blocks"][0]["attn"]["lambda_q"]), 0.0)

    def test_head_sizing(self):
        """control: E/H; diff/ndiff: E/(2H) with doubled value
        (control.py:96, diff_transformer.py:111)."""
        c = tiny_cfg("control", n_embd=64, n_head=4)
        d = tiny_cfg("diff", n_embd=64, n_head=4)
        assert c.head_size == 16 and c.value_size == 16
        assert d.head_size == 8 and d.value_size == 16
        pc = init_model(jax.random.PRNGKey(0), c)
        pd = init_model(jax.random.PRNGKey(0), d)
        assert pc["blocks"][0]["attn"]["wq"].shape == (64, 4, 16)
        assert pd["blocks"][0]["attn"]["wq"].shape == (2, 64, 4, 8)
        assert pd["blocks"][0]["attn"]["wv"].shape == (64, 4, 16)

    def test_only_diff_has_position_table(self):
        """diff has a learned position table (diff_transformer.py:134);
        control/ndiff rely on RoPE (control.py:118-119, Ndiff:188)."""
        assert "pos_emb" in init_model(jax.random.PRNGKey(0), tiny_cfg("diff"))
        assert "pos_emb" not in init_model(jax.random.PRNGKey(0), tiny_cfg("control"))
        assert "pos_emb" not in init_model(jax.random.PRNGKey(0), tiny_cfg("ndiff"))

    def test_param_count_rough_parity(self):
        """Control with doubled heads should roughly param-match diff
        (train.py:226's stated intent)."""
        c = tiny_cfg("control", n_embd=64, n_head=4)  # doubled from 2
        d = tiny_cfg("diff", n_embd=64, n_head=2)
        nc = param_count(init_model(jax.random.PRNGKey(0), c))
        nd = param_count(init_model(jax.random.PRNGKey(0), d))
        assert abs(nc - nd) / nd < 0.15

    def test_ndiff_term_stacking(self):
        cfg = tiny_cfg("ndiff", n_terms=3)
        p = init_model(jax.random.PRNGKey(0), cfg)
        assert p["blocks"][0]["attn"]["wq"].shape[0] == 3
        assert p["blocks"][0]["attn"]["lambda_q"].shape[0] == 3


class TestBehavior:
    def test_causality(self, model_kind):
        """Future-token edits must not change past logits."""
        cfg = tiny_cfg(model_kind)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
        logits1, _ = model_forward(params, idx, cfg)
        idx2 = idx.at[:, -1].set((idx[:, -1] + 1) % cfg.vocab_size)
        logits2, _ = model_forward(params, idx2, cfg)
        np.testing.assert_allclose(
            np.asarray(logits1[:, :-1]), np.asarray(logits2[:, :-1]), rtol=1e-4, atol=1e-5
        )

    def test_diff_at_zero_lambda_params_uses_schedule(self):
        """At zero-init lambda params, per-head lambda == lambda_init(layer)
        exactly; perturbing lambda_q must change the output (the lambda path
        is live)."""
        cfg = tiny_cfg("diff")
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jnp.arange(10)[None] % cfg.vocab_size
        base, _ = model_forward(params, idx, cfg)
        # Perturb only stream 1: perturbing both streams identically would
        # cancel in exp(lq1*lk1) - exp(lq2*lk2).
        params["blocks"][0]["attn"]["lambda_q"] = (
            params["blocks"][0]["attn"]["lambda_q"].at[0].add(0.5)
        )
        params["blocks"][0]["attn"]["lambda_k"] = (
            params["blocks"][0]["attn"]["lambda_k"].at[0].add(0.5)
        )
        pert, _ = model_forward(params, idx, cfg)
        assert not np.allclose(np.asarray(base), np.asarray(pert), atol=1e-5)

    def test_jit_forward(self, model_kind):
        cfg = tiny_cfg(model_kind)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jnp.zeros((2, 8), jnp.int32)
        tgt = jnp.ones((2, 8), jnp.int32)

        @jax.jit
        def f(p, i, t):
            return model_forward(p, i, cfg, targets=t)[1]

        loss = f(params, idx, tgt)
        assert np.isfinite(float(loss))

    def test_dropout_changes_output_train_only(self):
        cfg = tiny_cfg("diff", dropout=0.3)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jnp.arange(8)[None]
        det, _ = model_forward(params, idx, cfg)  # no rng -> deterministic
        det2, _ = model_forward(params, idx, cfg)
        np.testing.assert_array_equal(np.asarray(det), np.asarray(det2))
        drop, _ = model_forward(params, idx, cfg, rng=jax.random.PRNGKey(7))
        assert not np.allclose(np.asarray(det), np.asarray(drop), atol=1e-6)


class TestGenerate:
    def test_shapes_and_range(self, model_kind):
        cfg = tiny_cfg(model_kind)
        params = init_model(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        out = generate(params, prompt, cfg, 5, jax.random.PRNGKey(3))
        assert out.shape == (1, 8)
        np.testing.assert_array_equal(np.asarray(out[:, :3]), np.asarray(prompt))
        assert (np.asarray(out) >= 0).all() and (np.asarray(out) < cfg.vocab_size).all()

    def test_empty_prompt_rejected(self):
        cfg = tiny_cfg("control")
        params = init_model(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="prompt length"):
            generate(params, jnp.zeros((1, 0), jnp.int32), cfg, 2, jax.random.PRNGKey(0))

    def test_window_overflow(self):
        """Generation past block_size exercises the sliding-window path
        (the reference's idx[:, -block_size:] crop)."""
        cfg = tiny_cfg("control", block_size=8)
        params = init_model(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[1, 2, 3, 4, 5, 6]], jnp.int32)
        out = generate(params, prompt, cfg, 10, jax.random.PRNGKey(4))
        assert out.shape == (1, 16)
