"""Continuous-batching serving engine (serving/).

The load-bearing contract: batched continuous-batching output is
BIT-IDENTICAL to sequential ``generate_cached`` greedy decoding for all
three families on mixed-length prompt sets — the engine is a scheduler
over the same math, never a different model. Plus: slot reuse after
retirement, per-request seed determinism (independent of batch
composition), EOS retirement, scheduler budget/pool invariants, and
jit-stability (no recompilation as requests come and go).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    QueueFullError,
    SamplingParams,
    Scheduler,
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    FREE,
    PREFILL,
)


def _cfg(kind, vocab=61):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind, vocab=61):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# one family stays in the quick tier as the representative parity pin;
# the other two ride the full tier (conftest honors explicit slow marks)
@pytest.mark.parametrize("kind", [
    "control",
    pytest.param("diff", marks=pytest.mark.slow),
    pytest.param("ndiff", marks=pytest.mark.slow),
])
def test_batched_greedy_bit_identical_to_generate_cached(kind):
    """Acceptance pin: mixed-length prompts through a 2-slot pool (so
    requests queue and slots are reused) produce exactly the tokens
    sequential per-request generate_cached produces."""
    cfg, params = _setup(kind)
    prompts = _prompts([3, 9, 14, 6, 11], cfg.vocab_size)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=4, prefill_budget=6),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _ref_greedy(params, cfg, p, 8)
        assert o.prompt == p  # all in-window: no crop
        assert o.finish_reason == "length"
    # slot reuse + pool invariant: 5 requests through 2 slots
    assert eng.stats["completed"] == 5
    assert eng.scheduler.max_concurrent <= 2
    assert all(s.state == FREE for s in eng.scheduler.slots)


@pytest.mark.slow
def test_long_prompt_crop_and_rolling_decode_parity():
    """RoPE families crop prompts > block_size to the last block_size ids
    (the reference's own semantics, control.py:165) and roll the ring
    cache past block_size during decode — both bit-matching
    generate_cached."""
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg,
        ServingConfig(
            num_slots=3, prefill_chunk=8, prefill_budget=16, max_seq_len=64,
        ),
    )
    long_p, short_p = _prompts([40, 5], cfg.vocab_size, seed=2)
    outs = eng.generate([long_p, short_p], max_new_tokens=10, temperature=0.0)
    assert outs[0].tokens == _ref_greedy(params, cfg, long_p, 10)
    assert outs[0].prompt == long_p[-cfg.block_size:]  # cropped echo
    assert outs[1].tokens == _ref_greedy(params, cfg, short_p, 10)


@pytest.mark.slow
def test_per_request_seed_determinism_across_batch_compositions():
    """Sampled output is a function of (params, prompt, sampling params)
    only — the key chain fold_in(PRNGKey(seed), t) must not see slot
    assignment, pool size, or admission order."""
    cfg, params = _setup("control")
    reqs = list(zip(_prompts([4, 9, 6], cfg.vocab_size, seed=3), [7, 7, 99]))

    def run(num_slots, order):
        eng = ServingEngine(
            params, cfg,
            ServingConfig(num_slots=num_slots, prefill_chunk=4,
                          prefill_budget=4),
        )
        ids = {}
        for i in order:
            p, seed = reqs[i]
            ids[eng.submit(p, temperature=1.0, top_k=5, seed=seed,
                           max_new_tokens=6)] = i
        return {ids[o.request_id]: o.tokens for o in eng.run()}

    a = run(1, [0, 1, 2])
    b = run(3, [2, 0, 1])
    assert a == b
    assert all(len(t) == 6 for t in a.values())
    # and every draw is a valid token id
    assert all(0 <= tok < cfg.vocab_size for t in a.values() for tok in t)


@pytest.mark.slow
def test_sampled_chain_matches_sample_token_reference():
    """The engine's batched sampler must be bit-identical, token for
    token, to the single-request sample_token contract with the same
    fold_in key chain (models/generate.py)."""
    from differential_transformer_replication_tpu.models.decode import (
        forward_chunk,
        init_cache,
    )
    from differential_transformer_replication_tpu.models.generate import (
        sample_token,
    )

    cfg, params = _setup("control")
    prompt = _prompts([5], cfg.vocab_size, seed=4)[0]
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    out = eng.generate(
        [prompt], temperature=1.0, top_k=5, seed=11, max_new_tokens=6
    )[0]

    base = jax.random.PRNGKey(11)
    cache = init_cache(cfg, 1)
    logits, cache = forward_chunk(
        params, jnp.asarray(prompt, jnp.int32)[None], 0, cache, cfg,
        rope_len=cfg.block_size,
    )
    toks = []
    for t in range(6):
        key = jax.random.fold_in(base, t)
        tok = int(sample_token(
            key, logits[:, -1].astype(jnp.float32), 1.0, 5
        )[0])
        toks.append(tok)
        if t < 5:
            logits, cache = forward_chunk(
                params, jnp.asarray([[tok]], jnp.int32), len(prompt) + t,
                cache, cfg, rope_len=cfg.block_size,
            )
    assert out.tokens == toks


def test_eos_retires_slot_early_without_stalling_batch():
    cfg, params = _setup("control")
    prompts = _prompts([5, 8], cfg.vocab_size, seed=5)
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    ref = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
    first_tok = ref[0].tokens[0]

    eng2 = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    a = eng2.submit(prompts[0], max_new_tokens=6, temperature=0.0,
                    eos_token_id=first_tok)
    b = eng2.submit(prompts[1], max_new_tokens=6, temperature=0.0)
    outs = {o.request_id: o for o in eng2.run()}
    assert outs[a].tokens == [first_tok]
    assert outs[a].finish_reason == "eos"
    # the other sequence is unaffected by the early retirement
    assert outs[b].tokens == ref[1].tokens
    assert outs[b].finish_reason == "length"


def test_submit_validation():
    cfg, params = _setup("diff")
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=1))
    with pytest.raises(ValueError):  # diff cannot roll past block_size
        eng.submit(list(range(30)), max_new_tokens=10)
    with pytest.raises(ValueError):
        eng.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)

    ccfg, cparams = _setup("control")
    ceng = ServingEngine(cparams, ccfg, ServingConfig(num_slots=1))
    with pytest.raises(ValueError):  # past the engine's RoPE table
        ceng.submit(list(range(30)), max_new_tokens=10)


def test_decode_stays_jit_stable_as_requests_come_and_go():
    """Acceptance pin: a first wave compiles everything (decode step,
    prefill ladder, samplers); a second wave with different lengths,
    seeds, sampling params and admission patterns must not add a single
    cache entry, and the decode step must have compiled exactly once."""
    cfg, params = _setup("control", vocab=53)  # fresh compile-cache key
    serving = ServingConfig(num_slots=3, prefill_chunk=8, prefill_budget=8)
    eng = ServingEngine(params, cfg, serving)
    eng.generate(
        _prompts([1, 3, 9, 14], cfg.vocab_size, seed=6),
        max_new_tokens=4, temperature=0.0,
    )
    baseline = eng.compile_stats()
    assert baseline["decode"] == 1
    # ladder {8,4,2,1} -> at most 4 prefill shapes; first-token + pool
    # samplers -> at most 2
    assert baseline["prefill"] <= 4
    assert baseline["sample"] <= 2

    eng2 = ServingEngine(params, cfg, serving)  # same config: shared jits
    outs = eng2.generate(
        _prompts([2, 13, 7, 14, 5, 10, 1], cfg.vocab_size, seed=7),
        max_new_tokens=6, temperature=0.8, top_k=3, seed=42,
    )
    assert len(outs) == 7
    assert eng2.compile_stats() == baseline  # zero new compiles


class TestScheduler:
    """Host-side scheduling policy in isolation (no device work)."""

    def _sched(self, **kw):
        return Scheduler(ServingConfig(**kw))

    def _submit(self, sched, lens):
        from differential_transformer_replication_tpu.serving.request import (
            Request,
        )

        for i, L in enumerate(lens):
            sched.submit(
                Request.make(i, [1] * L), np.ones(L, np.int32), 0.0
            )

    def test_admission_is_fcfs_and_bounded_by_pool(self):
        s = self._sched(num_slots=2, prefill_chunk=8, prefill_budget=64)
        self._submit(s, [4, 4, 4])
        s.plan()
        assert s.occupied() == 2  # third request waits
        assert [sl.request.request_id
                for sl in s.slots if sl.state != FREE] == [0, 1]
        assert s.max_concurrent == 2

    def test_prefill_budget_caps_tokens_per_iteration(self):
        s = self._sched(num_slots=2, prefill_chunk=8, prefill_budget=8)
        self._submit(s, [16, 16])
        chunks = s.plan()
        assert sum(c[2] for c in chunks) <= 8
        assert all(c[0].index == chunks[0][0].index for c in chunks)  # FCFS
        for slot, start, size in chunks:
            slot.filled = start + size
        chunks = s.plan()  # budget renews each iteration
        assert sum(c[2] for c in chunks) <= 8

    def test_chunks_come_from_power_of_two_ladder(self):
        s = self._sched(num_slots=1, prefill_chunk=8, prefill_budget=64)
        self._submit(s, [13])
        sizes = [c[2] for c in s.plan()]
        assert sizes == [8, 4, 1]
        assert all(sz & (sz - 1) == 0 for sz in sizes)

    def test_retire_frees_slot_for_next_request(self):
        s = self._sched(num_slots=1, prefill_chunk=8, prefill_budget=8)
        self._submit(s, [4, 4])
        s.plan()
        slot = s.slots[0]
        assert slot.state == PREFILL and slot.request.request_id == 0
        s.retire(slot)
        s.plan()
        assert slot.request.request_id == 1
        assert s.max_concurrent == 1

    def test_queue_bound_rejects_fast(self):
        """max_queue_len: the (max+1)-th WAITING request is rejected
        immediately — overload degrades into fast retryable errors, not
        an unbounded queue."""
        s = self._sched(num_slots=1, max_queue_len=2)
        self._submit(s, [4, 4])
        with pytest.raises(QueueFullError, match="admission queue full"):
            self._submit(s, [4])
        # draining the queue re-opens admission
        s.plan()  # admits request 0 into the slot; queue drops to 1
        self._submit(s, [4])
        assert s.queue_len() == 2

    def test_cancel_queued_and_slotted(self):
        s = self._sched(num_slots=1, prefill_chunk=8, prefill_budget=8)
        self._submit(s, [4, 4])
        s.plan()  # req 0 -> slot, req 1 queued
        assert s.cancel(1) is True  # dropped from the queue
        assert s.queue_len() == 0
        assert s.cancel(0) is True  # slot retired back to the pool
        assert s.slots[0].state == FREE
        assert s.cancel(99) is False  # unknown

    def test_unbounded_by_default(self):
        s = self._sched(num_slots=1)
        self._submit(s, [4] * 50)
        assert s.queue_len() == 50


class _StubEngine:
    """Never-finishing engine: requests pile up in a fake queue so the
    runner-level admission bound and cancel plumbing are testable
    without device work."""

    def __init__(self, max_queue_len):
        self.serving = ServingConfig(num_slots=1, max_queue_len=max_queue_len)
        self.queue = []
        self.stats = {"rejected": 0, "cancelled": 0}
        self._next = 0

    def queue_len(self):
        return len(self.queue)

    def has_work(self):
        return bool(self.queue)

    def submit(self, prompt, params=None):
        if (
            self.serving.max_queue_len
            and len(self.queue) >= self.serving.max_queue_len
        ):
            self.stats["rejected"] += 1
            raise QueueFullError("admission queue full")
        rid = self._next
        self._next += 1
        self.queue.append(rid)
        return rid

    def cancel(self, rid):
        if rid in self.queue:
            self.queue.remove(rid)
            self.stats["cancelled"] += 1
            return True
        return False

    def step(self):
        import time as _t

        _t.sleep(0.005)  # never finishes anything; don't spin hot
        return []


class TestRunnerOverloadAndCancel:
    def test_runner_rejects_when_queue_full(self):
        from differential_transformer_replication_tpu.serving.server import (
            EngineRunner,
        )

        runner = EngineRunner(_StubEngine(max_queue_len=2))
        last = None
        try:
            handles = [runner.submit([1], max_new_tokens=4) for _ in range(2)]
            # give the runner time to move them into the engine queue
            deadline = time.time() + 5
            while runner.engine.queue_len() < 2 and time.time() < deadline:
                time.sleep(0.01)
            with pytest.raises(QueueFullError):
                runner.submit([1], max_new_tokens=4)
            assert runner.engine.stats["rejected"] >= 1
            # cancelling a queued request reopens admission
            runner.cancel(handles[0])
            deadline = time.time() + 5
            while runner.engine.queue_len() > 1 and time.time() < deadline:
                time.sleep(0.01)
            last = runner.submit([1], max_new_tokens=4)
        finally:
            # wait for the hand-off deque to flush before clearing the
            # stub queue, or the last submit re-populates it after the
            # clear and close() (which drains) times out on the
            # never-finishing stub
            deadline = time.time() + 10
            while last is not None and last.rid is None \
                    and time.time() < deadline:
                time.sleep(0.01)
            runner.engine.queue.clear()  # let close() drain
            runner.close()

    def test_timeout_cancels_before_engine_admission(self):
        """A request cancelled while still in the hand-off deque never
        reaches the engine at all."""
        from differential_transformer_replication_tpu.serving.server import (
            EngineRunner,
        )

        eng = _StubEngine(max_queue_len=0)
        runner = EngineRunner(eng)
        try:
            blocker = runner.submit([1], max_new_tokens=4)
            with pytest.raises(TimeoutError):
                runner.generate([2], max_new_tokens=4, timeout=0.01)
            # steady state either way: the timed-out request never hit
            # the engine (dropped from the hand-off deque) or was
            # cancelled out of its queue — only the blocker remains
            deadline = time.time() + 10
            while time.time() < deadline and eng.queue != [0]:
                time.sleep(0.01)
            assert eng.queue == [0] and blocker.rid == 0
        finally:
            eng.queue.clear()
            runner.close()


def test_engine_cancel_reclaims_slot_mid_decode():
    """The slot-leak fix at the engine level: cancelling an ACTIVE
    request frees its KV slot for the next admission instead of decoding
    to completion for nobody."""
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg, ServingConfig(num_slots=1, prefill_chunk=8,
                                   prefill_budget=8),
    )
    a = eng.submit(_prompts([5], cfg.vocab_size, seed=9)[0],
                   max_new_tokens=24, temperature=0.0)
    b = eng.submit(_prompts([4], cfg.vocab_size, seed=10)[0],
                   max_new_tokens=4, temperature=0.0)
    for _ in range(3):  # a occupies the only slot and starts decoding
        eng.step()
    assert eng.scheduler.slots[0].request.request_id == a
    assert eng.cancel(a) is True
    assert eng.scheduler.slots[0].state == FREE
    outs = eng.run()  # b admits into the freed slot and completes
    assert [o.request_id for o in outs] == [b]
    assert len(outs[0].tokens) == 4
    assert eng.stats["cancelled"] == 1
    assert eng.cancel(b) is False  # already finished
    # the interrupted slot leaves no residue: a fresh request matches
    # the reference decode bit-for-bit (ring-mask invariant)
    p = _prompts([6], cfg.vocab_size, seed=11)[0]
    out = eng.generate([p], max_new_tokens=6, temperature=0.0)[0]
    assert out.tokens == _ref_greedy(params, cfg, p, 6)


def test_client_timeout_cancels_and_slot_is_reused():
    """End-to-end slot-leak regression: a client timeout cancels the
    request in the engine (KV slot + queue entry reclaimed) and later
    requests still complete on the single slot."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, ServingConfig(num_slots=1, prefill_chunk=8,
                                   prefill_budget=8),
    ))
    try:
        with pytest.raises(TimeoutError):
            # tiny timeout: compilation alone exceeds it
            client.generate(_prompts([5], cfg.vocab_size, seed=12)[0],
                            max_new_tokens=24, timeout=0.01)
        p = _prompts([4], cfg.vocab_size, seed=13)[0]
        out = client.generate(p, max_new_tokens=4, temperature=0.0,
                              timeout=120)
        assert out.tokens == _ref_greedy(params, cfg, p, 4)
        deadline = time.time() + 30
        while time.time() < deadline and client.runner.engine.has_work():
            time.sleep(0.02)
        stats = client.stats
        assert stats["cancelled"] == 1
        assert not client.runner.engine.has_work()  # nothing decodes for nobody
    finally:
        client.close()


@pytest.mark.slow
def test_http_503_when_admission_queue_full():
    """Overload over HTTP: with a 1-slot pool and max_queue_len=1, a
    burst of 3 concurrent /generate calls gets at least one 503 and the
    accepted requests still complete; the server keeps serving after."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg,
        ServingConfig(num_slots=1, prefill_chunk=8, prefill_budget=8,
                      max_queue_len=1),
    ))
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        codes = []
        lock = threading.Lock()
        # a true simultaneous burst: all three requests hit /generate
        # within ~a millisecond, far faster than one 24-token decode can
        # finish, so the 1-slot + 1-queue server MUST shed at least one
        barrier = threading.Barrier(3)

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({
                    "prompt_ids": _prompts([5], cfg.vocab_size, seed=14)[0],
                    "max_new_tokens": 24, "temperature": 0.0,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            barrier.wait(timeout=30)
            try:
                with urllib.request.urlopen(req, timeout=300) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            with lock:
                codes.append(code)

        threads = [threading.Thread(target=post) for _ in range(3)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
        assert codes.count(200) >= 1, codes
        assert codes.count(503) >= 1, codes
        # the server is still healthy after shedding load
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert health["ok"]
        assert health["stats"]["rejected"] >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.close()


@pytest.mark.slow
def test_serving_client_and_http_server():
    """The concurrency boundary: many caller threads, one engine thread;
    and the stdlib HTTP endpoint end-to-end on an ephemeral port."""
    cfg, params = _setup("control")
    prompts = _prompts([5, 9, 3, 12], cfg.vocab_size, seed=8)
    refs = [_ref_greedy(params, cfg, p, 6) for p in prompts]

    client = ServingClient(ServingEngine(
        params, cfg, ServingConfig(num_slots=2, prefill_chunk=4,
                                   prefill_budget=8),
    ))
    try:
        # concurrent programmatic callers
        outs = client.generate_batch(
            prompts, max_new_tokens=6, temperature=0.0, timeout=120
        )
        assert [o.tokens for o in outs] == refs

        httpd = serve(client, port=0)  # ephemeral port
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({
                    "prompt_ids": prompts[0], "max_new_tokens": 6,
                    "temperature": 0.0,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                body = json.load(r)
            assert body["tokens"] == refs[0]
            assert body["finish_reason"] == "length"
            assert body["ttft_ms"] >= 0

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=30
            ) as r:
                health = json.load(r)
            assert health["ok"] and health["stats"]["completed"] >= 5

            # invalid request -> 400, server stays up
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=30)
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
            httpd.server_close()
    finally:
        client.close()


def test_serve_bench_smoke():
    """Acceptance pin: the --smoke bench completes with rc=0 under
    JAX_PLATFORMS=cpu and reports req/s, output tok/s and TTFT/ITL
    percentiles as a single JSON line."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # no need for the 8-device mesh here
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "serve_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "serving_output_tokens_per_sec"
    assert line["value"] > 0
    assert line["requests_per_sec"] > 0
    assert line["n_requests"] == 8
    for section in ("ttft_ms", "itl_ms"):
        assert line[section]["p50"] is not None
        assert line[section]["p95"] >= line[section]["p50"]
    # error breakdown (serving resilience PR): failures are reported by
    # type instead of silently folded into the latency stats
    assert line["failed"] == 0
    assert line["retries"] == 0
    assert set(line["errors"]) == {
        "queue_full", "engine_crash", "deadline", "timeout",
        "shutting_down", "other",
    }
    assert all(v == 0 for v in line["errors"].values())
