"""Continuous-batching serving engine (serving/).

The load-bearing contract: batched continuous-batching output is
BIT-IDENTICAL to sequential ``generate_cached`` greedy decoding for all
three families on mixed-length prompt sets — the engine is a scheduler
over the same math, never a different model. Plus: slot reuse after
retirement, per-request seed determinism (independent of batch
composition), EOS retirement, scheduler budget/pool invariants, and
jit-stability (no recompilation as requests come and go).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    SamplingParams,
    Scheduler,
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    FREE,
    PREFILL,
)


def _cfg(kind, vocab=61):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind, vocab=61):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# one family stays in the quick tier as the representative parity pin;
# the other two ride the full tier (conftest honors explicit slow marks)
@pytest.mark.parametrize("kind", [
    "control",
    pytest.param("diff", marks=pytest.mark.slow),
    pytest.param("ndiff", marks=pytest.mark.slow),
])
def test_batched_greedy_bit_identical_to_generate_cached(kind):
    """Acceptance pin: mixed-length prompts through a 2-slot pool (so
    requests queue and slots are reused) produce exactly the tokens
    sequential per-request generate_cached produces."""
    cfg, params = _setup(kind)
    prompts = _prompts([3, 9, 14, 6, 11], cfg.vocab_size)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=4, prefill_budget=6),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _ref_greedy(params, cfg, p, 8)
        assert o.prompt == p  # all in-window: no crop
        assert o.finish_reason == "length"
    # slot reuse + pool invariant: 5 requests through 2 slots
    assert eng.stats["completed"] == 5
    assert eng.scheduler.max_concurrent <= 2
    assert all(s.state == FREE for s in eng.scheduler.slots)


@pytest.mark.slow
def test_long_prompt_crop_and_rolling_decode_parity():
    """RoPE families crop prompts > block_size to the last block_size ids
    (the reference's own semantics, control.py:165) and roll the ring
    cache past block_size during decode — both bit-matching
    generate_cached."""
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg,
        ServingConfig(
            num_slots=3, prefill_chunk=8, prefill_budget=16, max_seq_len=64,
        ),
    )
    long_p, short_p = _prompts([40, 5], cfg.vocab_size, seed=2)
    outs = eng.generate([long_p, short_p], max_new_tokens=10, temperature=0.0)
    assert outs[0].tokens == _ref_greedy(params, cfg, long_p, 10)
    assert outs[0].prompt == long_p[-cfg.block_size:]  # cropped echo
    assert outs[1].tokens == _ref_greedy(params, cfg, short_p, 10)


@pytest.mark.slow
def test_per_request_seed_determinism_across_batch_compositions():
    """Sampled output is a function of (params, prompt, sampling params)
    only — the key chain fold_in(PRNGKey(seed), t) must not see slot
    assignment, pool size, or admission order."""
    cfg, params = _setup("control")
    reqs = list(zip(_prompts([4, 9, 6], cfg.vocab_size, seed=3), [7, 7, 99]))

    def run(num_slots, order):
        eng = ServingEngine(
            params, cfg,
            ServingConfig(num_slots=num_slots, prefill_chunk=4,
                          prefill_budget=4),
        )
        ids = {}
        for i in order:
            p, seed = reqs[i]
            ids[eng.submit(p, temperature=1.0, top_k=5, seed=seed,
                           max_new_tokens=6)] = i
        return {ids[o.request_id]: o.tokens for o in eng.run()}

    a = run(1, [0, 1, 2])
    b = run(3, [2, 0, 1])
    assert a == b
    assert all(len(t) == 6 for t in a.values())
    # and every draw is a valid token id
    assert all(0 <= tok < cfg.vocab_size for t in a.values() for tok in t)


@pytest.mark.slow
def test_sampled_chain_matches_sample_token_reference():
    """The engine's batched sampler must be bit-identical, token for
    token, to the single-request sample_token contract with the same
    fold_in key chain (models/generate.py)."""
    from differential_transformer_replication_tpu.models.decode import (
        forward_chunk,
        init_cache,
    )
    from differential_transformer_replication_tpu.models.generate import (
        sample_token,
    )

    cfg, params = _setup("control")
    prompt = _prompts([5], cfg.vocab_size, seed=4)[0]
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    out = eng.generate(
        [prompt], temperature=1.0, top_k=5, seed=11, max_new_tokens=6
    )[0]

    base = jax.random.PRNGKey(11)
    cache = init_cache(cfg, 1)
    logits, cache = forward_chunk(
        params, jnp.asarray(prompt, jnp.int32)[None], 0, cache, cfg,
        rope_len=cfg.block_size,
    )
    toks = []
    for t in range(6):
        key = jax.random.fold_in(base, t)
        tok = int(sample_token(
            key, logits[:, -1].astype(jnp.float32), 1.0, 5
        )[0])
        toks.append(tok)
        if t < 5:
            logits, cache = forward_chunk(
                params, jnp.asarray([[tok]], jnp.int32), len(prompt) + t,
                cache, cfg, rope_len=cfg.block_size,
            )
    assert out.tokens == toks


def test_eos_retires_slot_early_without_stalling_batch():
    cfg, params = _setup("control")
    prompts = _prompts([5, 8], cfg.vocab_size, seed=5)
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    ref = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
    first_tok = ref[0].tokens[0]

    eng2 = ServingEngine(params, cfg, ServingConfig(num_slots=2))
    a = eng2.submit(prompts[0], max_new_tokens=6, temperature=0.0,
                    eos_token_id=first_tok)
    b = eng2.submit(prompts[1], max_new_tokens=6, temperature=0.0)
    outs = {o.request_id: o for o in eng2.run()}
    assert outs[a].tokens == [first_tok]
    assert outs[a].finish_reason == "eos"
    # the other sequence is unaffected by the early retirement
    assert outs[b].tokens == ref[1].tokens
    assert outs[b].finish_reason == "length"


def test_submit_validation():
    cfg, params = _setup("diff")
    eng = ServingEngine(params, cfg, ServingConfig(num_slots=1))
    with pytest.raises(ValueError):  # diff cannot roll past block_size
        eng.submit(list(range(30)), max_new_tokens=10)
    with pytest.raises(ValueError):
        eng.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)

    ccfg, cparams = _setup("control")
    ceng = ServingEngine(cparams, ccfg, ServingConfig(num_slots=1))
    with pytest.raises(ValueError):  # past the engine's RoPE table
        ceng.submit(list(range(30)), max_new_tokens=10)


def test_decode_stays_jit_stable_as_requests_come_and_go():
    """Acceptance pin: a first wave compiles everything (decode step,
    prefill ladder, samplers); a second wave with different lengths,
    seeds, sampling params and admission patterns must not add a single
    cache entry, and the decode step must have compiled exactly once."""
    cfg, params = _setup("control", vocab=53)  # fresh compile-cache key
    serving = ServingConfig(num_slots=3, prefill_chunk=8, prefill_budget=8)
    eng = ServingEngine(params, cfg, serving)
    eng.generate(
        _prompts([1, 3, 9, 14], cfg.vocab_size, seed=6),
        max_new_tokens=4, temperature=0.0,
    )
    baseline = eng.compile_stats()
    assert baseline["decode"] == 1
    # ladder {8,4,2,1} -> at most 4 prefill shapes; first-token + pool
    # samplers -> at most 2
    assert baseline["prefill"] <= 4
    assert baseline["sample"] <= 2

    eng2 = ServingEngine(params, cfg, serving)  # same config: shared jits
    outs = eng2.generate(
        _prompts([2, 13, 7, 14, 5, 10, 1], cfg.vocab_size, seed=7),
        max_new_tokens=6, temperature=0.8, top_k=3, seed=42,
    )
    assert len(outs) == 7
    assert eng2.compile_stats() == baseline  # zero new compiles


class TestScheduler:
    """Host-side scheduling policy in isolation (no device work)."""

    def _sched(self, **kw):
        return Scheduler(ServingConfig(**kw))

    def _submit(self, sched, lens):
        from differential_transformer_replication_tpu.serving.request import (
            Request,
        )

        for i, L in enumerate(lens):
            sched.submit(
                Request.make(i, [1] * L), np.ones(L, np.int32), 0.0
            )

    def test_admission_is_fcfs_and_bounded_by_pool(self):
        s = self._sched(num_slots=2, prefill_chunk=8, prefill_budget=64)
        self._submit(s, [4, 4, 4])
        s.plan()
        assert s.occupied() == 2  # third request waits
        assert [sl.request.request_id
                for sl in s.slots if sl.state != FREE] == [0, 1]
        assert s.max_concurrent == 2

    def test_prefill_budget_caps_tokens_per_iteration(self):
        s = self._sched(num_slots=2, prefill_chunk=8, prefill_budget=8)
        self._submit(s, [16, 16])
        chunks = s.plan()
        assert sum(c[2] for c in chunks) <= 8
        assert all(c[0].index == chunks[0][0].index for c in chunks)  # FCFS
        for slot, start, size in chunks:
            slot.filled = start + size
        chunks = s.plan()  # budget renews each iteration
        assert sum(c[2] for c in chunks) <= 8

    def test_chunks_come_from_power_of_two_ladder(self):
        s = self._sched(num_slots=1, prefill_chunk=8, prefill_budget=64)
        self._submit(s, [13])
        sizes = [c[2] for c in s.plan()]
        assert sizes == [8, 4, 1]
        assert all(sz & (sz - 1) == 0 for sz in sizes)

    def test_retire_frees_slot_for_next_request(self):
        s = self._sched(num_slots=1, prefill_chunk=8, prefill_budget=8)
        self._submit(s, [4, 4])
        s.plan()
        slot = s.slots[0]
        assert slot.state == PREFILL and slot.request.request_id == 0
        s.retire(slot)
        s.plan()
        assert slot.request.request_id == 1
        assert s.max_concurrent == 1


@pytest.mark.slow
def test_serving_client_and_http_server():
    """The concurrency boundary: many caller threads, one engine thread;
    and the stdlib HTTP endpoint end-to-end on an ephemeral port."""
    cfg, params = _setup("control")
    prompts = _prompts([5, 9, 3, 12], cfg.vocab_size, seed=8)
    refs = [_ref_greedy(params, cfg, p, 6) for p in prompts]

    client = ServingClient(ServingEngine(
        params, cfg, ServingConfig(num_slots=2, prefill_chunk=4,
                                   prefill_budget=8),
    ))
    try:
        # concurrent programmatic callers
        outs = client.generate_batch(
            prompts, max_new_tokens=6, temperature=0.0, timeout=120
        )
        assert [o.tokens for o in outs] == refs

        httpd = serve(client, port=0)  # ephemeral port
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({
                    "prompt_ids": prompts[0], "max_new_tokens": 6,
                    "temperature": 0.0,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                body = json.load(r)
            assert body["tokens"] == refs[0]
            assert body["finish_reason"] == "length"
            assert body["ttft_ms"] >= 0

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=30
            ) as r:
                health = json.load(r)
            assert health["ok"] and health["stats"]["completed"] >= 5

            # invalid request -> 400, server stays up
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=30)
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
            httpd.server_close()
    finally:
        client.close()


def test_serve_bench_smoke():
    """Acceptance pin: the --smoke bench completes with rc=0 under
    JAX_PLATFORMS=cpu and reports req/s, output tok/s and TTFT/ITL
    percentiles as a single JSON line."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # no need for the 8-device mesh here
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "serve_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "serving_output_tokens_per_sec"
    assert line["value"] > 0
    assert line["requests_per_sec"] > 0
    assert line["n_requests"] == 8
    for section in ("ttft_ms", "itl_ms"):
        assert line[section]["p50"] is not None
        assert line[section]["p95"] >= line[section]["p50"]
