"""Parity tests: fused Pallas flash kernels vs the naive XLA path.

SURVEY.md section 4 ("Pallas kernel tests ... vs the naive jit reference
implementation, over shapes/dtypes/mask edges"). On CPU the kernels run in
Pallas interpreter mode; on TPU the same code compiles through Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.ops import (
    causal_mask,
    diff_attention,
    flash_diff_attention,
    flash_ndiff_attention,
    flash_vanilla_attention,
    multi_stream_flash_attention,
    ndiff_attention,
    ndiff_signs,
    vanilla_attention,
)

B, T, H, D = 2, 64, 2, 16


def _zseed():
    """No-dropout seed operand for the chunk op."""
    return jnp.zeros((1, 2), jnp.float32)


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("block", [(64, 64), (32, 16), (16, 32)])
def test_vanilla_parity(block):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = flash_vanilla_attention(q, k, v, block_q=block[0], block_k=block[1])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block", [(64, 64), (32, 32)])
def test_diff_parity(block):
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.array([0.2, 0.47], jnp.float32)
    ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
    got = flash_diff_attention(
        q1, k1, q2, k2, v, lam, block_q=block[0], block_k=block[1]
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ndiff_parity():
    n = 3
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    qs = _rand(ks[0], n, B, T, H, D)
    kss = _rand(ks[1], n, B, T, H, D)
    v = _rand(ks[2], B, T, H, 2 * D)
    lams = jnp.abs(_rand(jax.random.PRNGKey(3), n, H)) * 0.3 + 0.1
    signs = ndiff_signs(n)
    ref = ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(T))
    got = flash_ndiff_attention(qs, kss, v, lams, signs, block_q=32, block_k=32)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_odd_seq_len_single_block():
    """T not a multiple of 128 falls back to divisor blocks."""
    t = 48
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (_rand(kk, 1, t, 1, 8) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(t))
    got = flash_vanilla_attention(q, k, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_diff_grad_parity():
    """The custom VJP matches autodiff through the naive path — q/k/v AND
    the lambda coefficients (the dcoeff einsum in the backward)."""
    ks = jax.random.split(jax.random.PRNGKey(5), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.array([0.2, 0.47], jnp.float32)

    def loss_ref(q1, k1, q2, k2, v, lam):
        out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        return jnp.sum(out * jnp.cos(out))  # non-trivial cotangent

    def loss_flash(q1, k1, q2, k2, v, lam):
        out = flash_diff_attention(q1, k1, q2, k2, v, lam, block_q=32, block_k=32, block_q_train=32, block_k_train=16)
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(q1, k1, q2, k2, v, lam)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2, 3, 4, 5))(q1, k1, q2, k2, v, lam)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_vanilla_grad_parity():
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q, k, v = (_rand(kk, 1, 32, 2, 8) for kk in ks)

    def loss_ref(q, k, v):
        return jnp.sum(vanilla_attention(q, k, v, mask=causal_mask(32)) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_vanilla_attention(q, k, v, block_q=16, block_k=16, block_q_train=16, block_k_train=16) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_ndiff_grad_parity():
    n = 2
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    qs = _rand(ks[0], n, 1, 32, H, 8)
    kss = _rand(ks[1], n, 1, 32, H, 8)
    v = _rand(ks[2], 1, 32, H, 16)
    lams = jnp.abs(_rand(jax.random.PRNGKey(8), n, H)) * 0.3 + 0.1
    signs = ndiff_signs(n)

    def loss_ref(qs, kss, v, lams):
        return jnp.sum(ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(32)) ** 2)

    def loss_flash(qs, kss, v, lams):
        return jnp.sum(
            flash_ndiff_attention(qs, kss, v, lams, signs, block_q=16, block_k=16, block_q_train=16, block_k_train=16) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(qs, kss, v, lams)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(qs, kss, v, lams)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_bf16_runs_and_is_close():
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (_rand(kk, B, T, H, D).astype(jnp.bfloat16) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = flash_vanilla_attention(q, k, v, block_q=32, block_k=32)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        got.astype(jnp.float32), ref.astype(jnp.float32), rtol=5e-2, atol=5e-2
    )


class TestKVTiled:
    """The KV-streaming (tiled) kernel variant must match the full-K/V
    path exactly. Forced on at small T via the dispatch threshold."""

    @pytest.fixture(autouse=True)
    def _force_tiled(self, monkeypatch):
        from differential_transformer_replication_tpu.ops import flash
        monkeypatch.setattr(flash, "_KV_TILE_THRESHOLD", 16)
        # the backward holds its own dispatch threshold (it may tile
        # earlier than the forward) AND a fused whole-T fast path that
        # intercepts BEFORE the threshold check — force all three off so
        # the class exercises the tiled dq/dkv kernels it names
        monkeypatch.setattr(flash, "_BWD_KV_TILE_THRESHOLD", 16)
        monkeypatch.setattr(flash, "_FUSED_BWD_BUDGET", 0)

    def test_diff_parity_tiled(self):
        ks = jax.random.split(jax.random.PRNGKey(20), 5)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)
        ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        got = flash_diff_attention(
            q1, k1, q2, k2, v, lam, block_q=32, block_k=16
        )
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_diff_grad_parity_tiled(self):
        ks = jax.random.split(jax.random.PRNGKey(21), 5)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)

        def loss_ref(q1, k1, q2, k2, v, lam):
            out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
            return jnp.sum(out * jnp.cos(out))

        def loss_flash(q1, k1, q2, k2, v, lam):
            out = flash_diff_attention(
                q1, k1, q2, k2, v, lam,
                block_q=32, block_k=32, block_q_train=32, block_k_train=16,
            )
            return jnp.sum(out * jnp.cos(out))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(
            q1, k1, q2, k2, v, lam
        )
        g_got = jax.grad(loss_flash, argnums=(0, 1, 2, 3, 4, 5))(
            q1, k1, q2, k2, v, lam
        )
        for r, g in zip(g_ref, g_got):
            np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)

    def test_chunk_tiled_matches_untiled(self):
        """Offset-aware chunk op: tiled vs full-residency bitwise-close."""
        from differential_transformer_replication_tpu.ops import flash
        ks = jax.random.split(jax.random.PRNGKey(22), 3)
        q = _rand(ks[0], 4, 2, 64, 16)
        k = _rand(ks[1], 4, 2, 64, 16)
        v = _rand(ks[2], 4, 64, 32)
        for off_val in (0.0, 64.0, -64.0):
            off = jnp.full((1, 1), off_val, jnp.float32)
            o_t, lse_t = flash.flash_chunk_attention(
                q, k, v, off, _zseed(), (32, 16, 32, 16), True
            )
            with pytest.MonkeyPatch.context() as mp:
                mp.setattr(flash, "_KV_TILE_THRESHOLD", 4096)
                o_u, lse_u = flash.flash_chunk_attention(
                    q, k, v, off, _zseed(), (32, 16, 32, 16), True
                )
            np.testing.assert_allclose(o_t, o_u, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(lse_t, lse_u, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("off_val", [0.0, 32.0, 64.0, -32.0])
    def test_chunk_grads_tiled_match_untiled(self, off_val):
        """Tiled backward kernels with nonzero ring offsets: gradients
        (including the dlse cotangent) must match the full-residency
        backward exactly."""
        from differential_transformer_replication_tpu.ops import flash
        ks = jax.random.split(jax.random.PRNGKey(23), 3)
        q = _rand(ks[0], 4, 2, 64, 16)
        k = _rand(ks[1], 4, 2, 64, 16)
        v = _rand(ks[2], 4, 64, 32)
        off = jnp.full((1, 1), off_val, jnp.float32)

        def loss(q, k, v):
            o, lse = flash.flash_chunk_attention(
                q, k, v, off, _zseed(), (32, 16, 32, 16), True
            )
            return jnp.sum(o * jnp.cos(o)) + jnp.sum(
                jnp.where(lse > -1e29, lse, 0.0)
            )

        g_tiled = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)  # threshold=16
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(flash, "_KV_TILE_THRESHOLD", 4096)
            g_full = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_tiled, g_full):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_bwd_tiled_below_fwd_threshold(monkeypatch):
    """The mixed regime the backward-only threshold enables: forward stays
    on the full-K/V-resident kernels while the backward streams K/V
    through the tiled kernels (the VMEM-friendly option at
    1024 < T <= 4096). Grad parity vs the dense reference pins it."""
    from differential_transformer_replication_tpu.ops import flash

    monkeypatch.setattr(flash, "_BWD_KV_TILE_THRESHOLD", 16)  # fwd stays 4096
    # the fused whole-T backward intercepts before the threshold check;
    # disable it so the tiled backward actually runs at this small T
    monkeypatch.setattr(flash, "_FUSED_BWD_BUDGET", 0)
    ks = jax.random.split(jax.random.PRNGKey(23), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.array([0.2, 0.47], jnp.float32)

    def loss_ref(q1, k1, q2, k2, v, lam):
        out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        return jnp.sum(out * jnp.cos(out))

    def loss_flash(q1, k1, q2, k2, v, lam):
        out = flash_diff_attention(
            q1, k1, q2, k2, v, lam,
            block_q=32, block_k=32, block_q_train=32, block_k_train=16,
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(
        q1, k1, q2, k2, v, lam
    )
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2, 3, 4, 5))(
        q1, k1, q2, k2, v, lam
    )
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


class TestTokenMajor:
    """The token-major (tm) kernels (ops/flash.py): per-stream (B, T, H, d)
    in, (B, T, H, dv) out — the projection-native layout the recipe-scale
    train step runs on (round 4). Parity vs the dense XLA ops."""

    def _diff_inputs(self, seed=7):
        ks = jax.random.split(jax.random.PRNGKey(seed), 6)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)
        return q1, k1, q2, k2, v, lam

    def test_use_tm_envelope(self):
        from differential_transformer_replication_tpu.ops import flash

        assert flash.use_tm(2, 512, 0.0)  # the flagship recipe point
        assert flash.use_tm(1, 512, 0.0)  # control
        assert flash.use_tm(4, 512, 0.0)  # ndiff n_terms=4 (round 5)
        assert not flash.use_tm(1, 1024, 0.0)  # T^2 transients blow VMEM
        assert not flash.use_tm(4, 1024, 0.0)  # likewise at any S
        assert not flash.use_tm(8, 512, 0.0)  # past the measured stream cap
        assert not flash.use_tm(2, 512, 0.1)  # dropout stays head-major
        assert not flash.use_tm(1, 2048, 0.0)  # past the bias-resident max

    def test_diff_parity_tm(self):
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_tm,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            diff_coeffs,
        )

        q1, k1, q2, k2, v, lam = self._diff_inputs()
        ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        got = multi_stream_flash_attention_tm(
            (q1, q2), (k1, k2), v, diff_coeffs(lam), B, H
        )
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_vanilla_parity_tm(self):
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_tm,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            vanilla_coeffs,
        )

        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
        ref = vanilla_attention(q, k, v, mask=causal_mask(T))
        got = multi_stream_flash_attention_tm(
            (q,), (k,), v, vanilla_coeffs(H), B, H
        )
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_diff_grad_parity_tm(self):
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_tm,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            diff_coeffs,
        )

        q1, k1, q2, k2, v, lam = self._diff_inputs(seed=13)

        def loss_ref(q1, k1, q2, k2, v, lam):
            out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
            return jnp.sum(out * jnp.cos(out))

        def loss_tm(q1, k1, q2, k2, v, lam):
            out = multi_stream_flash_attention_tm(
                (q1, q2), (k1, k2), v, diff_coeffs(lam), B, H
            )
            return jnp.sum(out * jnp.cos(out))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(
            q1, k1, q2, k2, v, lam
        )
        g_got = jax.grad(loss_tm, argnums=(0, 1, 2, 3, 4, 5))(
            q1, k1, q2, k2, v, lam
        )
        for r, g in zip(g_ref, g_got):
            np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)

    def test_bh_fn_routes_tm_and_matches_dense(self):
        """flash_bh_fn's tm branch (models/common.py) end to end: same
        closure the model families install, eligible shape, vs the dense
        path on identical projections."""
        from differential_transformer_replication_tpu.models import common
        from differential_transformer_replication_tpu.ops.streams import (
            diff_coeffs,
        )

        E, d = 32, D
        ks = jax.random.split(jax.random.PRNGKey(17), 4)
        x = _rand(ks[0], B, T, E)
        wq = _rand(ks[1], 2, E, H, d) * 0.2
        wk = _rand(ks[2], 2, E, H, d) * 0.2
        wv = _rand(ks[3], E, H, 2 * d) * 0.2
        lam = jnp.array([0.3, 0.5], jnp.float32)
        coeffs = diff_coeffs(lam)
        got = common.flash_bh_fn(
            x, wq, wk, wv, coeffs, dropout_rate=0.0, rng=None
        )()
        q1, q2 = (jnp.einsum("bte,ehd->bthd", x, wq[s]) for s in range(2))
        k1, k2 = (jnp.einsum("bte,ehd->bthd", x, wk[s]) for s in range(2))
        v = jnp.einsum("bte,ehd->bthd", x, wv)
        ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_bh_fn_tm_with_rope_matches_dense(self):
        """The tm branch with LIVE RoPE tables — the path every
        recipe-scale control run takes (control.py:94 passes cos/sin,
        S=1, T<=512): rotation in the (B, T, H, d) headed layout must
        match rotating the dense path's projections."""
        from differential_transformer_replication_tpu.models import common
        from differential_transformer_replication_tpu.ops.rope import (
            apply_rope,
            rope_cos_sin,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            vanilla_coeffs,
        )

        E, d = 32, D
        ks = jax.random.split(jax.random.PRNGKey(19), 4)
        x = _rand(ks[0], B, T, E)
        wq = _rand(ks[1], 1, E, H, d) * 0.2
        wk = _rand(ks[2], 1, E, H, d) * 0.2
        wv = _rand(ks[3], E, H, d) * 0.2
        cos, sin = rope_cos_sin(d, T)
        got = common.flash_bh_fn(
            x, wq, wk, wv, vanilla_coeffs(H),
            dropout_rate=0.0, rng=None, cos=cos, sin=sin,
        )()
        q = apply_rope(jnp.einsum("bte,ehd->bthd", x, wq[0]), cos, sin)
        k = apply_rope(jnp.einsum("bte,ehd->bthd", x, wk[0]), cos, sin)
        v = jnp.einsum("bte,ehd->bthd", x, wv)
        ref = vanilla_attention(q, k, v, mask=causal_mask(T))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_packed_grad_parity_tm(self):
        """The packed-projection entry (one fused matmul, windowed
        operands, single packed dproj) must match dense gradients — this
        is the recipe-hot diff training path (models/common.py packed
        branch)."""
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_tm_packed,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            diff_coeffs,
        )

        q1, k1, q2, k2, v, lam = self._diff_inputs(seed=29)
        coeffs = diff_coeffs(lam)
        d, dv = D, 2 * D

        def pack(q1, q2, k1, k2, v):
            return jnp.concatenate(
                [a.reshape(B, T, -1) for a in (q1, q2, k1, k2, v)], axis=-1
            )

        def loss_packed(args):
            out = multi_stream_flash_attention_tm_packed(
                pack(*args), coeffs, B, H, 2, d, dv
            )
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(args):
            q1, q2, k1, k2, v = args
            out = diff_attention(
                q1, k1, q2, k2, v, lam, mask=causal_mask(T)
            )
            return jnp.sum(out * jnp.cos(out))

        args = (q1, q2, k1, k2, v)
        g_p = jax.grad(loss_packed)(args)
        g_r = jax.grad(loss_ref)(args)
        for name, a, b in zip("q1 q2 k1 k2 v".split(), g_p, g_r):
            np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=1e-4, err_msg=name
            )


class TestTokenMajorNdiff:
    """S=4 (ndiff n_terms=4) on the token-major kernels — the stream
    count the round-5 tm admission envelope allows at recipe T (the tm
    backward walks (head, stream) pairs sequentially, so its transients
    do not scale with S; see ops/flash.py use_tm)."""

    def test_ndiff_s4_grad_parity_tm(self):
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_tm,
        )
        from differential_transformer_replication_tpu.ops.attention import (
            ndiff_attention,
        )
        from differential_transformer_replication_tpu.ops.lambdas import (
            ndiff_signs,
        )
        from differential_transformer_replication_tpu.ops.streams import (
            ndiff_coeffs,
        )

        n = 4
        ks = jax.random.split(jax.random.PRNGKey(31), 3)
        qs = _rand(ks[0], n, B, T, H, D)
        kss = _rand(ks[1], n, B, T, H, D)
        v = _rand(ks[2], B, T, H, 2 * D)
        lams = jnp.linspace(0.2, 0.7, n * H).reshape(n, H)
        signs = ndiff_signs(n)
        coeffs = ndiff_coeffs(lams, signs)

        def loss_ref(qs, kss, v):
            out = ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(T))
            return jnp.sum(out * jnp.cos(out))

        def loss_tm(qs, kss, v):
            out = multi_stream_flash_attention_tm(
                tuple(qs[i] for i in range(n)),
                tuple(kss[i] for i in range(n)),
                v, coeffs, B, H,
            )
            return jnp.sum(out * jnp.cos(out))

        np.testing.assert_allclose(
            loss_tm(qs, kss, v), loss_ref(qs, kss, v), rtol=1e-5
        )
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qs, kss, v)
        g_tm = jax.grad(loss_tm, argnums=(0, 1, 2))(qs, kss, v)
        for r, g in zip(g_ref, g_tm):
            np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_tm_block_clamp_and_packed_ok():
    """Round-5 dispatch helpers: the S>=3 VMEM block clamp (including
    explicit overrides) and packed-window eligibility (offset + 128-lane
    rules)."""
    from differential_transformer_replication_tpu.ops import flash

    assert flash._tm_train_block_q(1) == 512
    assert flash._tm_train_block_q(2) == 512
    assert flash._tm_train_block_q(3) == 256
    assert flash._tm_train_block_q(4) == 256

    # recipe widths: diff S=2 H=4 d=96 dv=192 -> packed eligible
    assert flash.tm_packed_ok(2, 4, 96, 192)
    # control S=1, dv=d -> offset 2*Hd is 2 v-blocks, eligible at H*d>=128
    assert flash.tm_packed_ok(1, 4, 96, 96)
    # narrow test-scale model: H*d = 32 < 128 lanes -> per-array path
    assert not flash.tm_packed_ok(2, 2, 16, 32)
    # exotic dv/d ratio that misaligns the v window offset
    assert not flash.tm_packed_ok(1, 1, 128, 384)
