"""Ring-attention (sequence parallelism) tests on the virtual CPU mesh.

SURVEY.md section 4: distributed tests without a cluster via
``xla_force_host_platform_device_count`` (set in conftest.py). The ring
path must match the dense single-device ops bit-for-bit up to fp32
accumulation order, including gradients through the ppermute rotation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.ops import (
    causal_mask,
    diff_attention,
    ndiff_attention,
    ndiff_signs,
    vanilla_attention,
)
from differential_transformer_replication_tpu.parallel import create_mesh
from differential_transformer_replication_tpu.parallel.ring import (
    ring_diff_attention,
    ring_ndiff_attention,
    ring_vanilla_attention,
    use_ring,
)

B, T, H, D = 2, 64, 2, 16


def _seq_mesh(n_seq: int, tensor: int = 1) -> Mesh:
    return create_mesh(MeshConfig(data=1, fsdp=1, tensor=tensor, sequence=n_seq))


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("n_seq", [2, 4, 8])
def test_vanilla_ring_parity(n_seq):
    mesh = _seq_mesh(n_seq)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = jax.jit(lambda q, k, v: ring_vanilla_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_diff_ring_parity():
    mesh = _seq_mesh(4)
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.array([0.2, 0.47], jnp.float32)
    ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
    got = jax.jit(
        lambda *a: ring_diff_attention(*a, lam, mesh)
    )(q1, k1, q2, k2, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ndiff_ring_parity():
    mesh = _seq_mesh(4)
    n = 3
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    qs = _rand(ks[0], n, B, T, H, D)
    kss = _rand(ks[1], n, B, T, H, D)
    v = _rand(ks[2], B, T, H, 2 * D)
    lams = jnp.abs(_rand(jax.random.PRNGKey(3), n, H)) * 0.3 + 0.1
    signs = ndiff_signs(n)
    ref = ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(T))
    got = jax.jit(lambda qs, kss, v: ring_ndiff_attention(qs, kss, v, lams, signs, mesh))(
        qs, kss, v
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ring_grad_parity():
    """Gradients flow back around the ring (ppermute transpose)."""
    mesh = _seq_mesh(4)
    ks = jax.random.split(jax.random.PRNGKey(4), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.array([0.2, 0.47], jnp.float32)

    def loss_ref(q1, k1, q2, k2, v):
        out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        return jnp.sum(out * jnp.cos(out))

    def loss_ring(q1, k1, q2, k2, v):
        out = ring_diff_attention(q1, k1, q2, k2, v, lam, mesh)
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(q1, k1, q2, k2, v)
    g_got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2, 3, 4)))(q1, k1, q2, k2, v)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_ring_composes_with_tensor_axis():
    """sequence ring + tensor head sharding in one shard_map."""
    mesh = _seq_mesh(4, tensor=2)
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (_rand(kk, B, T, 2, D) for kk in ks)  # H=2 divisible by tensor
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = jax.jit(lambda q, k, v: ring_vanilla_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_model_forward_sequence_parallel(kind):
    """Full model forward with mesh threading: ring attention inside an
    otherwise GSPMD-partitioned forward matches the dense forward."""
    mesh = _seq_mesh(4)
    cfg = ModelConfig(
        model=kind, vocab_size=97, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=2, compute_dtype="float32",
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    ref, _ = model_forward(params, idx, cfg)
    got, _ = jax.jit(lambda p, i: model_forward(p, i, cfg, mesh=mesh))(params, idx)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_sharded_train_step_with_sequence_axis():
    """End-to-end sharded train step on a data=2 x sequence=2 x tensor=2
    mesh: compiles, runs, loss finite, step increments."""
    from differential_transformer_replication_tpu.parallel import (
        make_sharded_train_step,
    )
    from differential_transformer_replication_tpu.parallel.dp_step import (
        create_sharded_train_state,
    )

    mesh_cfg = MeshConfig(data=2, fsdp=1, tensor=2, sequence=2)
    model = ModelConfig(
        model="diff", vocab_size=64, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, compute_dtype="float32",
    )
    cfg = TrainConfig(
        model=model, mesh=mesh_cfg, vocab_size=64, micro_batch_size=4,
        grad_acc_steps=2, control_head_multiplier=1,
    )
    mesh = create_mesh(mesh_cfg)
    state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_sharded_train_step(cfg, mesh, state)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 32), 0, 64)
    batch = {"x": x, "y": jnp.roll(x, -1, axis=-1)}
    state2, metrics = step(state, batch)
    assert jnp.isfinite(float(metrics["loss"]))
    assert int(state2["step"]) == 1
    # a second step keeps working (state round-trips through the shardings)
    state3, metrics2 = step(state2, batch)
    assert jnp.isfinite(float(metrics2["loss"]))


def test_use_ring_predicate():
    assert not use_ring(None)
    assert not use_ring(_seq_mesh(1))
    assert use_ring(_seq_mesh(2))


class TestRingFlash:
    """Ring with the fused flash chunk kernel (impl="pallas"): same math as
    the dense ring and the single-device ops, including gradients through
    the chunk custom_vjp + logsumexp merge + ppermute composition."""

    def test_vanilla_ring_flash_parity(self):
        mesh = _seq_mesh(4)
        ks = jax.random.split(jax.random.PRNGKey(10), 3)
        q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
        ref = vanilla_attention(q, k, v, mask=causal_mask(T))
        got = jax.jit(
            lambda q, k, v: ring_vanilla_attention(q, k, v, mesh, "pallas")
        )(q, k, v)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_diff_ring_flash_parity(self):
        mesh = _seq_mesh(4)
        ks = jax.random.split(jax.random.PRNGKey(11), 5)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)
        ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        got = jax.jit(
            lambda *a: ring_diff_attention(*a, lam, mesh, "pallas")
        )(q1, k1, q2, k2, v)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_ndiff_ring_flash_parity(self):
        mesh = _seq_mesh(2)
        n = 3
        ks = jax.random.split(jax.random.PRNGKey(12), 3)
        qs = _rand(ks[0], n, B, T, H, D)
        kss = _rand(ks[1], n, B, T, H, D)
        v = _rand(ks[2], B, T, H, 2 * D)
        lams = jnp.abs(_rand(jax.random.PRNGKey(13), n, H)) * 0.3 + 0.1
        signs = ndiff_signs(n)
        ref = ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(T))
        got = jax.jit(
            lambda qs, kss, v: ring_ndiff_attention(
                qs, kss, v, lams, signs, mesh, "pallas"
            )
        )(qs, kss, v)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_ring_flash_grad_parity(self):
        mesh = _seq_mesh(4)
        ks = jax.random.split(jax.random.PRNGKey(14), 5)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)

        def loss_ref(q1, k1, q2, k2, v):
            out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
            return jnp.sum(out * jnp.cos(out))

        def loss_ring(q1, k1, q2, k2, v):
            out = ring_diff_attention(q1, k1, q2, k2, v, lam, mesh, "pallas")
            return jnp.sum(out * jnp.cos(out))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(q1, k1, q2, k2, v)
        g_got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2, 3, 4)))(
            q1, k1, q2, k2, v
        )
        for r, g in zip(g_ref, g_got):
            np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


class TestRingDropout:
    """Attention-prob dropout on the sequence-parallel path (both impls):
    softmax-then-dropout semantics with the normalizer accumulating
    undropped sums. Masks differ from the dense path's rng stream, so the
    checks are behavioral: determinism per key, variation across keys,
    inertness without one, mean preservation, and live gradients."""

    def _inputs(self, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 5)
        q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
        v = _rand(ks[4], B, T, H, 2 * D)
        lam = jnp.array([0.2, 0.47], jnp.float32)
        return q1, k1, q2, k2, v, lam

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_deterministic_inert_and_varying(self, impl):
        mesh = _seq_mesh(4)
        q1, k1, q2, k2, v, lam = self._inputs()
        f = jax.jit(
            lambda rng: ring_diff_attention(
                q1, k1, q2, k2, v, lam, mesh, impl,
                dropout_rate=0.3, dropout_rng=rng,
            )
        )
        a = np.asarray(f(jax.random.PRNGKey(2)))
        b = np.asarray(f(jax.random.PRNGKey(2)))
        c = np.asarray(f(jax.random.PRNGKey(3)))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert np.isfinite(a).all()
        # no key -> identical to the dropout-free ring
        base = np.asarray(
            ring_diff_attention(q1, k1, q2, k2, v, lam, mesh, impl)
        )
        nokey = np.asarray(
            ring_diff_attention(
                q1, k1, q2, k2, v, lam, mesh, impl, dropout_rate=0.3
            )
        )
        np.testing.assert_array_equal(base, nokey)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_mean_preservation(self, impl):
        """Inverted dropout is unbiased: averaging the ring output over
        many keys approaches the dropout-free output."""
        mesh = _seq_mesh(2)
        q1, k1, q2, k2, v, lam = self._inputs(1)
        base = np.asarray(
            ring_diff_attention(q1, k1, q2, k2, v, lam, mesh, impl)
        )
        f = jax.jit(
            lambda rng: ring_diff_attention(
                q1, k1, q2, k2, v, lam, mesh, impl,
                dropout_rate=0.3, dropout_rng=rng,
            )
        )
        n = 48
        acc = np.zeros_like(base)
        for i in range(n):
            acc += np.asarray(f(jax.random.PRNGKey(100 + i)))
        err = np.abs(acc / n - base).mean()
        scale = np.abs(base).mean()
        assert err < 0.12 * scale, (err, scale)

    def test_grads_flow(self):
        mesh = _seq_mesh(4)
        q1, k1, q2, k2, v, lam = self._inputs(2)
        g = jax.grad(
            lambda q1, k1, q2, k2, v: jnp.sum(
                ring_diff_attention(
                    q1, k1, q2, k2, v, lam, mesh, "pallas",
                    dropout_rate=0.3, dropout_rng=jax.random.PRNGKey(4),
                ) ** 2
            ),
            argnums=(0, 1, 2, 3, 4),
        )(q1, k1, q2, k2, v)
        for a in g:
            assert np.isfinite(np.asarray(a)).all()
        assert sum(float(jnp.sum(jnp.abs(a))) for a in g) > 0

    def test_model_forward_ring_dropout(self):
        """End to end: a diff model on a sequence-parallel mesh with
        dropout active trains without the old NotImplementedError."""
        mesh = _seq_mesh(2)
        cfg = ModelConfig(
            model="diff", vocab_size=64, n_embd=32, n_head=2, n_layer=2,
            block_size=16, dropout=0.25, compute_dtype="float32",
        )
        params = init_model(jax.random.PRNGKey(0), cfg)
        x = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        y = jnp.roll(x, -1, -1)
        _, loss = model_forward(
            params, x, cfg, targets=y, rng=jax.random.PRNGKey(2), mesh=mesh
        )
        assert np.isfinite(float(loss))
