"""Model-quality observability plane (obs/quality.py + the engine's
in-step telemetry tail).

Pinned here, against numpy oracles where the math matters:

- the fixed-bin QuantileSketch/PSI/drift machinery (stdlib-only, no
  device) — identical distributions score ~0, shifted ones cross the
  0.25 canary budget, NaN observations are "no signal" (skipped,
  never drift), thin evidence scores 0.0, and a mismatched bin ladder
  fails loudly (inf drift / raise);
- models/decode.py:quality_vector vs a numpy entropy/margin/repeat
  oracle, including the fully-masked-row degradation contract;
- the engine contract: telemetry OFF leaves outputs bit-identical
  (and ``RequestOutput.quality`` None); telemetry ON changes no
  token while populating per-request quality and the registry
  series; the decode compile count stays pinned at 1 across mixed
  constrained/sampled/plain traffic (RecompileSentinel budget 0);
- the chaos drills: ``quality_drift@N`` moves the PSI score past the
  budget in every family with zero failed requests (control greedy
  tokens bit-unchanged), ``quality_nan@N`` degrades to "no signal"
  without a crash or a drift false-positive;
- EventLog size-based rotation (whole-line generations, atomic
  cascade) and the report tools' ``{"record": "quality"}`` learning.
"""

import json
import math
import os
import sys
from functools import lru_cache
from types import SimpleNamespace

import importlib.util

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.analysis.sanitizers import (
    RecompileSentinel,
)
from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import init_model
from differential_transformer_replication_tpu.models.decode import (
    quality_vector,
)
from differential_transformer_replication_tpu.obs.events import (
    EventLog,
    NOOP_EVENTS,
    open_event_log,
)
from differential_transformer_replication_tpu.obs.quality import (
    ENTROPY_BINS,
    FINGERPRINT_RECORD,
    MARGIN_BINS,
    MIN_DRIFT_COUNT,
    QualityMonitor,
    QuantileSketch,
    build_quality_row,
    drift_score,
    fingerprint,
    load_fingerprint,
    psi,
    save_fingerprint,
)
from differential_transformer_replication_tpu.serving import (
    SamplingParams,
    ServingEngine,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _cfg(kind, vocab=61):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind, vocab=61):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _serving(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("prefill_budget", 6)
    kw.setdefault("quality_telemetry", True)
    return ServingConfig(**kw)


# ---------------------------------------------------------------------
# QuantileSketch
# ---------------------------------------------------------------------


class TestQuantileSketch:
    def test_bucketing_matches_numpy_searchsorted(self):
        rng = np.random.default_rng(0)
        vals = rng.uniform(-1.0, 30.0, size=500)
        sk = QuantileSketch(MARGIN_BINS)
        for v in vals:
            assert sk.add(v)
        # add() places v in the first bucket whose upper bound >= v
        idx = np.searchsorted(np.asarray(MARGIN_BINS), vals, side="left")
        expect = np.bincount(idx, minlength=len(MARGIN_BINS) + 1)
        assert sk.counts == expect.tolist()
        assert sk.total == 500
        assert sk.mean() == pytest.approx(float(vals.mean()))

    def test_non_finite_and_junk_skipped(self):
        sk = QuantileSketch(ENTROPY_BINS)
        assert sk.add(1.0)
        for bad in (float("nan"), float("inf"), float("-inf"),
                    None, "not-a-number"):
            assert not sk.add(bad)
        assert sk.total == 1
        assert sk.mean() == pytest.approx(1.0)

    def test_roundtrip_dict(self):
        sk = QuantileSketch(ENTROPY_BINS)
        for v in (0.01, 0.3, 2.0, 50.0):
            sk.add(v)
        back = QuantileSketch.from_dict(sk.to_dict())
        assert back.counts == sk.counts
        assert back.total == sk.total
        assert back.mean() == pytest.approx(sk.mean())

    def test_from_dict_validates_counts_length(self):
        with pytest.raises(ValueError, match="does not match"):
            QuantileSketch.from_dict({"bins": [1.0, 2.0], "counts": [1, 2]})

    def test_bins_must_strictly_increase(self):
        with pytest.raises(ValueError, match="strictly increasing"):
            QuantileSketch((1.0, 1.0, 2.0))
        with pytest.raises(ValueError, match="strictly increasing"):
            QuantileSketch((2.0, 1.0))


# ---------------------------------------------------------------------
# PSI + drift score
# ---------------------------------------------------------------------


def _sketch_from(vals, bins=ENTROPY_BINS):
    sk = QuantileSketch(bins)
    for v in vals:
        sk.add(v)
    return sk


class TestPsiAndDrift:
    def test_identical_distributions_score_zero(self):
        rng = np.random.default_rng(1)
        vals = rng.uniform(0.0, 8.0, size=400)
        assert psi(_sketch_from(vals), _sketch_from(vals)) == \
            pytest.approx(0.0, abs=1e-12)

    def test_shifted_distribution_crosses_canary_budget(self):
        rng = np.random.default_rng(2)
        ref = _sketch_from(rng.normal(4.5, 0.4, size=600))
        live = _sketch_from(rng.normal(7.0, 0.4, size=600))
        score = psi(ref, live)
        assert score > 0.25  # the "shifted" knee / default budget
        assert math.isfinite(score)

    def test_psi_matches_numpy_oracle(self):
        rng = np.random.default_rng(3)
        ref = _sketch_from(rng.uniform(0, 10, size=300))
        live = _sketch_from(rng.uniform(2, 12, size=250))
        eps = 1e-4
        p = (np.asarray(live.counts) + eps) / (live.total + len(live.counts) * eps)
        q = (np.asarray(ref.counts) + eps) / (ref.total + len(ref.counts) * eps)
        expect = float(np.sum((p - q) * np.log(p / q)))
        assert psi(ref, live) == pytest.approx(expect, rel=1e-12)

    def test_mismatched_ladder_raises(self):
        with pytest.raises(ValueError, match="ladders differ"):
            psi(QuantileSketch(ENTROPY_BINS), QuantileSketch(MARGIN_BINS))

    def test_drift_no_reference_is_zero(self):
        live = {"entropy": _sketch_from(np.full(100, 5.0))}
        assert drift_score(None, live) == 0.0
        assert drift_score({}, live) == 0.0

    def test_drift_thin_evidence_is_zero(self):
        ref = fingerprint({"entropy": _sketch_from(np.full(200, 1.0))})
        live = {"entropy": _sketch_from(np.full(MIN_DRIFT_COUNT - 1, 9.0))}
        assert drift_score(ref, live) == 0.0
        # one more observation and the same shift becomes signal
        live = {"entropy": _sketch_from(np.full(MIN_DRIFT_COUNT, 9.0))}
        assert drift_score(ref, live) > 0.25

    def test_drift_incompatible_ladder_is_inf(self):
        ref = fingerprint({"entropy": _sketch_from(np.full(100, 1.0),
                                                   bins=MARGIN_BINS)})
        live = {"entropy": _sketch_from(np.full(100, 1.0))}
        assert drift_score(ref, live) == math.inf

    def test_drift_takes_worst_signal(self):
        rng = np.random.default_rng(4)
        base_e = rng.normal(4.0, 0.3, size=300)
        base_m = rng.uniform(0.0, 2.0, size=300)
        ref = fingerprint({
            "entropy": _sketch_from(base_e),
            "margin": _sketch_from(base_m, bins=MARGIN_BINS),
        })
        live = {
            "entropy": _sketch_from(base_e),  # unmoved
            "margin": _sketch_from(base_m + 10.0, bins=MARGIN_BINS),
        }
        score = drift_score(ref, live)
        assert score > 0.25
        assert score == pytest.approx(psi(
            QuantileSketch.from_dict(ref["sketches"]["margin"]),
            live["margin"],
        ))


class TestFingerprintIO:
    def test_save_load_roundtrip(self, tmp_path):
        rec = fingerprint(
            {"entropy": _sketch_from([1.0, 2.0, 3.0])},
            meta={"model": "control"},
        )
        path = str(tmp_path / "sub" / "fp.json")
        save_fingerprint(path, rec)
        assert not os.path.exists(path + ".tmp")  # atomic rename landed
        back = load_fingerprint(path)
        assert back["record"] == FINGERPRINT_RECORD
        assert back["meta"] == {"model": "control"}
        assert back["sketches"]["entropy"] == rec["sketches"]["entropy"]

    def test_load_rejects_non_fingerprint(self, tmp_path):
        path = tmp_path / "bogus.json"
        path.write_text('{"record": "quality"}')
        with pytest.raises(ValueError, match="not a quality fingerprint"):
            load_fingerprint(str(path))


class TestQualityMonitor:
    def test_observe_and_no_signal_accounting(self):
        mon = QualityMonitor()
        mon.observe(2.0, 0.5)
        mon.observe(float("nan"), 0.7)   # entropy skipped
        mon.observe(3.0, float("inf"))   # margin skipped
        s = mon.stats()
        assert s["tokens_observed"] == 2
        assert s["no_signal_observations"] == 2
        assert s["entropy_mean"] == pytest.approx(2.5)
        assert s["margin_mean"] == pytest.approx(0.6)
        assert s["drift"] == 0.0  # no reference

    def test_quality_row_shape(self):
        mon = QualityMonitor()
        for _ in range(3):
            mon.observe(1.0, 2.0)
        row = build_quality_row(mon, 7, lambdas={"lambda_l1": 0.123456789})
        assert row["record"] == "quality"
        assert row["iter"] == 7
        assert row["entropy_mean"] == pytest.approx(1.0)
        assert row["lambda_l1"] == pytest.approx(0.123457)  # rounded
        assert json.loads(json.dumps(row)) == row  # JSONL-safe


# ---------------------------------------------------------------------
# quality_vector vs numpy oracle
# ---------------------------------------------------------------------


class TestQualityVector:
    def _oracle(self, lp, proc, tokens, prev):
        p = np.exp(lp)
        plogp = np.where(np.isfinite(lp), p * lp, 0.0)
        entropy = -plogp.sum(-1)
        top2 = np.sort(proc, axis=-1)[..., ::-1][..., :2]
        margin = top2[..., 0] - top2[..., 1]
        repeat = ((tokens == prev) & (prev >= 0)).astype(np.float32)
        return entropy, margin, repeat

    def test_matches_oracle_2d(self):
        rng = np.random.default_rng(5)
        logits = rng.normal(0, 3, size=(6, 40)).astype(np.float32)
        lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        tokens = jnp.asarray([0, 1, 2, 3, 4, 5], jnp.int32)
        prev = jnp.asarray([0, 9, 2, -1, 4, 7], jnp.int32)
        qv = np.asarray(jax.jit(quality_vector)(
            lp, jnp.asarray(logits), tokens, prev
        ))
        assert qv.shape == (6, 3)
        ent, mar, rep = self._oracle(
            np.asarray(lp), logits, np.asarray(tokens), np.asarray(prev)
        )
        np.testing.assert_allclose(qv[:, 0], ent, rtol=1e-5)
        np.testing.assert_allclose(qv[:, 1], mar, rtol=1e-5)
        # prev=-1 means "no previous token": never a repeat, even when
        # tokens coincidentally matches
        np.testing.assert_array_equal(qv[:, 2], rep)
        assert rep.tolist() == [1.0, 0.0, 1.0, 0.0, 1.0, 0.0]

    def test_matches_oracle_3d_spec_shape(self):
        rng = np.random.default_rng(6)
        logits = rng.normal(0, 2, size=(3, 4, 17)).astype(np.float32)
        lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        tokens = jnp.asarray(rng.integers(0, 17, size=(3, 4)), jnp.int32)
        prev = jnp.asarray(rng.integers(-1, 17, size=(3, 4)), jnp.int32)
        qv = np.asarray(quality_vector(lp, jnp.asarray(logits), tokens, prev))
        assert qv.shape == (3, 4, 3)
        ent, mar, rep = self._oracle(
            np.asarray(lp), logits, np.asarray(tokens), np.asarray(prev)
        )
        np.testing.assert_allclose(qv[..., 0], ent, rtol=1e-5)
        np.testing.assert_allclose(qv[..., 1], mar, rtol=1e-5)
        np.testing.assert_array_equal(qv[..., 2], rep)

    def test_single_allowed_token_degrades_not_crashes(self):
        # a constraint mask that leaves ONE legal token: entropy is an
        # exact 0 (the where() keeps 0 * -inf NaN out), margin is +inf
        # (the host's sketch add() skips it as "no signal")
        V = 8
        proc = np.full((2, V), -np.inf, np.float32)
        proc[:, 3] = 1.5
        lp = jax.nn.log_softmax(jnp.asarray(proc), axis=-1)
        qv = np.asarray(quality_vector(
            lp, jnp.asarray(proc),
            jnp.asarray([3, 3], jnp.int32), jnp.asarray([-1, 3], jnp.int32),
        ))
        np.testing.assert_array_equal(qv[:, 0], [0.0, 0.0])
        assert np.isposinf(qv[:, 1]).all()
        np.testing.assert_array_equal(qv[:, 2], [0.0, 1.0])
        assert not QuantileSketch(MARGIN_BINS).add(float(qv[0, 1]))


# ---------------------------------------------------------------------
# engine telemetry
# ---------------------------------------------------------------------


class TestEngineQuality:
    def test_off_by_default(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving(quality_telemetry=False))
        (out,) = eng.generate(_prompts([5], cfg.vocab_size),
                              max_new_tokens=4, temperature=0.0)
        assert out.quality is None
        assert eng.quality_stats() is None
        assert eng.quality_fingerprint() is None
        assert eng.quality_row() is None

    def test_greedy_tokens_bit_identical_on_vs_off(self):
        """The telemetry tail reads the step's arrays; it must never
        change a token. Per-request quality rides the output when on."""
        cfg, params = _setup("control")
        prompts = _prompts([3, 9, 14, 6], cfg.vocab_size)
        off = ServingEngine(params, cfg, _serving(quality_telemetry=False))
        ref = off.generate(prompts, max_new_tokens=8, temperature=0.0)
        on = ServingEngine(params, cfg, _serving())
        outs = on.generate(prompts, max_new_tokens=8, temperature=0.0)
        for a, b in zip(ref, outs):
            assert a.tokens == b.tokens
            assert a.quality is None
            assert b.quality is not None
            assert b.quality["tokens_observed"] == 8
            assert math.isfinite(b.quality["entropy_mean"])
            assert math.isfinite(b.quality["margin_mean"])
            assert b.quality["rep_run_max"] >= 0
        s = on.quality_stats()
        assert s["tokens_observed"] == 8 * len(prompts)
        assert s["no_signal_observations"] == 0
        assert s["drift"] == 0.0
        assert s["constraint_validity_rate"] == 1.0

    @pytest.mark.slow
    def test_sampled_tokens_bit_identical_on_vs_off(self):
        cfg, params = _setup("control")
        prompts = _prompts([4, 7, 11], cfg.vocab_size, seed=8)
        kw = dict(max_new_tokens=6, temperature=1.0, top_k=5, seed=17)
        off = ServingEngine(params, cfg, _serving(quality_telemetry=False))
        on = ServingEngine(params, cfg, _serving())
        for a, b in zip(off.generate(prompts, **kw),
                        on.generate(prompts, **kw)):
            assert a.tokens == b.tokens
            assert b.quality["tokens_observed"] == 6

    def test_registry_series_and_quality_row(self):
        cfg, params = _setup("diff")
        eng = ServingEngine(params, cfg, _serving())
        eng.generate(_prompts([5, 8], cfg.vocab_size),
                     max_new_tokens=6, temperature=0.0)
        expo = eng.registry.render()
        for name in ("serving_token_entropy", "serving_logit_margin",
                     "serving_quality_drift", "serving_lambda_mean"):
            assert name in expo, name
        assert 'serving_lambda_mean{layer="1"}' in expo
        s = eng.quality_stats()
        # layer-1 lambda init schedule: 0.8 - 0.6*exp(0) = 0.2
        assert s["lambda_l1"] == pytest.approx(0.2, abs=1e-6)
        assert s["lambda_l2"] == pytest.approx(0.35551, abs=1e-4)
        row = eng.quality_row()
        assert row["record"] == "quality"
        assert row["lambda_l1"] == pytest.approx(0.2, abs=1e-6)
        assert json.loads(json.dumps(row)) == row

    def test_constrained_run_length_and_validity(self):
        """A forced-repetition constraint pins the host-side run-length
        accumulator exactly, and the one-legal-token margin degrades to
        "no signal" instead of poisoning the sketches."""
        vocab = [chr(i) if 32 <= i < 127 else "" for i in range(128)]
        cfg, params = _setup("control", vocab=128)
        eng = ServingEngine(params, cfg, _serving(num_slots=4), vocab=vocab)
        (out,) = eng.generate(
            [_prompts([5], 128, seed=9)[0]],
            params=[SamplingParams(max_new_tokens=12, temperature=0.0,
                                   seed=0, regex="a{8}")],
        )
        assert out.tokens == [ord("a")] * 8
        assert out.finish_reason == "constraint_complete"
        # 8 identical tokens = 7 consecutive repeat flags
        assert out.quality["rep_run_max"] == 7
        assert out.quality["entropy_mean"] == pytest.approx(0.0, abs=1e-6)
        s = eng.quality_stats()
        assert s["constraint_validity_rate"] == 1.0
        assert s["no_signal_observations"] > 0  # inf margins skipped

    def test_decode_compile_pinned_with_quality_mixed_traffic(self):
        """Quality telemetry rides the SAME jitted step: after one
        warming batch, mixed constrained/sampled/plain traffic compiles
        nothing new and the decode cache stays at one entry."""
        vocab = [chr(i) if 32 <= i < 127 else "" for i in range(128)]
        cfg, params = _setup("control", vocab=128)
        eng = ServingEngine(params, cfg, _serving(num_slots=4), vocab=vocab)
        warm = _prompts([4, 7, 5], 128, seed=10)
        eng.generate(
            warm,
            params=[
                SamplingParams(max_new_tokens=6, temperature=0.0, seed=0,
                               regex="(ab|ba){1,4}"),
                SamplingParams(max_new_tokens=6, temperature=1.0, top_k=5,
                               seed=1),
                SamplingParams(max_new_tokens=6, temperature=0.0, seed=2),
            ],
        )
        baseline = eng.compile_stats()
        assert baseline["decode"] == 1
        with RecompileSentinel(budget=0, name="quality-mixed"):
            outs = eng.generate(
                _prompts([6, 3, 8, 5], 128, seed=11),
                params=[
                    SamplingParams(max_new_tokens=5, temperature=0.0,
                                   seed=3, regex="[xy]{2,6}"),
                    SamplingParams(max_new_tokens=5, temperature=1.0,
                                   top_k=3, seed=4),
                    SamplingParams(max_new_tokens=5, temperature=0.0,
                                   seed=5),
                    SamplingParams(max_new_tokens=5, temperature=0.7,
                                   seed=6),
                ],
            )
        assert len(outs) == 4
        assert all(o.quality is not None for o in outs)
        assert eng.compile_stats() == baseline

    @pytest.mark.slow
    def test_spec_engine_quality_parity_and_acceptance(self):
        cfg, params = _setup("control")
        prompts = _prompts([4, 9, 6], cfg.vocab_size, seed=12)
        plain = ServingEngine(params, cfg, _serving())
        ref = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        spec = ServingEngine(
            params, cfg, _serving(spec_mode="ngram", spec_draft_len=3)
        )
        outs = spec.generate(prompts, max_new_tokens=8, temperature=0.0)
        for a, b in zip(ref, outs):
            assert a.tokens == b.tokens  # spec greedy == non-spec greedy
            assert b.quality is not None
            assert b.quality["tokens_observed"] == 8
            if "spec_acceptance" in b.quality:
                assert 0.0 <= b.quality["spec_acceptance"] <= 1.0
        s = spec.quality_stats()
        assert s["tokens_observed"] == 8 * len(prompts)
        if spec.stats["spec_proposed"]:
            assert 0.0 <= s["spec_acceptance_rate"] <= 1.0

    def test_quality_nan_fault_degrades_to_no_signal(self):
        cfg, params = _setup("control")
        faults.arm("quality_nan@1")
        eng = ServingEngine(params, cfg, _serving())
        outs = eng.generate(_prompts([3, 6], cfg.vocab_size, seed=13),
                            max_new_tokens=6, temperature=0.0)
        assert all(o.finish_reason == "length" for o in outs)
        s = eng.quality_stats()
        assert s["no_signal_observations"] > 0
        assert s["drift"] == 0.0  # poisoned telemetry is not drift
        assert s["tokens_observed"] < 12  # the NaN iteration was skipped

    @pytest.mark.parametrize("kind", [
        "control",
        pytest.param("diff", marks=pytest.mark.slow),
        pytest.param("ndiff", marks=pytest.mark.slow),
    ])
    def test_quality_drift_fault_trips_fingerprint(self, kind, tmp_path):
        """The silent-drift chaos drill: requests keep finishing, greedy
        control tokens stay bit-identical (argmax-preserving rescale),
        and ONLY the PSI score vs the recorded fingerprint convicts —
        past the 0.25 default canary budget in every family."""
        cfg, params = _setup(kind)
        prompts = _prompts([3, 9, 14, 6, 11, 7], cfg.vocab_size)
        clean = ServingEngine(params, cfg, _serving())
        ref = clean.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert clean.quality_stats()["tokens_observed"] >= MIN_DRIFT_COUNT
        fp = str(tmp_path / "fp.json")
        save_fingerprint(fp, clean.quality_fingerprint(
            meta={"model": kind}
        ))

        faults.arm("quality_drift@1")
        eng = ServingEngine(params, cfg, _serving(quality_fingerprint=fp))
        outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert all(o.finish_reason == "length" for o in outs)
        s = eng.quality_stats()
        assert s["drift"] > 0.25, s
        assert math.isfinite(s["drift"])
        expo = eng.registry.render()
        assert "serving_quality_drift" in expo
        if kind == "control":
            # lm_head rescale preserves the argmax: same greedy tokens
            for a, b in zip(ref, outs):
                assert a.tokens == b.tokens
        elif kind == "diff":
            # the λ collapse is the fault's visible gauge signature
            assert s["lambda_l1"] > 1.0
        else:
            # ndiff's layer mean cancels (t0 +δ, t1 -δ via the shared
            # subtracted exponential); the per-term row shows the shift
            assert s["lambda_l1_t0"] > 1.0

    def test_fingerprint_survives_engine_roundtrip(self, tmp_path):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving())
        eng.generate(_prompts([5, 8, 12], cfg.vocab_size, seed=14),
                     max_new_tokens=8, temperature=0.0)
        fp = str(tmp_path / "fp.json")
        save_fingerprint(fp, eng.quality_fingerprint(meta={"m": 1}))
        # identical traffic against its own fingerprint: drift ~ 0
        again = ServingEngine(params, cfg, _serving(quality_fingerprint=fp))
        again.generate(_prompts([5, 8, 12], cfg.vocab_size, seed=14),
                       max_new_tokens=8, temperature=0.0)
        assert again.quality_stats()["drift"] == pytest.approx(0.0, abs=1e-9)


# ---------------------------------------------------------------------
# EventLog rotation
# ---------------------------------------------------------------------


def _lines(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh.read().splitlines() if ln]


class TestEventLogRotation:
    def test_no_rotation_by_default(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = EventLog(path, process="t", flush_every=1)
        for i in range(50):
            log.emit("tick", i=i)
        log.close()
        assert len(_lines(path)) == 50
        assert not os.path.exists(path + ".1")

    def test_rotation_cascade_whole_lines(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = EventLog(path, process="t", flush_every=1,
                       max_bytes=256, keep=2)
        for i in range(60):
            log.emit("tick", i=i, pad="x" * 16)
        log.close()
        assert os.path.exists(path + ".1")
        assert os.path.exists(path + ".2")
        assert not os.path.exists(path + ".3")  # oldest fell off
        seen = []
        for p in (path + ".2", path + ".1", path):
            recs = _lines(p)  # every generation parses whole-line clean
            assert all(r["event"] == "tick" for r in recs)
            seen.extend(r["i"] for r in recs)
        # the retained tail is contiguous and ends at the last emit
        assert seen == list(range(seen[0], 60))

    def test_keep_zero_truncates(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = EventLog(path, flush_every=1, max_bytes=128, keep=0)
        for i in range(40):
            log.emit("tick", i=i)
        log.close()
        assert not os.path.exists(path + ".1")
        recs = _lines(path)  # only the newest tail survives
        assert len(recs) < 40
        assert recs[-1]["i"] == 39 if recs else True

    def test_rotation_batches_flush_boundary(self, tmp_path):
        # flush_every > 1: rotation happens only at flush boundaries,
        # so a burst smaller than the buffer never splits mid-line
        path = str(tmp_path / "events.jsonl")
        log = EventLog(path, flush_every=8, max_bytes=64, keep=3)
        for i in range(8):
            log.emit("tick", i=i)
        log.close()
        total = sum(
            len(_lines(p)) for p in
            (path, path + ".1", path + ".2", path + ".3")
            if os.path.exists(p)
        )
        assert total == 8

    def test_invalid_params_raise(self, tmp_path):
        with pytest.raises(ValueError):
            EventLog(str(tmp_path / "e.jsonl"), max_bytes=-1)
        with pytest.raises(ValueError):
            EventLog(str(tmp_path / "e.jsonl"), keep=-1)

    def test_open_event_log_passthrough(self, tmp_path):
        assert open_event_log(None) is NOOP_EVENTS
        log = open_event_log(str(tmp_path / "e.jsonl"), process="x",
                             max_bytes=1024, keep=5)
        assert log.max_bytes == 1024
        assert log.keep == 5
        log.close()


# ---------------------------------------------------------------------
# report tools learn {"record": "quality"} rows
# ---------------------------------------------------------------------


def _check_args(**kw):
    base = dict(require_loss_decrease=False, max_stall_frac=0.9,
                max_skipped=0, max_rollbacks=0, max_compile_events=0,
                max_capture_failures=0, max_drift=0.0)
    base.update(kw)
    return SimpleNamespace(**base)


class TestReportToolsQuality:
    def _stream(self, tmp_path, drifts):
        path = tmp_path / "metrics.jsonl"
        rows = [
            {"record": "run_header", "config_hash": "abc"},
            {"loss": 3.0, "step_time_ms": 10.0},
            {"loss": 2.5, "step_time_ms": 10.0},
        ]
        for i, d in enumerate(drifts):
            rows.append({
                "record": "quality", "iter": 10 * (i + 1),
                "entropy_mean": 4.0 + i, "margin_mean": 0.5,
                "drift": d, "lambda_l1": 0.2 + i * 0.01,
                "lambda_init_l1": 0.2,
            })
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        return str(path)

    def test_metrics_report_summarizes_and_gates_drift(self, tmp_path):
        mr = _load_tool("metrics_report")
        path = self._stream(tmp_path, [0.05, 0.31, float("nan")])
        summary = mr.summarize(mr.load(path))
        assert summary["quality_records"] == 3
        assert summary["quality_drift_max"] == pytest.approx(0.31)
        assert summary["quality_entropy_mean_last"] == pytest.approx(6.0)
        assert "quality" not in summary.get("unknown_records", {})
        assert mr.check(summary, _check_args()) == []  # gate off
        bad = mr.check(summary, _check_args(max_drift=0.25))
        assert any("quality drift" in b for b in bad)
        assert mr.check(summary, _check_args(max_drift=0.5)) == []

    def test_lambda_report_serving_rows_need_flag(self, tmp_path):
        lr = _load_tool("lambda_report")
        path = self._stream(tmp_path, [0.01, 0.02])
        series, inits = lr.load_series(path)  # default: training rows only
        assert series == {}
        series, inits = lr.load_series(
            path, records=("introspection", "quality")
        )
        assert (1, None) in series
        assert [v for _, v in sorted(series[(1, None)])] == \
            pytest.approx([0.2, 0.21])
        assert inits[(1, None)] == pytest.approx(0.2)
        # mixed stream: a training introspection row rides alongside
        with open(path, "a") as fh:
            fh.write(json.dumps({
                "record": "introspection", "iter": 5, "lambda_l1": 0.19,
            }) + "\n")
        series, _ = lr.load_series(
            path, records=("introspection", "quality")
        )
        assert len(series[(1, None)]) == 3
