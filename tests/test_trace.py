"""End-to-end request tracing, fleet metrics aggregation, SLO gating
(ISSUE 7).

The load-bearing contracts:

- traceparent mint/parse round-trips, and anything malformed degrades
  into a fresh trace (never a failed request);
- spans emitted under a trace context carry ``trace_id``/``span_id``/
  ``parent_id`` args, and ONE ``trace_id`` links two in-process hops
  (a router-side forward span and the engine's request span parented
  to it) across two separate trace files — what tools/trace_stitch.py
  merges into one timeline;
- the engine stamps per-request lifecycle (admit / first_token /
  finish instants, a submit→finish ``request`` span) without touching
  its jitted closures: the decode compile count is PINNED at 1 with
  tracing and per-request trace contexts on;
- ``/fleet/metrics`` aggregation sums counters/histograms across
  replica bodies and labels gauges per replica, one TYPE per name;
- SLO burn-rate math matches hand-computed histograms, conservatively
  at non-bucket-edge thresholds;
- the structured event log records request/replica events with trace
  ids, append-mode, crash-tolerant;
- tools/slo_report.py and tools/trace_stitch.py gate/stitch from the
  command line (subprocess, like the other tool tests).

Quick tier throughout, except the slow fleet chaos test at the bottom:
a fault-injected retried request over a real 2-replica fleet whose
three trace files stitch into one validated timeline.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from functools import lru_cache
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    RouterConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import init_model
from differential_transformer_replication_tpu.obs import (
    EventLog,
    NOOP_EVENTS,
    Registry,
    SpanTracer,
    set_build_info,
)
from differential_transformer_replication_tpu.obs import trace as trace_mod
from differential_transformer_replication_tpu.obs.slo import (
    AvailabilityObjective,
    LatencyObjective,
    SLOMonitor,
    burn_rate,
    histogram_from_samples,
    latency_error_ratio,
)
from differential_transformer_replication_tpu.serving import (
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.serving.router import (
    Router,
    aggregate_fleet_metrics,
    serve_router,
)
from differential_transformer_replication_tpu.utils import faults

from test_obs import assert_histogram_valid, parse_exposition

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind="control", vocab=61):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind="control", vocab=61):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


# -- traceparent mint/parse ---------------------------------------------


class TestTraceContext:
    def test_mint_parse_round_trip(self):
        ctx = trace_mod.mint()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        parsed = trace_mod.parse_traceparent(ctx.to_traceparent())
        assert parsed == ctx

    def test_child_keeps_trace_changes_span(self):
        ctx = trace_mod.mint()
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id

    @pytest.mark.parametrize("bad", [
        None, 42, "", "nonsense", "00-zz-bb-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",   # short trace
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",   # short span
        "00-" + "a" * 32 + "-" + "b" * 16,            # missing flags
    ])
    def test_malformed_parses_to_none(self, bad):
        assert trace_mod.parse_traceparent(bad) is None

    def test_from_payload_mints_or_parses(self):
        ctx = trace_mod.mint()
        got = trace_mod.from_payload(
            {"traceparent": ctx.to_traceparent()}
        )
        assert got == ctx
        minted = trace_mod.from_payload({"traceparent": "garbage"})
        assert minted is not None and minted.trace_id != ctx.trace_id
        assert trace_mod.from_payload({}, mint_if_absent=False) is None

    def test_mint_is_unique(self):
        assert len({trace_mod.mint_trace_id() for _ in range(100)}) == 100


# -- parented spans across two in-process hops --------------------------


def test_parented_spans_link_two_hops_across_trace_files(tmp_path):
    """Hop 1 (a router) emits a ``forward`` span and serializes its
    child context to the wire; hop 2 (a replica) parses it and emits a
    ``request`` span. Both files are valid Chrome traces, share ONE
    trace_id, and the replica span's parent_id equals the forward
    span's span_id — the exact join trace_stitch aligns on."""
    router_path = str(tmp_path / "router.trace.json")
    replica_path = str(tmp_path / "replica.trace.json")
    t_router = SpanTracer(router_path, process_name="router")
    t_replica = SpanTracer(replica_path, process_name="replica")

    root = trace_mod.mint()
    fwd = root.child()
    wire = None
    with t_router.span("forward", replica="r0",
                       trace_id=root.trace_id, span_id=fwd.span_id,
                       parent_id=root.span_id):
        wire = fwd.to_traceparent()
        # hop 2: the "replica" parses the wire context
        ctx = trace_mod.parse_traceparent(wire)
        args = trace_mod.child_span_args(ctx)
        with t_replica.span("request", rid=0, **args):
            time.sleep(0.001)
    t_router.close()
    t_replica.close()

    router_events = json.load(open(router_path))
    replica_events = json.load(open(replica_path))
    fwd_ev = next(e for e in router_events
                  if e.get("name") == "forward")
    req_ev = next(e for e in replica_events
                  if e.get("name") == "request")
    assert fwd_ev["args"]["trace_id"] == root.trace_id
    assert req_ev["args"]["trace_id"] == root.trace_id
    # the replica hop parents to the forward hop's span id
    assert req_ev["args"]["parent_id"] == fwd_ev["args"]["span_id"]
    assert req_ev["args"]["span_id"] != fwd_ev["args"]["span_id"]


def test_noop_tracer_accepts_trace_calls():
    from differential_transformer_replication_tpu.obs import NOOP_TRACER

    ctx = trace_mod.mint()
    with NOOP_TRACER.span("x", **trace_mod.child_span_args(ctx)):
        pass
    NOOP_TRACER.complete("request", 0.0, 1.0,
                         **trace_mod.child_span_args(ctx))
    NOOP_TRACER.instant("admit", **trace_mod.instant_args(ctx))


# -- engine lifecycle stamping ------------------------------------------


def test_engine_stamps_request_lifecycle_with_trace(tmp_path):
    cfg, params = _setup("control")
    path = str(tmp_path / "engine.trace.json")
    tracer = SpanTracer(path, process_name="engine")
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
        tracer=tracer,
    )
    ctx = trace_mod.mint()
    rid = eng.submit(_prompts([5], cfg.vocab_size)[0],
                     max_new_tokens=3, trace=ctx)
    outs = eng.run()
    tracer.close()
    assert outs[0].trace_id == ctx.trace_id

    events = json.load(open(path))
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    admit = by_name["admit"][0]
    first = by_name["first_token"][0]
    finish = by_name["finish"][0]
    request = by_name["request"][0]
    for ev in (admit, first, finish, request):
        assert ev["args"]["trace_id"] == ctx.trace_id
        assert ev["args"]["rid"] == rid
    # lifecycle instants hang off the caller's hop; the request span
    # is a child of it
    assert request["args"]["parent_id"] == ctx.span_id
    assert request["ph"] == "X" and request["dur"] > 0
    assert finish["args"]["reason"] == "length"
    # the batched decode span names the traces it advanced
    decode = by_name["decode"]
    assert any(
        ctx.trace_id in (e["args"].get("trace_ids") or [])
        for e in decode
    )


def test_untraced_requests_emit_lifecycle_without_trace_args(tmp_path):
    cfg, params = _setup("control")
    path = str(tmp_path / "e2.trace.json")
    tracer = SpanTracer(path)
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
        tracer=tracer,
    )
    eng.submit(_prompts([4], cfg.vocab_size)[0], max_new_tokens=2)
    outs = eng.run()
    tracer.close()
    assert outs[0].trace_id is None
    events = json.load(open(path))
    req = next(e for e in events if e["name"] == "request")
    assert "trace_id" not in req["args"]


def test_tracing_with_trace_contexts_adds_zero_recompiles():
    """THE compile pin: trace stamping is host-side strings — decode
    compiles once whether requests are traced, untraced, or the tracer
    is off (the train-step twin is pinned in test_obs.py, which runs a
    traced trainer and asserts compile_events == 1)."""
    cfg, params = _setup("control", vocab=47)  # fresh compile-cache key
    serving = ServingConfig(num_slots=2, prefill_chunk=8,
                            prefill_budget=16)
    eng = ServingEngine(params, cfg, serving)
    eng.generate(_prompts([3, 9], cfg.vocab_size), max_new_tokens=3,
                 temperature=0.0)
    baseline = eng.compile_stats()
    assert baseline["decode"] == 1

    class _Sink:
        def span(self, name, **a):
            from differential_transformer_replication_tpu.obs.spans import (
                _NOOP_SPAN,
            )
            return _NOOP_SPAN

        def instant(self, *a, **k):
            pass

        def complete(self, *a, **k):
            pass

        counter = flush = close = instant

    eng2 = ServingEngine(params, cfg, serving, tracer=_Sink())
    # same prompt SHAPES as the baseline run — only the trace contexts
    # differ, and they must not add a single cache entry
    for i, p in enumerate(_prompts([3, 9], cfg.vocab_size)):
        eng2.submit(p, max_new_tokens=3,
                    trace=trace_mod.mint() if i % 2 == 0 else None)
    outs = eng2.run()
    assert len(outs) == 2
    assert eng2.compile_stats() == baseline  # zero new compiles


# -- server + router HTTP propagation -----------------------------------


class _EchoReplica(BaseHTTPRequestHandler):
    """Canned replica recording each request body; replies 200 with
    the received traceparent echoed."""

    received = None  # list, set per subclass
    script = None    # optional list of (status, body) before the 200s

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        payload = json.loads(self.rfile.read(n) or b"{}")
        self.received.append(payload)
        if self.script:
            status, body = self.script.pop(0)
        else:
            status, body = 200, {
                "request_id": 1, "prompt_ids": [1], "tokens": [2, 3],
                "finish_reason": "length", "ttft_ms": 1.0,
                "echo_traceparent": payload.get("traceparent"),
            }
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


def _echo_server(script=None):
    received = []
    handler = type("H", (_EchoReplica,),
                   {"received": received,
                    "script": list(script) if script else None})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}", received


def _router_cfg(**kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_backoff_s", 0.05)
    kw.setdefault("retry_base_s", 0.001)
    kw.setdefault("retry_cap_s", 0.01)
    kw.setdefault("wait_for_replica_s", 0.0)
    return RouterConfig(**kw)


def _mark_up(*replicas):
    for r in replicas:
        r.note_probe_success(True, "healthy", {}, now=0.0)


class TestRouterTracePropagation:
    def test_router_mints_propagates_and_stamps(self, tmp_path):
        httpd, url, received = _echo_server()
        trace_path = str(tmp_path / "router.trace.json")
        events_path = str(tmp_path / "router.events.jsonl")
        router = Router(
            [url], _router_cfg(),
            tracer=SpanTracer(trace_path, process_name="router"),
            events=EventLog(events_path, process="router"),
        )
        _mark_up(*router.replicas)
        try:
            status, body, headers = router.handle_generate(
                {"prompt_ids": [1]}
            )
            assert status == 200
            # reply carries the minted trace id; the forwarded payload
            # carried a traceparent of the SAME trace, different span
            tid = body["trace_id"]
            assert len(tid) == 32
            fwd = trace_mod.parse_traceparent(
                received[0]["traceparent"]
            )
            assert fwd.trace_id == tid
        finally:
            router.tracer.close()
            router.events.close()
            httpd.shutdown()
            httpd.server_close()

        events = json.load(open(trace_path))
        names = {e["name"] for e in events if e["ph"] in ("X", "i")}
        assert {"pick", "forward"} <= names
        fwd_ev = next(e for e in events if e["name"] == "forward")
        assert fwd_ev["args"]["trace_id"] == tid
        # the traceparent the replica saw IS the forward span's id —
        # replica spans will parent to it in the stitched timeline
        assert fwd_ev["args"]["span_id"] == fwd.span_id
        log = [json.loads(l) for l in open(events_path)]
        fin = next(e for e in log if e["event"] == "request_finished")
        assert fin["trace_id"] == tid and fin["process"] == "router"

    def test_client_supplied_traceparent_is_honored(self):
        httpd, url, received = _echo_server()
        router = Router([url], _router_cfg())
        _mark_up(*router.replicas)
        try:
            ctx = trace_mod.mint()
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "traceparent": ctx.to_traceparent()}
            )
            assert status == 200
            assert body["trace_id"] == ctx.trace_id
            fwd = trace_mod.parse_traceparent(
                received[0]["traceparent"]
            )
            assert fwd.trace_id == ctx.trace_id
            assert fwd.span_id != ctx.span_id  # a child hop, not a copy
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_retry_keeps_one_trace_and_logs_events(self, tmp_path):
        """A failed-over request: both attempts carry the SAME
        trace_id on DIFFERENT forward hops, the trace file shows the
        retry instant, the event log shows request_retried."""
        ha, url_a, rec_a = _echo_server(
            script=[(503, {"code": "queue_full"})]
        )
        hb, url_b, rec_b = _echo_server()
        trace_path = str(tmp_path / "r.trace.json")
        events_path = str(tmp_path / "r.events.jsonl")
        router = Router(
            [url_a, url_b], _router_cfg(),
            tracer=SpanTracer(trace_path),
            events=EventLog(events_path, process="router"),
        )
        _mark_up(*router.replicas)
        try:
            router._affinity["s"] = router.replicas[0]
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200 and body["attempts"] == 2
            tid = body["trace_id"]
            fwd_a = trace_mod.parse_traceparent(
                rec_a[0]["traceparent"]
            )
            fwd_b = trace_mod.parse_traceparent(
                rec_b[0]["traceparent"]
            )
            assert fwd_a.trace_id == tid == fwd_b.trace_id
            assert fwd_a.span_id != fwd_b.span_id
        finally:
            router.tracer.close()
            router.events.close()
            for h in (ha, hb):
                h.shutdown()
                h.server_close()
        trace = json.load(open(trace_path))
        retry = [e for e in trace if e["name"] == "retry"]
        assert retry and retry[0]["args"]["trace_id"] == tid
        forwards = [e for e in trace if e["name"] == "forward"]
        assert len(forwards) == 2
        log = [json.loads(l) for l in open(events_path)]
        retried = next(
            e for e in log if e["event"] == "request_retried"
        )
        assert retried["trace_id"] == tid
        assert retried["code"] == "queue_full"


def test_server_round_trip_emits_trace_and_events(tmp_path):
    """Replica server end to end: a posted traceparent reaches the
    engine, the reply echoes its trace_id, the trace file carries the
    parented request span, the event log records received+finished."""
    cfg, params = _setup("control")
    trace_path = str(tmp_path / "replica.trace.json")
    events_path = str(tmp_path / "replica.events.jsonl")
    tracer = SpanTracer(trace_path, process_name="replica")
    events = EventLog(events_path, process="replica")
    client = ServingClient(ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
        tracer=tracer,
    ))
    httpd = serve(client, port=0, events=events)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    ctx = trace_mod.mint()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({
                "prompt_ids": _prompts([5], cfg.vocab_size)[0],
                "max_new_tokens": 3, "temperature": 0.0,
                "traceparent": ctx.to_traceparent(),
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            body = json.load(r)
        assert body["trace_id"] == ctx.trace_id
        # an untraced request still gets a trace id (server-minted)
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({
                "prompt_ids": _prompts([4], cfg.vocab_size)[0],
                "max_new_tokens": 2, "temperature": 0.0,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2, timeout=120) as r:
            body2 = json.load(r)
        assert len(body2["trace_id"]) == 32
        assert body2["trace_id"] != ctx.trace_id
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.close()
        tracer.close()
        events.close()
    trace = json.load(open(trace_path))
    req_span = next(
        e for e in trace
        if e["name"] == "request"
        and e.get("args", {}).get("trace_id") == ctx.trace_id
    )
    assert req_span["args"]["parent_id"] == ctx.span_id
    log = [json.loads(l) for l in open(events_path)]
    kinds = [e["event"] for e in log]
    assert kinds.count("request_received") == 2
    assert kinds.count("request_finished") == 2
    fin = next(e for e in log if e["event"] == "request_finished")
    assert fin["trace_id"] == ctx.trace_id


# -- /fleet/metrics aggregation -----------------------------------------


_REPLICA_BODY_A = """\
# HELP serving_requests_completed_total Requests finished normally.
# TYPE serving_requests_completed_total counter
serving_requests_completed_total 10
# TYPE serving_requests_finished_total counter
serving_requests_finished_total{reason="length"} 8
serving_requests_finished_total{reason="eos"} 2
# TYPE serving_slot_occupancy gauge
serving_slot_occupancy 2
# TYPE serving_ttft_seconds histogram
serving_ttft_seconds_bucket{le="0.1"} 4
serving_ttft_seconds_bucket{le="1"} 9
serving_ttft_seconds_bucket{le="+Inf"} 10
serving_ttft_seconds_sum 3.5
serving_ttft_seconds_count 10
"""

_REPLICA_BODY_B = """\
# TYPE serving_requests_completed_total counter
serving_requests_completed_total 30
# TYPE serving_requests_finished_total counter
serving_requests_finished_total{reason="length"} 30
# TYPE serving_slot_occupancy gauge
serving_slot_occupancy 4
# TYPE serving_ttft_seconds histogram
serving_ttft_seconds_bucket{le="0.1"} 10
serving_ttft_seconds_bucket{le="1"} 25
serving_ttft_seconds_bucket{le="+Inf"} 30
serving_ttft_seconds_sum 12.5
serving_ttft_seconds_count 30
"""


class TestFleetMetricsAggregation:
    def test_counters_sum_gauges_get_replica_labels(self):
        text = aggregate_fleet_metrics({
            "a:8101": _REPLICA_BODY_A, "b:8102": _REPLICA_BODY_B,
        })
        types, samples = parse_exposition(text)  # oracle: must parse
        vals = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
        # counters sum across replicas, per label set
        assert vals[("serving_requests_completed_total", ())] == 40
        assert vals[("serving_requests_finished_total",
                     (("reason", "length"),))] == 38
        assert vals[("serving_requests_finished_total",
                     (("reason", "eos"),))] == 2
        # gauges keep per-replica identity
        assert vals[("serving_slot_occupancy",
                     (("replica", "a:8101"),))] == 2
        assert vals[("serving_slot_occupancy",
                     (("replica", "b:8102"),))] == 4
        # histograms sum per bucket and stay valid histograms
        assert types["serving_ttft_seconds"] == "histogram"
        assert_histogram_valid(samples, "serving_ttft_seconds")
        assert vals[("serving_ttft_seconds_bucket",
                     (("le", "0.1"),))] == 14
        assert vals[("serving_ttft_seconds_count", ())] == 40
        assert vals[("serving_ttft_seconds_sum", ())] == 16.0
        # exactly one TYPE line per family
        assert text.count("# TYPE serving_ttft_seconds ") == 1

    def test_own_metrics_pass_through_and_merge_types(self):
        own = (
            "# TYPE router_requests_total counter\n"
            'router_requests_total{replica="a:8101"} 7\n'
            "# TYPE build_info gauge\n"
            'build_info{role="router"} 1\n'
        )
        body = (
            "# TYPE build_info gauge\n"
            'build_info{role="replica"} 1\n'
        )
        text = aggregate_fleet_metrics({"a:8101": body}, own=own)
        types, samples = parse_exposition(text)
        assert text.count("# TYPE build_info ") == 1
        roles = {
            (l.get("role"), l.get("replica"))
            for n, l, v in samples if n == "build_info"
        }
        assert roles == {("router", None), ("replica", "a:8101")}
        vals = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
        assert vals[("router_requests_total",
                     (("replica", "a:8101"),))] == 7

    def test_router_http_fleet_metrics_endpoint(self):
        router = Router(
            ["http://127.0.0.1:19101", "http://127.0.0.1:19102"],
            _router_cfg(),
        )
        a, b = router.replicas
        _mark_up(a, b)
        with a.lock:
            a.metrics_text = _REPLICA_BODY_A
        with b.lock:
            b.metrics_text = _REPLICA_BODY_B
        httpd = serve_router(router, port=0)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            with urllib.request.urlopen(
                url + "/fleet/metrics", timeout=30
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            types, samples = parse_exposition(text)
            vals = {n: v for n, l, v in samples if not l}
            # fleet-wide sum from canned replica bodies
            assert vals["serving_requests_completed_total"] == 40
            # the router's own metrics ride along...
            assert "router_replicas" in types
            # ...as does its build_info identity and the synthesized
            # per-replica up gauge
            assert types["build_info"] == "gauge"
            assert any(
                n == "build_info" and l.get("role") == "router"
                for n, l, v in samples
            )
            ups = {
                l["replica"]: v for n, l, v in samples
                if n == "fleet_replica_up"
            }
            assert set(ups) == {a.name, b.name}
            assert all(v == 1 for v in ups.values())
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_build_info_gauge_renders_through_oracle(self):
        reg = Registry()
        set_build_info(reg, role="replica", config_hash="abc123",
                       version="0.4.37")
        types, samples = parse_exposition(reg.render())
        assert types["build_info"] == "gauge"
        assert types["process_start_time_seconds"] == "gauge"
        info = next(l for n, l, v in samples if n == "build_info")
        assert info == {"role": "replica", "config_hash": "abc123",
                        "jax_version": "0.4.37"}
        start = next(
            v for n, l, v in samples
            if n == "process_start_time_seconds"
        )
        assert abs(start - time.time()) < 60


# -- SLO burn-rate math -------------------------------------------------


class TestSLOMath:
    # hand-computed: bounds (0.1, 0.5, 1.0), cumulative (60, 90, 99),
    # count 100 -> 1 observation above 1.0, 10 above 0.5, 40 above 0.1
    BOUNDS = (0.1, 0.5, 1.0)
    CUM = (60, 90, 99)

    def test_error_ratio_at_bucket_edges(self):
        assert latency_error_ratio(
            self.BOUNDS, self.CUM, 100, 0.5
        ) == pytest.approx(0.10)
        assert latency_error_ratio(
            self.BOUNDS, self.CUM, 100, 1.0
        ) == pytest.approx(0.01)
        assert latency_error_ratio(
            self.BOUNDS, self.CUM, 100, 0.1
        ) == pytest.approx(0.40)

    def test_threshold_between_edges_rounds_conservatively(self):
        # 0.75 sits between 0.5 and 1.0: only <=0.5 is provably good
        assert latency_error_ratio(
            self.BOUNDS, self.CUM, 100, 0.75
        ) == pytest.approx(0.10)
        # below every bound: nothing provably good
        assert latency_error_ratio(
            self.BOUNDS, self.CUM, 100, 0.05
        ) == pytest.approx(1.0)

    def test_burn_rate_math(self):
        # 10% errors against a 99% target = 10x budget burn
        assert burn_rate(0.10, 0.99) == pytest.approx(10.0)
        assert burn_rate(0.01, 0.99) == pytest.approx(1.0)
        assert burn_rate(0.0, 0.99) == 0.0
        assert burn_rate(None, 0.99) is None
        assert latency_error_ratio(self.BOUNDS, self.CUM, 0, 1.0) is None

    def test_monitor_evaluates_against_live_registry(self):
        reg = Registry()
        h = reg.histogram("ttft_seconds", "", buckets=(0.1, 0.5, 1.0))
        # 8 fast, 2 slow -> 20% above 0.5
        for _ in range(8):
            h.observe(0.05)
        for _ in range(2):
            h.observe(0.7)
        reg.counter("ok_total", "").inc(99)
        reg.counter("bad_total", "").inc(1)
        mon = SLOMonitor(
            reg,
            latency=[LatencyObjective("ttft", "ttft_seconds", 0.5, 0.9)],
            availability=[AvailabilityObjective(
                "availability", good=("ok_total",), bad=("bad_total",),
                target=0.99,
            )],
        )
        out = mon.evaluate()
        assert out["ttft"]["error_ratio"] == pytest.approx(0.2)
        assert out["ttft"]["burn_rate"] == pytest.approx(2.0)
        assert out["availability"]["error_ratio"] == pytest.approx(0.01)
        assert out["availability"]["burn_rate"] == pytest.approx(1.0)
        # results are re-exposed as gauges in the SAME registry
        types, samples = parse_exposition(reg.render())
        vals = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
        assert vals[("slo_burn_rate",
                     (("objective", "ttft"),))] == pytest.approx(2.0)
        assert vals[("slo_target",
                     (("objective", "availability"),))] == 0.99
        # windowed burn: a clean second window reports zero burn even
        # though the lifetime ratio stays dirty
        for _ in range(10):
            h.observe(0.05)
        out2 = mon.evaluate()
        assert out2["ttft"]["window_error_ratio"] == pytest.approx(0.0)
        assert out2["ttft"]["error_ratio"] == pytest.approx(0.1)

    def test_histogram_from_samples_round_trips_exposition(self):
        reg = Registry()
        h = reg.histogram("x_seconds", "", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        _, samples = parse_exposition(reg.render())
        bounds, cumulative, count = histogram_from_samples(
            samples, "x_seconds"
        )
        assert bounds == [0.1, 1.0]
        assert cumulative == [1, 2]
        assert count == 3
        assert latency_error_ratio(
            bounds, cumulative, count, 1.0
        ) == pytest.approx(1 / 3)

    def test_histogram_from_samples_sums_labeled_children(self):
        """A labeled histogram (two replicas' worth of children) must
        aggregate to ONE valid histogram — per-bound sums and a summed
        count — not interleave the children's ladders."""
        reg = Registry()
        h = reg.histogram("y_seconds", "", labelnames=("replica",),
                          buckets=(0.5,))
        for v in (0.1, 0.1, 0.1, 9.0):       # a: 3 fast, 1 slow
            h.observe(v, replica="a")
        for v in (0.1, 0.1, 9.0, 9.0, 9.0, 9.0):  # b: 2 fast, 4 slow
            h.observe(v, replica="b")
        _, samples = parse_exposition(reg.render())
        bounds, cumulative, count = histogram_from_samples(
            samples, "y_seconds"
        )
        assert bounds == [0.5]
        assert cumulative == [5]   # 3 + 2 fast across both children
        assert count == 10
        assert latency_error_ratio(
            bounds, cumulative, count, 0.5
        ) == pytest.approx(0.5)
        # match narrows to one child
        bounds, cumulative, count = histogram_from_samples(
            samples, "y_seconds", match={"replica": "a"}
        )
        assert cumulative == [3] and count == 4

    def test_slo_gauges_ride_the_server_metrics_endpoint(self):
        cfg, params = _setup("control")
        from differential_transformer_replication_tpu.obs.slo import (
            default_serving_objectives,
        )

        engine = ServingEngine(
            params, cfg,
            ServingConfig(num_slots=2, prefill_chunk=8,
                          prefill_budget=16),
        )
        latency, availability = default_serving_objectives()
        mon = SLOMonitor(engine.registry, latency=latency,
                         availability=availability)
        client = ServingClient(engine)
        httpd = serve(client, port=0, slo=mon)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            client.generate(_prompts([4], cfg.vocab_size)[0],
                            max_new_tokens=2, timeout=120)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ) as r:
                text = r.read().decode()
            types, samples = parse_exposition(text)
            assert types["slo_burn_rate"] == "gauge"
            burns = {
                l["objective"]: v
                for n, l, v in samples if n == "slo_burn_rate"
            }
            # a single fast CPU request burns nothing
            assert burns.get("availability", 0.0) == 0.0
        finally:
            httpd.shutdown()
            httpd.server_close()
            client.close()


# -- structured event log -----------------------------------------------


class TestEventLog:
    def test_emit_flush_close_and_append(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = EventLog(path, process="test", flush_every=100)
        log.emit("request_finished", trace_id="abc", status=200)
        log.flush()
        first = [json.loads(l) for l in open(path)]
        assert first[0]["event"] == "request_finished"
        assert first[0]["process"] == "test"
        assert first[0]["trace_id"] == "abc"
        assert abs(first[0]["ts"] - time.time()) < 60
        log.close()
        log.close()  # idempotent
        log.emit("late")  # dropped, never corrupts the closed file
        # append mode: a relaunch extends, not truncates
        log2 = EventLog(path, process="test")
        log2.emit("relaunched")
        log2.close()
        lines = [json.loads(l) for l in open(path)]
        assert [e["event"] for e in lines] == [
            "request_finished", "relaunched"
        ]

    def test_unserializable_fields_degrade_to_repr(self, tmp_path):
        path = str(tmp_path / "e.jsonl")
        log = EventLog(path)
        log.emit("weird", obj=object())
        log.close()
        rec = json.loads(open(path).read())
        assert "object" in rec["obj"]

    def test_noop_is_silent(self):
        NOOP_EVENTS.emit("x", a=1)
        NOOP_EVENTS.flush()
        NOOP_EVENTS.close()


# -- tools: slo_report + trace_stitch -----------------------------------


class TestSLOReportTool:
    def _exposition(self, slow_count):
        reg = Registry()
        h = reg.histogram("serving_ttft_seconds", "",
                          buckets=(0.1, 0.5, 1.0))
        for _ in range(100 - slow_count):
            h.observe(0.05)
        for _ in range(slow_count):
            h.observe(2.0)
        reg.histogram("serving_itl_seconds", "",
                      buckets=(0.1, 0.5)).observe(0.01)
        reg.counter("serving_requests_completed_total", "").inc(100)
        reg.counter("serving_requests_rejected_total", "").inc(0)
        return reg.render()

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(TOOLS, "slo_report.py"),
             *argv],
            capture_output=True, text=True, timeout=60,
        )

    def test_check_passes_inside_budget(self, tmp_path):
        path = str(tmp_path / "metrics.txt")
        open(path, "w").write(self._exposition(slow_count=1))
        r = self._run(path, "--check", "--ttft", "1.0",
                      "--target", "0.99")
        assert r.returncode == 0, r.stderr
        summary = json.loads(r.stdout)
        assert summary["ok"] is True
        assert summary["ttft"]["burn_rate"] == pytest.approx(1.0)
        assert summary["availability"]["error_ratio"] == 0.0

    def test_check_fails_on_burn(self, tmp_path):
        path = str(tmp_path / "metrics.txt")
        open(path, "w").write(self._exposition(slow_count=10))
        r = self._run(path, "--check", "--ttft", "1.0",
                      "--target", "0.99")
        assert r.returncode == 1
        assert "objective ttft" in r.stderr
        summary = json.loads(r.stdout)
        assert summary["ttft"]["burn_rate"] == pytest.approx(10.0)

    def test_from_metrics_jsonl_shared_input(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as fh:
            for i in range(10):
                fh.write(json.dumps({
                    "iter": i, "loss": 2.0, "learning_rate": 1e-3,
                    "step_time_ms": 80.0 if i else 5000.0,
                    "skipped_steps": 0,
                }) + "\n")
        # 1/10 steps above 500ms vs target 0.99 -> burn 10 -> fail
        r = self._run("--from-metrics-jsonl", path, "--check",
                      "--step-time-ms", "500", "--target", "0.99")
        assert r.returncode == 1
        assert "step_time" in r.stderr
        # metrics_report accepts the same flag spelling (satellite)
        r2 = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "metrics_report.py"),
             "--from-metrics-jsonl", path],
            capture_output=True, text=True, timeout=60,
        )
        assert r2.returncode == 0, r2.stderr
        assert json.loads(r2.stdout)["step_records"] == 10

    def test_no_traffic_is_not_an_outage(self, tmp_path):
        reg = Registry()
        reg.histogram("serving_ttft_seconds", "", buckets=(1.0,))
        path = str(tmp_path / "empty.txt")
        open(path, "w").write(reg.render())
        r = self._run(path, "--check")
        assert r.returncode == 0, r.stderr
        r = self._run(path, "--check", "--require-traffic")
        assert r.returncode == 1


class TestTraceStitch:
    def _make_traces(self, tmp_path, skew_us=0.0):
        """A router file + a replica file for one traced request; the
        replica's clock optionally skewed."""
        router_path = str(tmp_path / "router.trace.json")
        replica_path = str(tmp_path / "replica.trace.json")
        t_r = SpanTracer(router_path, process_name="router")
        t_p = SpanTracer(replica_path, process_name="replica")
        root = trace_mod.mint()
        fwd = root.child()
        with t_r.span("forward", trace_id=root.trace_id,
                      span_id=fwd.span_id, parent_id=root.span_id):
            with t_p.span("request",
                          **trace_mod.child_span_args(fwd)):
                time.sleep(0.01)
            time.sleep(0.002)
        t_r.close()
        t_p.close()
        if skew_us:
            events = json.load(open(replica_path))
            for e in events:
                if "ts" in e:
                    e["ts"] += skew_us
            json.dump(events, open(replica_path, "w"))
        return router_path, replica_path, root.trace_id

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(TOOLS, "trace_stitch.py"),
             *argv],
            capture_output=True, text=True, timeout=60,
        )

    def test_stitch_merges_lanes_and_aligns_skewed_clocks(self, tmp_path):
        router_path, replica_path, tid = self._make_traces(
            tmp_path, skew_us=5_000_000.0  # replica clock 5s ahead
        )
        out = str(tmp_path / "stitched.json")
        r = self._run(router_path, replica_path, "-o", out)
        assert r.returncode == 0, r.stderr
        summary = json.loads(r.stdout)
        assert summary["files"] == 2
        # the 5s skew was detected and removed (to within the span)
        assert abs(summary["offsets_us"][1] + 5_000_000.0) < 50_000
        events = json.load(open(out))
        fwd = next(e for e in events if e.get("name") == "forward")
        req = next(e for e in events if e.get("name") == "request")
        assert fwd["pid"] != req["pid"]  # per-file lanes
        # after alignment the replica span lies inside its cause again
        assert fwd["ts"] <= req["ts"]
        assert req["ts"] + req["dur"] <= fwd["ts"] + fwd["dur"] + 1
        # process lanes keep their names
        names = {
            (e.get("args") or {}).get("name")
            for e in events if e.get("ph") == "M"
        }
        assert any(n and n.startswith("router") for n in names)

    def test_trace_id_filter(self, tmp_path):
        router_path, replica_path, tid = self._make_traces(tmp_path)
        out = str(tmp_path / "one.json")
        r = self._run(router_path, replica_path, "-o", out,
                      "--trace-id", tid)
        assert r.returncode == 0, r.stderr
        events = json.load(open(out))
        spans = [e for e in events if e.get("ph") != "M"]
        assert spans and all(
            tid == (e.get("args") or {}).get("trace_id")
            or tid in ((e.get("args") or {}).get("trace_ids") or [])
            for e in spans
        )
        # an unknown id exits nonzero (gate-style)
        r = self._run(router_path, replica_path,
                      "-o", str(tmp_path / "none.json"),
                      "--trace-id", "f" * 32)
        assert r.returncode == 1

    def test_truncated_input_is_repaired(self, tmp_path):
        router_path, replica_path, tid = self._make_traces(tmp_path)
        # simulate a crashed process: valid "[" + events, no "]"
        text = open(replica_path).read()
        torn = text.rstrip().rstrip("]").rstrip()
        torn = torn + '\n{"name": "torn'  # half-written tail
        open(replica_path, "w").write(torn)
        out = str(tmp_path / "s.json")
        r = self._run(router_path, replica_path, "-o", out)
        assert r.returncode == 0, r.stderr
        events = json.load(open(out))
        assert any(e.get("name") == "request" for e in events)


# -- serve_bench exemplars (satellite) ----------------------------------


def test_serve_bench_smoke_reports_slow_exemplars(tmp_path, capsys):
    """In-process --smoke run: every request minted a trace context,
    so the JSON line carries p99 exemplar trace ids and --trace-dir
    lands the engine's span trace next to them."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench_t", os.path.join(TOOLS, "serve_bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    trace_dir = str(tmp_path / "traces")
    argv = sys.argv
    sys.argv = ["serve_bench.py", "--smoke", "--trace-dir", trace_dir]
    try:
        bench.main()
    finally:
        sys.argv = argv
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["failed"] == 0
    exemplars = line["slow_exemplars"]
    assert 1 <= len(exemplars) <= 10
    for e in exemplars:
        assert len(e["trace_id"]) == 32 and e["ttft_ms"] > 0
    # slowest-first ordering
    ttfts = [e["ttft_ms"] for e in exemplars]
    assert ttfts == sorted(ttfts, reverse=True)
    assert line["trace_dir"] == trace_dir
    trace_file = os.path.join(trace_dir,
                              "serve_bench.engine.trace.json")
    events = json.load(open(trace_file))
    stamped = {
        (e.get("args") or {}).get("trace_id")
        for e in events if e.get("name") == "request"
    }
    # the exemplar ids are findable in the engine's own trace
    assert {e["trace_id"] for e in exemplars} <= stamped


# -- chaos (slow tier): retried request -> stitched fleet timeline ------


@pytest.mark.slow
def test_chaos_retried_request_produces_stitched_timeline(tmp_path):
    """Acceptance pin (ISSUE 7): a 2-replica fleet (tools/fleet.py,
    every process writing its own trace + event log) serves a request
    whose first attempt CRASHES mid-decode on replica A (injected
    ``serve_raise``); the router fails it over to replica B. One
    ``trace_id`` must then span router pick -> forward to A -> failed
    attempt on A -> retry -> forward to B -> B's admit/first_token/
    finish + decode spans, all inside ONE stitched Perfetto file
    (tools/trace_stitch.py), validated structurally. Engine compile
    pins hold on both replicas (decode == 1: tracing + the supervised
    restart added no shapes)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fleet", os.path.join(TOOLS, "fleet.py")
    )
    fleet_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_mod)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("DTX_FAULTS", None)
    replica_trace = str(tmp_path / "replica-{replica}.trace.json")
    replica_events = str(tmp_path / "replica-{replica}.events.jsonl")
    fleet = fleet_mod.Fleet(
        2,
        server_args=[
            "--num-slots", "2", "--prefill-chunk", "16",
            "--prefill-budget", "32", "--drain-timeout", "30",
            "--restart-backoff", "0.2",
            "--trace-path", replica_trace,
            "--event-log", replica_events,
        ],
        env=env,
        # the injected fault arms replica 0 ONLY: its engine raises at
        # engine iteration 2 — mid-decode of our traced request
        replica_env={0: {"DTX_FAULTS": "serve_raise@2"}},
        max_restarts=3, backoff_base=0.2, backoff_max=2.0,
        ready_timeout_s=180.0,
        fleet_log=str(tmp_path / "fleet.events.jsonl"),
    )
    router_trace = str(tmp_path / "router.trace.json")
    router_events = str(tmp_path / "router.events.jsonl")
    router = None
    try:
        fleet.start()
        cfg = RouterConfig(
            probe_interval_s=0.05, probe_backoff_s=0.05,
            eject_after=3, readmit_after=2, max_attempts=4,
            retry_base_s=0.02, retry_cap_s=0.2, retry_after_cap_s=0.5,
            default_deadline_s=120.0, wait_for_replica_s=5.0,
        )
        router = Router(
            fleet.urls, cfg,
            tracer=SpanTracer(router_trace, process_name="router"),
            events=EventLog(router_events, process="router"),
        ).start()
        rep_a, rep_b = router.replicas

        # pin the session to replica A so the FIRST attempt lands on
        # the armed fault deterministically
        router._affinity["s"] = rep_a
        status, body, _ = router.handle_generate({
            "prompt_ids": [1, 2, 3, 4],
            "max_new_tokens": 8, "temperature": 0.0, "seed": 0,
            "session_id": "s",
        })
        assert status == 200, body
        assert body["attempts"] == 2
        assert body["replica"] == rep_b.name  # failed over A -> B
        tid = body["trace_id"]
        assert len(tid) == 32

        # compile pins on BOTH replicas: the crashed+rebuilt engine on
        # A and the healthy engine on B each sit at decode == 1
        for r_url in fleet.urls:
            deadline = time.time() + 60
            while True:
                with urllib.request.urlopen(r_url + "/health",
                                            timeout=30) as r:
                    health = json.load(r)
                if health["status"] == "healthy":
                    break
                assert time.time() < deadline, (r_url, health)
                time.sleep(0.1)
            assert health["compiles"]["decode"] == 1, (r_url, health)
        # the crash was real: A's engine restarted once
        with urllib.request.urlopen(fleet.urls[0] + "/health",
                                    timeout=30) as r:
            assert json.load(r)["stats"]["engine_restarts"] == 1
    finally:
        if router is not None:
            router.close()
            router.tracer.close()
            router.events.close()
        fleet.stop()  # SIGTERM: replicas drain + close their tracers

    # -- stitch all three processes into one timeline -------------------
    trace_a = replica_trace.replace("{replica}", "0")
    trace_b = replica_trace.replace("{replica}", "1")
    stitched_path = str(tmp_path / "stitched.trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_stitch.py"),
         router_trace, trace_a, trace_b, "-o", stitched_path,
         "--trace-id", tid],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["files"] == 3 and summary["span_events"] > 0

    events = json.load(open(stitched_path))
    spans = [e for e in events if e.get("ph") != "M"]
    # every surviving event belongs to OUR trace
    for e in spans:
        args = e.get("args") or {}
        assert (args.get("trace_id") == tid
                or tid in (args.get("trace_ids") or [])), e
    by_lane = {}
    for e in spans:
        by_lane.setdefault(e["pid"], []).append(e["name"])
    # lane 0 = router: pick, two forwards (A then B), the retry marker
    assert by_lane[0].count("forward") == 2
    assert "pick" in by_lane[0] and "retry" in by_lane[0]
    # lane 1 = replica A: the FAILED attempt still left its admission
    # (and decode work) in the timeline
    assert "admit" in by_lane[1], by_lane
    # lane 2 = replica B: the successful attempt end to end
    for name in ("admit", "first_token", "finish", "request"):
        assert name in by_lane[2], by_lane
    assert "decode" in by_lane[2]
    # B's request span parents to the router's SECOND forward hop
    fwd_span_ids = [
        e["args"]["span_id"] for e in spans
        if e["name"] == "forward"
    ]
    req_b = next(e for e in spans
                 if e["name"] == "request" and e["pid"] == 2)
    assert req_b["args"]["parent_id"] in fwd_span_ids
    # clocks are one host: alignment applied only µs-scale offsets
    assert all(abs(o) < 1e6 for o in summary["offsets_us"])

    # -- and the event logs tell the same story by trace_id -------------
    router_log = [json.loads(l) for l in open(router_events)]
    assert any(e["event"] == "request_retried"
               and e["trace_id"] == tid for e in router_log)
    assert any(e["event"] == "request_finished"
               and e["trace_id"] == tid for e in router_log)
    a_log = [json.loads(l)
             for l in open(replica_events.replace("{replica}", "0"))]
    failed = next(e for e in a_log if e["event"] == "request_failed")
    assert failed["code"] == "engine_crash"
    assert failed["trace_id"] == tid
    b_log = [json.loads(l)
             for l in open(replica_events.replace("{replica}", "1"))]
    assert any(e["event"] == "request_finished"
               and e["trace_id"] == tid for e in b_log)
