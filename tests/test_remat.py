"""Remat (jax.checkpoint) option: identical losses and gradients, for all
three families — rematerialization must be numerically invisible.

Covers the two properties most at risk from refactors:
  - the per-block rng is passed as a checkpoint ARGUMENT, so the backward
    recompute reuses the same dropout mask (dropout > 0 cases),
  - jax.checkpoint composes with the flash kernel's custom_vjp
    (attention_impl="pallas"; interpret mode on CPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import init_model, model_forward


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
@pytest.mark.parametrize(
    "dropout,impl",
    [(0.0, "xla"), (0.3, "xla"), (0.0, "pallas")],
    ids=["plain", "dropout", "pallas"],
)
def test_remat_matches(kind, dropout, impl):
    cfg = ModelConfig(
        model=kind, vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=16, dropout=dropout, n_terms=2, compute_dtype="float32",
        attention_impl=impl,
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 61)
    tgt = jnp.roll(idx, -1, axis=-1)
    rng = jax.random.PRNGKey(7) if dropout > 0 else None

    def loss(p, remat):
        _, l = model_forward(
            p, idx, cfg.replace(remat=remat), targets=tgt, rng=rng
        )
        return l

    l0, g0 = jax.value_and_grad(loss)(params, False)
    l1, g1 = jax.value_and_grad(loss)(params, True)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-6)
