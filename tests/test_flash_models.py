"""End-to-end model parity: attention_impl="pallas" vs "xla".

The flash path must produce the same logits and loss gradients as the
naive path for every model family, since it is a pure backend swap."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import init_model, model_forward


def _cfg(kind):
    return ModelConfig(
        model=kind,
        vocab_size=97,
        n_embd=32,
        n_head=2,
        n_layer=2,
        block_size=32,
        dropout=0.0,
        n_terms=3,
        compute_dtype="float32",
    )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_logits_parity(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    logits_xla, _ = model_forward(params, idx, cfg.replace(attention_impl="xla"))
    logits_pl, _ = model_forward(params, idx, cfg.replace(attention_impl="pallas"))
    np.testing.assert_allclose(logits_pl, logits_xla, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_grad_parity(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    tgt = jnp.roll(idx, -1, axis=-1)

    def loss_fn(p, impl):
        _, loss = model_forward(p, idx, cfg.replace(attention_impl=impl), targets=tgt)
        return loss

    g_xla = jax.grad(loss_fn)(params, "xla")
    g_pl = jax.grad(loss_fn)(params, "pallas")
    flat_x, _ = jax.tree.flatten(g_xla)
    flat_p, _ = jax.tree.flatten(g_pl)
    for a, b in zip(flat_x, flat_p):
        np.testing.assert_allclose(b, a, rtol=5e-4, atol=5e-4)
