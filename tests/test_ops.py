"""Unit + parity-fixture tests for core ops.

Fixtures are independent numpy re-derivations of the reference formulas
(cited per test); nothing is imported from /root/reference.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.ops import (
    apply_rope,
    causal_mask,
    diff_attention,
    diff_lambda,
    group_layer_norm,
    lambda_init_schedule,
    layer_norm,
    masked_softmax,
    ndiff_attention,
    ndiff_lambdas,
    ndiff_signs,
    rope_cos_sin,
    swiglu,
    vanilla_attention,
)
from differential_transformer_replication_tpu.ops.lambdas import OUTPUT_SCALE


def np_softmax(x, axis=-1):
    x = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def np_rope(x, theta=10000.0):
    """Complex-arithmetic RoPE exactly as control.py:4-22: consecutive
    feature pairs as complex numbers times exp(i*t*f_j)."""
    T, d = x.shape[-2], x.shape[-1]
    j = np.arange(0, d, 2)[: d // 2].astype(np.float64)
    freqs = 1.0 / (theta ** (j / d))
    angles = np.outer(np.arange(T), freqs)
    f_cis = np.exp(1j * angles)  # (T, d/2)
    xc = x.astype(np.float64).reshape(*x.shape[:-1], d // 2, 2)
    xc = xc[..., 0] + 1j * xc[..., 1]
    rot = xc * f_cis
    out = np.stack([rot.real, rot.imag], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class TestRope:
    def test_matches_complex_formulation(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 7, 16)).astype(np.float32)  # (B, T, d)
        cos, sin = rope_cos_sin(16, 32)
        got = apply_rope(jnp.asarray(x), cos, sin)
        np.testing.assert_allclose(np.asarray(got), np_rope(x), rtol=1e-5, atol=1e-5)

    def test_headed_layout(self):
        """(B, T, H, d) must equal per-head application."""
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 5, 3, 8)).astype(np.float32)
        cos, sin = rope_cos_sin(8, 5)
        got = np.asarray(apply_rope(jnp.asarray(x), cos, sin))
        for h in range(3):
            np.testing.assert_allclose(got[:, :, h], np_rope(x[:, :, h]), rtol=1e-5, atol=1e-5)

    def test_table_truncation(self):
        """Tables longer than T are truncated at apply time (control.py:18)."""
        x = np.ones((1, 3, 4), np.float32)
        cos, sin = rope_cos_sin(4, 100)
        got = apply_rope(jnp.asarray(x), cos, sin)
        assert got.shape == (1, 3, 4)

    def test_preserves_dtype(self):
        cos, sin = rope_cos_sin(8, 4)
        x = jnp.ones((1, 4, 8), jnp.bfloat16)
        assert apply_rope(x, cos, sin).dtype == jnp.bfloat16

    def test_headed_override_for_rank3(self):
        """An unbatched (T, H, d) tensor is rank 3 and must be rotated by
        position, not head index, when headed=True is passed."""
        rng = np.random.default_rng(9)
        x = rng.standard_normal((5, 3, 8)).astype(np.float32)  # (T, H, d)
        cos, sin = rope_cos_sin(8, 5)
        got = np.asarray(apply_rope(jnp.asarray(x), cos, sin, headed=True))
        batched = np.asarray(apply_rope(jnp.asarray(x[None]), cos, sin))[0]
        np.testing.assert_allclose(got, batched, rtol=1e-6)
        # the auto rule would have mis-rotated this shape
        auto = np.asarray(apply_rope(jnp.asarray(x), cos, sin))
        assert not np.allclose(got, auto, atol=1e-4)

    def test_position_zero_identity(self):
        """t=0 -> angle 0 -> no rotation."""
        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 1, 6)).astype(np.float32)
        cos, sin = rope_cos_sin(6, 1)
        np.testing.assert_allclose(np.asarray(apply_rope(jnp.asarray(x), cos, sin)), x, rtol=1e-6)


class TestNorms:
    def test_layer_norm_formula(self):
        """Biased variance, eps inside sqrt (diff_transformer.py:17-19)."""
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 4, 10)).astype(np.float32)
        w = rng.standard_normal(10).astype(np.float32)
        b = rng.standard_normal(10).astype(np.float32)
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)  # biased
        want = (x - mean) / np.sqrt(var + 1e-5) * w + b
        got = layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)

    def test_group_layer_norm_is_full_width(self):
        """The quirk: GroupLayerNorm normalizes the ENTIRE concat dim, not
        per head (diff_transformer.py:17-18). With per-head stats this
        fixture would NOT match."""
        rng = np.random.default_rng(4)
        H, two_d = 3, 8
        x = rng.standard_normal((2, 5, H * two_d)).astype(np.float32)
        w = np.ones(H * two_d, np.float32)
        b = np.zeros(H * two_d, np.float32)
        got = np.asarray(group_layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        want = (x - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        # sanity: differs from per-head normalization
        xh = x.reshape(2, 5, H, two_d)
        per_head = (xh - xh.mean(-1, keepdims=True)) / np.sqrt(xh.var(-1, keepdims=True) + 1e-5)
        assert not np.allclose(got, per_head.reshape(2, 5, -1), atol=1e-3)


class TestSwiGLU:
    def test_formula(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((4, 6)).astype(np.float32)
        wg = rng.standard_normal((6, 9)).astype(np.float32)
        bg = rng.standard_normal(9).astype(np.float32)
        wx = rng.standard_normal((6, 9)).astype(np.float32)
        bx = rng.standard_normal(9).astype(np.float32)
        g = x @ wg + bg
        want = (g / (1 + np.exp(-g))) * (x @ wx + bx)  # silu(g) * xform
        got = swiglu(*map(jnp.asarray, (x, wg, bg, wx, bx)))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


class TestLambdas:
    def test_dynamic_init_schedule_pinned_values(self):
        """SURVEY.md section 2.1 table: 1-based layers (diff_transformer.py:43)."""
        want = {1: 0.2, 2: 0.3555091, 3: 0.4707130, 4: 0.5560582,
                5: 0.6192835, 6: 0.6661219, 7: 0.7008207, 8: 0.7265261}
        for layer, val in want.items():
            assert lambda_init_schedule(layer) == pytest.approx(val, abs=1e-6)
        # layer 1 exactly: 0.8 - 0.6*exp(0) = 0.2
        assert lambda_init_schedule(1) == pytest.approx(0.2, abs=1e-12)

    def test_diff_lambda_zero_init_equals_lambda_init(self):
        """At zero-initialized lambda params (diff_transformer.py:35-38),
        exp(0)-exp(0)+init = init exactly."""
        z = jnp.zeros((4, 16))
        lam = diff_lambda(z, z, z, z, 0.2)
        np.testing.assert_allclose(np.asarray(lam), 0.2 * np.ones(4), rtol=1e-6)

    def test_diff_lambda_formula(self):
        rng = np.random.default_rng(6)
        lq1, lk1, lq2, lk2 = (rng.standard_normal((2, 8)).astype(np.float32) * 0.1 for _ in range(4))
        init = 0.4707
        want = (np.exp(lq1 * lk1) - np.exp(lq2 * lk2) + init).mean(-1)
        got = diff_lambda(*map(jnp.asarray, (lq1, lk1, lq2, lk2)), init)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_ndiff_lambda_chain(self):
        """Ndiff_transformer.py:85-93: term 0 has no subtraction; term i
        subtracts term i-1's exponential."""
        rng = np.random.default_rng(7)
        n, H, d = 3, 2, 8
        lqs = (rng.standard_normal((n, H, d)) * 0.1).astype(np.float32)
        lks = (rng.standard_normal((n, H, d)) * 0.1).astype(np.float32)
        init = 0.2
        e = np.exp(lqs * lks)
        want = np.stack(
            [(e[0] + init).mean(-1)]
            + [(e[i] - e[i - 1] + init).mean(-1) for i in range(1, n)]
        )
        got = ndiff_lambdas(jnp.asarray(lqs), jnp.asarray(lks), init)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_ndiff_signs(self):
        np.testing.assert_array_equal(np.asarray(ndiff_signs(5)), [1, -1, 1, -1, 1])

    def test_output_scale_is_fixed_point_two(self):
        """diff_transformer.py:86,91 — the multi-head module's lambda_init
        buffer is never updated, so the output scale is constant 0.2."""
        assert OUTPUT_SCALE == pytest.approx(0.2)


def np_attention_probs(q, k, causal=True):
    """Per-head fixture: (T, d) x (T, d) -> masked softmax probs."""
    T = q.shape[0]
    scale = 1.0 / math.sqrt(q.shape[-1])
    att = (q @ k.T) * scale
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        att = np.where(mask, att, -np.inf)
    return np_softmax(att)


class TestAttention:
    def setup_method(self):
        self.rng = np.random.default_rng(8)

    def test_masked_softmax_rows_sum_to_one(self):
        s = jnp.asarray(self.rng.standard_normal((2, 3, 4, 4)), dtype=jnp.float32)
        p = masked_softmax(s, causal_mask(4))
        np.testing.assert_allclose(np.asarray(p.sum(-1)), 1.0, rtol=1e-5)
        assert p.dtype == jnp.float32

    def test_vanilla_matches_per_head_fixture(self):
        B, T, H, d = 2, 6, 3, 4
        q, k, v = (self.rng.standard_normal((B, T, H, d)).astype(np.float32) for _ in range(3))
        out = np.asarray(vanilla_attention(*map(jnp.asarray, (q, k, v)), mask=causal_mask(T)))
        for b in range(B):
            for h in range(H):
                probs = np_attention_probs(q[b, :, h], k[b, :, h])
                np.testing.assert_allclose(out[b, :, h], probs @ v[b, :, h], rtol=1e-4, atol=1e-5)

    def test_causality(self):
        """Changing future tokens must not change past outputs."""
        B, T, H, d = 1, 5, 2, 4
        q, k, v = (self.rng.standard_normal((B, T, H, d)).astype(np.float32) for _ in range(3))
        out1 = np.asarray(vanilla_attention(*map(jnp.asarray, (q, k, v)), mask=causal_mask(T)))
        k2, v2 = k.copy(), v.copy()
        k2[:, -1], v2[:, -1] = 99.0, 99.0
        out2 = np.asarray(vanilla_attention(*map(jnp.asarray, (q, k2, v2)), mask=causal_mask(T)))
        np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5, atol=1e-6)

    def test_diff_attention_fixture(self):
        """diff_transformer.py:57-72: out = (att1 - lam*att2) @ v per head."""
        B, T, H, d = 2, 5, 2, 4
        q1, k1, q2, k2 = (self.rng.standard_normal((B, T, H, d)).astype(np.float32) for _ in range(4))
        v = self.rng.standard_normal((B, T, H, 2 * d)).astype(np.float32)
        lam = np.asarray([0.2, 0.5], np.float32)
        out = np.asarray(
            diff_attention(*map(jnp.asarray, (q1, k1, q2, k2, v)), jnp.asarray(lam), mask=causal_mask(T))
        )
        for b in range(B):
            for h in range(H):
                a1 = np_attention_probs(q1[b, :, h], k1[b, :, h])
                a2 = np_attention_probs(q2[b, :, h], k2[b, :, h])
                want = (a1 - lam[h] * a2) @ v[b, :, h]
                np.testing.assert_allclose(out[b, :, h], want, rtol=1e-4, atol=1e-5)

    def test_ndiff_attention_fixture(self):
        """Ndiff_transformer.py:117-125: lambda_0-scaled first map plus
        alternating-sign terms."""
        n, B, T, H, d = 3, 1, 4, 2, 4
        qs = self.rng.standard_normal((n, B, T, H, d)).astype(np.float32)
        ks = self.rng.standard_normal((n, B, T, H, d)).astype(np.float32)
        v = self.rng.standard_normal((B, T, H, 2 * d)).astype(np.float32)
        lams = (self.rng.uniform(0.1, 0.9, (n, H))).astype(np.float32)
        out = np.asarray(
            ndiff_attention(
                jnp.asarray(qs), jnp.asarray(ks), jnp.asarray(v),
                jnp.asarray(lams), ndiff_signs(n), mask=causal_mask(T),
            )
        )
        for b in range(B):
            for h in range(H):
                maps = [np_attention_probs(qs[i, b, :, h], ks[i, b, :, h]) for i in range(n)]
                acc = lams[0, h] * maps[0]
                for i in range(1, n):
                    sign = -1.0 if i % 2 else 1.0
                    acc = acc + sign * lams[i, h] * maps[i]
                np.testing.assert_allclose(out[b, :, h], acc @ v[b, :, h], rtol=1e-4, atol=1e-5)

    def test_dropout_zero_is_identity_and_active_scales(self):
        B, T, H, d = 1, 4, 1, 4
        q, k, v = (self.rng.standard_normal((B, T, H, d)).astype(np.float32) for _ in range(3))
        key = jax.random.PRNGKey(0)
        out0 = vanilla_attention(*map(jnp.asarray, (q, k, v)), mask=causal_mask(T), dropout_rate=0.0, rng=key)
        out_none = vanilla_attention(*map(jnp.asarray, (q, k, v)), mask=causal_mask(T))
        np.testing.assert_allclose(np.asarray(out0), np.asarray(out_none), rtol=1e-6)
        out_drop = vanilla_attention(
            *map(jnp.asarray, (q, k, v)), mask=causal_mask(T), dropout_rate=0.5, rng=key
        )
        assert not np.allclose(np.asarray(out_drop), np.asarray(out_none))
