"""Numerics parity + scheduling pins for the fused FFN/norm hot path
(ISSUE 9): ops/fused_ffn.py, ops/fused_norm_residual.py, the ffn_impl
switch through all three model families and decode, the remat-policy
knob, and the overlap-scheduled pure-DP step (parallel/dp_step.py).

The kernels run in interpret mode on the CPU mesh — the same code paths
the TPU compiles — so this is the tier-1 gate for the fused path.
"""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.models import (
    init_model,
    model_forward,
)
from differential_transformer_replication_tpu.ops import (
    group_layer_norm,
    layer_norm,
    swiglu,
)
from differential_transformer_replication_tpu.ops.fused_ffn import (
    fused_swiglu,
)
from differential_transformer_replication_tpu.ops.fused_norm_residual import (
    fused_add_norm,
    fused_group_norm,
    fused_norm,
)

REPO = Path(__file__).resolve().parents[1]

TINY = dict(vocab_size=61, n_embd=32, n_head=2, n_layer=2, block_size=16,
            dropout=0.0, n_terms=2, compute_dtype="float32")

# fp32: the kernels compute the exact same fp32 chain as the reference
# ops — tight. bf16: identical math, but fp32 reduction ORDER differs
# before the bf16 quantization, so parity is to within bf16 ulps.
TOLS = {
    jnp.float32: dict(rtol=2e-5, atol=2e-6),
    jnp.bfloat16: dict(rtol=3e-2, atol=3e-2),
}
GRAD_TOLS = {
    jnp.float32: dict(rtol=2e-4, atol=2e-5),
    jnp.bfloat16: dict(rtol=6e-2, atol=6e-2),
}


def _close(got, want, tols):
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **tols
    )


def _norm_inputs(dtype, E=48, rows=24):
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(ks[0], (3, rows // 3, E), dtype)
    d = jax.random.normal(ks[1], (3, rows // 3, E), dtype)
    w = jax.random.normal(ks[2], (E,)) * 0.2 + 1.0
    b = jax.random.normal(ks[3], (E,)) * 0.2
    return x, d, w, b


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
class TestNormResidualKernels:
    def test_fused_norm_matches_layer_norm(self, dtype):
        x, _, w, b = _norm_inputs(dtype)
        _close(fused_norm(x, w, b), layer_norm(x, w, b), TOLS[dtype])

    def test_group_alias_matches_group_layer_norm(self, dtype):
        x, _, w, b = _norm_inputs(dtype)
        _close(
            fused_group_norm(x, w, b), group_layer_norm(x, w, b), TOLS[dtype]
        )

    def test_fused_add_norm_forward(self, dtype):
        x, d, w, b = _norm_inputs(dtype)
        xnew, normed = fused_add_norm(x, d, w, b)
        # the residual carry is the plain stored-dtype add, bit-exact
        np.testing.assert_array_equal(
            np.asarray(xnew, np.float32), np.asarray(x + d, np.float32)
        )
        _close(normed, layer_norm(x + d, w, b), TOLS[dtype])

    def test_fused_add_norm_grads(self, dtype):
        """Both outputs' cotangents flow: the normed branch through the
        LN backward, the carry branch straight through the add."""
        x, d, w, b = _norm_inputs(dtype)

        def ref(x, d, w, b):
            xn = x + d
            n = layer_norm(xn, w, b)
            return (jnp.sum(jnp.sin(n.astype(jnp.float32)))
                    + jnp.sum(xn.astype(jnp.float32) ** 2))

        def got(x, d, w, b):
            xn, n = fused_add_norm(x, d, w, b)
            return (jnp.sum(jnp.sin(n.astype(jnp.float32)))
                    + jnp.sum(xn.astype(jnp.float32) ** 2))

        g0 = jax.grad(ref, argnums=(0, 1, 2, 3))(x, d, w, b)
        g1 = jax.grad(got, argnums=(0, 1, 2, 3))(x, d, w, b)
        for a, bb in zip(g0, g1):
            _close(bb, a, GRAD_TOLS[dtype])

    def test_fused_norm_grads(self, dtype):
        x, _, w, b = _norm_inputs(dtype)

        def ref(x, w, b):
            return jnp.sum(jnp.sin(layer_norm(x, w, b).astype(jnp.float32)))

        def got(x, w, b):
            return jnp.sum(jnp.sin(fused_norm(x, w, b).astype(jnp.float32)))

        g0 = jax.grad(ref, argnums=(0, 1, 2))(x, w, b)
        g1 = jax.grad(got, argnums=(0, 1, 2))(x, w, b)
        for a, bb in zip(g0, g1):
            _close(bb, a, GRAD_TOLS[dtype])


def _ffn_inputs(dtype, E=32, F=128, rows=24):
    ks = jax.random.split(jax.random.PRNGKey(1), 6)
    x = jax.random.normal(ks[0], (2, rows // 2, E), dtype)
    lnw = jax.random.normal(ks[1], (E,)) * 0.1 + 1.0
    lnb = jax.random.normal(ks[2], (E,)) * 0.1
    wg = jax.random.normal(ks[3], (E, F)) * 0.05
    bg = jax.random.normal(ks[4], (F,)) * 0.05
    wx = jax.random.normal(ks[5], (E, F)) * 0.05
    bx = jnp.zeros((F,)) + 0.01
    return x, lnw, lnb, wg, bg, wx, bx


def _ref_swiglu(x, wg, bg, wx, bx):
    return swiglu(
        x, wg.astype(x.dtype), bg.astype(x.dtype),
        wx.astype(x.dtype), bx.astype(x.dtype),
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
class TestFusedSwiGLU:
    def test_forward_matches_reference(self, dtype):
        x, _, _, wg, bg, wx, bx = _ffn_inputs(dtype)
        _close(
            fused_swiglu(x, wg, bg, wx, bx),
            _ref_swiglu(x, wg, bg, wx, bx), TOLS[dtype],
        )

    def test_block_boundary_composition_matches_reference(self, dtype):
        """The pairing the blocks actually run (apply_block_ffn):
        fused residual-add+LN feeding the fused SwiGLU kernel vs the
        un-fused add -> layer_norm -> swiglu reference chain."""
        x, lnw, lnb, wg, bg, wx, bx = _ffn_inputs(dtype)
        y = jnp.flip(x, axis=1) * 0.5
        carry, normed = fused_add_norm(x, y, lnw, lnb)
        ref_carry = x + y
        _close(carry, ref_carry, TOLS[dtype])
        _close(
            fused_swiglu(normed, wg, bg, wx, bx),
            _ref_swiglu(layer_norm(ref_carry, lnw, lnb), wg, bg, wx, bx),
            TOLS[dtype],
        )

    def test_grads_match_reference(self, dtype):
        x, _, _, wg, bg, wx, bx = _ffn_inputs(dtype)

        def ref(x, wg, bg, wx, bx):
            return jnp.sum(
                jnp.tanh(_ref_swiglu(x, wg, bg, wx, bx).astype(jnp.float32))
            )

        def got(x, wg, bg, wx, bx):
            return jnp.sum(
                jnp.tanh(fused_swiglu(x, wg, bg, wx, bx).astype(jnp.float32))
            )

        g0 = jax.grad(ref, argnums=tuple(range(5)))(x, wg, bg, wx, bx)
        g1 = jax.grad(got, argnums=tuple(range(5)))(x, wg, bg, wx, bx)
        for a, bb in zip(g0, g1):
            _close(bb, a, GRAD_TOLS[dtype])

    def test_block_boundary_composition_grads(self, dtype):
        """Grads through the fused add+LN -> fused SwiGLU pairing match
        the un-fused reference chain (both kernel backwards compose)."""
        x, lnw, lnb, wg, bg, wx, bx = _ffn_inputs(dtype)
        y = jnp.flip(x, axis=1) * 0.5
        args = (x, y, lnw, lnb, wg, bg, wx, bx)

        def ref(x, y, lnw, lnb, wg, bg, wx, bx):
            h = _ref_swiglu(layer_norm(x + y, lnw, lnb), wg, bg, wx, bx)
            return jnp.sum(jnp.tanh(h.astype(jnp.float32)))

        def got(x, y, lnw, lnb, wg, bg, wx, bx):
            _, normed = fused_add_norm(x, y, lnw, lnb)
            h = fused_swiglu(normed, wg, bg, wx, bx)
            return jnp.sum(jnp.tanh(h.astype(jnp.float32)))

        g0 = jax.grad(ref, argnums=tuple(range(8)))(*args)
        g1 = jax.grad(got, argnums=tuple(range(8)))(*args)
        for a, bb in zip(g0, g1):
            _close(bb, a, GRAD_TOLS[dtype])

    def test_odd_tile_shapes(self, dtype):
        """Rows/hidden not divisible by the default tiles: pick_block
        must find exact divisors and the kernel stay correct."""
        x, _, _, wg, bg, wx, bx = _ffn_inputs(dtype, E=24, F=72, rows=18)
        _close(
            fused_swiglu(x, wg, bg, wx, bx, block_m=4, block_f=24),
            _ref_swiglu(x, wg, bg, wx, bx), TOLS[dtype],
        )


class TestModelParity:
    """ffn_impl='pallas' vs 'xla' through the full forward/backward for
    every family — the switch must be numerically invisible."""

    @pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
    def test_loss_and_grads_fp32(self, kind):
        cfg = ModelConfig(model=kind, **TINY)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 61)
        tgt = jnp.roll(idx, -1, axis=-1)

        def loss(p, impl):
            _, l = model_forward(
                p, idx, cfg.replace(ffn_impl=impl), targets=tgt
            )
            return l

        l0, g0 = jax.value_and_grad(loss)(params, "xla")
        l1, g1 = jax.value_and_grad(loss)(params, "pallas")
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-6
            )

    @pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
    def test_forward_bf16(self, kind):
        cfg = ModelConfig(model=kind, **{**TINY, "compute_dtype": "bfloat16"})
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 61)
        tgt = jnp.roll(idx, -1, axis=-1)
        _, l0 = model_forward(params, idx, cfg, targets=tgt)
        _, l1 = model_forward(
            params, idx, cfg.replace(ffn_impl="pallas"), targets=tgt
        )
        np.testing.assert_allclose(float(l1), float(l0), rtol=2e-2)

    def test_fused_path_composes_with_pallas_attention(self):
        """attention_impl and ffn_impl both 'pallas' — the full fused
        hot path bench.py now measures."""
        cfg = ModelConfig(model="diff", **TINY)
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 61)
        tgt = jnp.roll(idx, -1, axis=-1)
        _, l0 = model_forward(params, idx, cfg, targets=tgt)
        _, l1 = model_forward(
            params, idx,
            cfg.replace(ffn_impl="pallas", attention_impl="pallas"),
            targets=tgt,
        )
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)

    def test_decode_greedy_parity(self):
        """generate_cached fused vs reference: bit-identical greedy
        tokens — the serving decode path (fused_add_norm at every block
        boundary + fused_swiglu + the GLN alias) is loss-free."""
        from differential_transformer_replication_tpu.models.decode import (
            generate_cached,
        )

        for kind in ("control", "diff"):
            cfg = ModelConfig(model=kind, **TINY)
            params = init_model(jax.random.PRNGKey(0), cfg)
            prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, 61)
            o0 = generate_cached(
                params, prompt, cfg, 8, jax.random.PRNGKey(4),
                temperature=1e-4,
            )
            o1 = generate_cached(
                params, prompt, cfg.replace(ffn_impl="pallas"), 8,
                jax.random.PRNGKey(4), temperature=1e-4,
            )
            np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))

    def test_ffn_impl_validated(self):
        with pytest.raises(ValueError, match="ffn_impl"):
            ModelConfig(ffn_impl="cuda")


class TestRematPolicies:
    def test_policy_validated(self):
        with pytest.raises(ValueError, match="remat_policy"):
            ModelConfig(remat_policy="sometimes")

    @pytest.mark.parametrize("policy", ["none", "dots", "dots_no_batch",
                                        "nothing", "everything"])
    def test_policies_numerically_invisible(self, policy):
        """Every save policy must give the no-remat loss AND grads on
        the fused path — remat changes memory, never math."""
        cfg = ModelConfig(model="diff", **TINY).replace(ffn_impl="pallas")
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 61)
        tgt = jnp.roll(idx, -1, axis=-1)

        def loss(p, c):
            _, l = model_forward(p, idx, c, targets=tgt)
            return l

        l0, g0 = jax.value_and_grad(loss)(params, cfg)
        l1, g1 = jax.value_and_grad(loss)(
            params, cfg.replace(remat=True, remat_policy=policy)
        )
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6
            )


def _overlap_cfg(**kw):
    model = ModelConfig(
        model="diff", vocab_size=128, n_embd=32, n_head=2, n_layer=4,
        block_size=16, dropout=0.0, compute_dtype="float32",
    )
    return TrainConfig(
        model=model, mesh=MeshConfig(data=8), vocab_size=128,
        learning_rate=1e-2, min_lr=1e-3, warmup_iters=2, max_iters=100,
        control_head_multiplier=1, **kw,
    )


class TestOverlapDP:
    """The overlap-scheduled pure-DP step (parallel/dp_step.py): bucketed
    pmean-in-backward, single jit, donated state, zero recompiles."""

    def test_eligibility(self):
        from differential_transformer_replication_tpu.parallel.dp_step import (
            overlap_eligible,
        )

        assert overlap_eligible(_overlap_cfg())
        assert not overlap_eligible(_overlap_cfg(dp_overlap=False))
        for mesh in (MeshConfig(data=4, tensor=2), MeshConfig(data=4, fsdp=2),
                     MeshConfig(data=4, sequence=2), MeshConfig(data=1)):
            cfg = _overlap_cfg().replace(mesh=mesh)
            assert not overlap_eligible(cfg), mesh

    def test_parity_and_zero_recompile_pin(self):
        """THE acceptance pin: the overlapped step equals the
        single-device step after one update, and compile_events stays at
        exactly 1 across M further steps on the 8-device mesh (the
        sentinel additionally proves zero backend compiles happen in the
        steady-state window)."""
        from differential_transformer_replication_tpu.analysis.sanitizers import (
            RecompileSentinel,
        )
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )
        from differential_transformer_replication_tpu.train import (
            create_train_state,
            make_train_step,
        )

        cfg = _overlap_cfg(dp_bucket_layers=2)
        mesh = create_mesh(cfg.mesh)
        x = jax.random.randint(jax.random.PRNGKey(1), (1, 8, 16), 0, 128)
        batch = {"x": x, "y": jnp.roll(x, -1, -1)}

        s1, m1 = make_train_step(cfg)(
            create_train_state(jax.random.PRNGKey(0), cfg), batch
        )
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        s2, m2 = step(state, batch)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(s1["params"]), jax.tree.leaves(s2["params"])
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(jax.device_get(b)),
                rtol=2e-4, atol=1e-5,
            )
        with RecompileSentinel(budget=0, name="overlap-steady-state"):
            for _ in range(3):
                s2, m2 = step(s2, batch)
            _ = float(m2["loss"])
        assert int(step._cache_size()) == 1
        assert step._compile_counter_source == "jit-cache"

    def test_grad_accumulation_parity_once_per_step_sync(self):
        """grad_acc_steps > 1 on the overlap path: the microbatch scan
        differentiates the LOCAL loss and one whole-tree pmean runs after
        it (train/step.py grad_sync) — NOT the per-bucket pmeans inside
        every microbatch backward, which would move A x the collective
        volume. Parity with the single-device accumulated step proves
        the once-per-step sync still yields the global mean gradient."""
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )
        from differential_transformer_replication_tpu.train import (
            create_train_state,
            make_train_step,
        )

        cfg = _overlap_cfg(grad_acc_steps=2)
        cfg = cfg.replace(model=cfg.model.replace(ffn_impl="pallas"))
        x = jax.random.randint(jax.random.PRNGKey(5), (2, 8, 16), 0, 128)
        batch = {"x": x, "y": jnp.roll(x, -1, -1)}

        s1, m1 = make_train_step(cfg)(
            create_train_state(jax.random.PRNGKey(0), cfg), batch
        )
        mesh = create_mesh(cfg.mesh)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        s2, m2 = step(state, batch)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(s1["params"]), jax.tree.leaves(s2["params"])
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(jax.device_get(b)),
                rtol=2e-4, atol=1e-5,
            )

    def test_loss_decreases_with_fused_ffn(self):
        """Overlap + fused kernels together: the full round-6 hot path
        trains."""
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )

        cfg = _overlap_cfg()
        cfg = cfg.replace(model=cfg.model.replace(ffn_impl="pallas"))
        mesh = create_mesh(cfg.mesh)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        x = jax.random.randint(jax.random.PRNGKey(2), (1, 8, 16), 0, 128)
        batch = {"x": x, "y": jnp.roll(x, -1, -1)}
        first = None
        for _ in range(25):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first - 0.5

    def test_bucket_counts(self):
        """One pmean per layer group + embeddings + tail: the bucket
        assignment is the overlap schedule, so pin its shape."""
        from differential_transformer_replication_tpu.parallel.dp_step import (
            make_param_sync,
        )

        calls = []

        def fake_sync_factory(axis):
            def sync(tree):
                calls.append(jax.tree_util.tree_structure(tree))
                return tree
            return sync

        import differential_transformer_replication_tpu.parallel.dp_step as dp

        orig = dp._bucket_sync
        dp._bucket_sync = fake_sync_factory
        try:
            ps = make_param_sync("data", bucket_layers=2)
            cfg = ModelConfig(model="diff", **TINY)  # n_layer=2
            params = init_model(jax.random.PRNGKey(0), cfg)
            out = ps(params)
        finally:
            dp._bucket_sync = orig
        # embed bucket + tail bucket + ceil(2/2)=1 block bucket
        assert len(calls) == 3
        assert jax.tree_util.tree_structure(out) == (
            jax.tree_util.tree_structure(params)
        )


class TestMeshGuardAndShardRng:
    """Fused kernels must never reach a multi-device GSPMD placement as
    bare pallas_calls (models/common.py:use_fused_ffn), and the overlap
    path's replicated dropout key must be folded per shard."""

    def test_use_fused_ffn_matrix(self):
        from differential_transformer_replication_tpu.models import common
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
        )

        pallas = ModelConfig(model="diff", **TINY).replace(ffn_impl="pallas")
        xla = ModelConfig(model="diff", **TINY)
        multi = create_mesh(MeshConfig(data=8))
        single = create_mesh(MeshConfig(data=1))
        assert common.use_fused_ffn(pallas, None)
        assert common.use_fused_ffn(pallas, single)
        assert not common.use_fused_ffn(pallas, multi)
        assert not common.use_fused_ffn(xla, None)
        assert not common.use_fused_ffn(None, None)

    def test_gspmd_multidevice_falls_back_to_xla(self):
        """On the 8-device GSPMD placement (overlap off) ffn_impl='pallas'
        must compile the same XLA-composition program as 'xla': bit-equal
        loss proves the guard dispatched identically."""
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )

        x = jax.random.randint(jax.random.PRNGKey(3), (1, 8, 16), 0, 128)
        batch = {"x": x, "y": jnp.roll(x, -1, -1)}
        losses = {}
        for impl in ("xla", "pallas"):
            cfg = _overlap_cfg(dp_overlap=False)
            cfg = cfg.replace(model=cfg.model.replace(ffn_impl=impl))
            mesh = create_mesh(cfg.mesh)
            state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
            step = make_sharded_train_step(cfg, mesh, state)
            _, m = step(state, batch)
            losses[impl] = float(m["loss"])
        assert losses["pallas"] == losses["xla"]

    def test_overlap_shards_draw_independent_dropout_masks(self):
        """8 shards each holding the SAME example: without the per-shard
        fold_in(axis_index) every shard reuses the single-device key
        chain, making the overlap loss bit-equal to the single-device
        loss on one example — the exact correlated-mask bug."""
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )
        from differential_transformer_replication_tpu.train import (
            create_train_state,
            make_train_step,
        )

        cfg = _overlap_cfg()
        cfg = cfg.replace(model=cfg.model.replace(dropout=0.5))
        rng = jax.random.PRNGKey(7)
        one = jax.random.randint(jax.random.PRNGKey(4), (1, 1, 16), 0, 128)
        single_batch = {"x": one, "y": jnp.roll(one, -1, -1)}
        tiled = jnp.tile(one, (1, 8, 1))
        tiled_batch = {"x": tiled, "y": jnp.roll(tiled, -1, -1)}

        _, m1 = make_train_step(cfg)(
            create_train_state(jax.random.PRNGKey(0), cfg), single_batch, rng
        )
        mesh = create_mesh(cfg.mesh)
        state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_sharded_train_step(cfg, mesh, state)
        _, m2 = step(state, tiled_batch, rng)
        l1, l2 = float(m1["loss"]), float(m2["loss"])
        assert np.isfinite(l2)
        assert l1 != l2, "shards reused the replicated dropout key"


class TestCompileCounterFallback:
    """Satellite: jax-version drift removes jit._cache_size -> the
    trainer's compile-event counter must fall back to the backend-
    compile monitoring instead of silently reporting nothing."""

    def test_fallback_attaches_backend_monitor(self, capsys):
        from differential_transformer_replication_tpu.parallel.dp_step import (
            _attach_compile_counter,
        )

        class NoCacheJit:  # a jitted fn on a drifted jax version
            pass

        def step(state, batch, rng=None):
            return state, {}

        out = _attach_compile_counter(step, NoCacheJit(), "drifted")
        assert out._compile_counter_source == "backend-compile-monitor"
        assert isinstance(out._cache_size(), int)
        assert "backend-compile-monitor" in capsys.readouterr().out

    def test_native_source_preferred(self, capsys):
        from differential_transformer_replication_tpu.parallel.dp_step import (
            _attach_compile_counter,
        )

        class WithCache:
            _cache_size = staticmethod(lambda: 1)

        def step(state, batch, rng=None):
            return state, {}

        out = _attach_compile_counter(step, WithCache(), "native")
        assert out._compile_counter_source == "jit-cache"
        assert out._cache_size() == 1
        assert "jit-cache" in capsys.readouterr().out

    def test_fallback_counts_real_compiles(self):
        """The fallback source must actually move when XLA compiles."""
        from differential_transformer_replication_tpu.parallel.dp_step import (
            _attach_compile_counter,
        )

        class NoCacheJit:
            pass

        def step(state, batch, rng=None):
            return state, {}

        out = _attach_compile_counter(step, NoCacheJit(), "live")
        before = out._cache_size()
        _ = jax.jit(lambda v: v * 3.0 + jnp.float32(before))(
            jnp.ones((4,), jnp.float32)
        )
        assert out._cache_size() >= before + 1


class TestToolGates:
    """CI smoke for the new tooling (satellite: ffn_sweep --smoke and
    the machine-readable profile in tier-1)."""

    @pytest.mark.parametrize("tool", ["ffn_sweep"])
    def test_ffn_sweep_smoke(self, tool):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "ffn_sweep.py"),
             "--smoke"],
            capture_output=True, text=True, cwd=str(REPO), timeout=580,
            env=_cpu_env(),
        )
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(l) for l in r.stdout.splitlines() if l.strip()]
        cases = {d["case"] for d in lines}
        assert cases == {"ffn_chain", "remat_step"}, cases
        assert not any("failed" in d for d in lines), lines
        # both impls timed, so before/after deltas are diffable
        assert {"xla", "pallas"} <= {
            d.get("impl") for d in lines if d["case"] == "ffn_chain"
        }

    def test_profile_step_json_line(self):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "profile_step.py"),
             "--json", "--steps", "2", "--micro-batch", "2",
             "--block-size", "16", "--n-embd", "32", "--n-head", "2",
             "--n-layer", "2", "--vocab-size", "64", "--dtype", "float32"],
            capture_output=True, text=True, cwd=str(REPO), timeout=580,
            env=_cpu_env(),
        )
        assert r.returncode == 0, r.stderr[-2000:]
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        assert doc["metric"] == "profile_step_breakdown"
        # the capture window ran inside the recompile sentinel — a
        # warmed-up tiny step compiles nothing inside the window
        assert doc["compiles_in_window"] == 0
        # CPU CI has no TPU plane: the breakdown degrades to an explicit
        # error field, never a crash (TPU runs carry groups_ms_per_step)
        assert ("groups_ms_per_step" in doc) or ("error" in doc)


def _cpu_env():
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env
