"""Data pipeline tests: corpus, tokenizer, window sampler."""

import numpy as np
import pytest

from differential_transformer_replication_tpu.data import (
    TokenWindows,
    encode_corpus,
    load_corpus,
    load_tokenizer,
    split_tokens,
    train_bpe_tokenizer,
)
from differential_transformer_replication_tpu.data.corpus import synthetic_corpus
from differential_transformer_replication_tpu.data.tokenizer import EOT


class TestCorpus:
    def test_synthetic_deterministic(self):
        a = synthetic_corpus(10, seed=1)
        b = synthetic_corpus(10, seed=1)
        assert a == b and len(a) == 10
        assert synthetic_corpus(10, seed=2) != a

    def test_load_corpus_path(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("hello world\nsecond doc\n\nthird\n")
        texts = load_corpus(str(p), 10)
        assert texts == ["hello world", "second doc", "third"]

    def test_load_corpus_truncates(self):
        assert len(load_corpus("synthetic", 5)) == 5

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            load_corpus("no-such-dataset", 5)


@pytest.fixture(scope="module")
def tok_and_tokens(tmp_path_factory):
    texts = synthetic_corpus(300, seed=3)
    d = tmp_path_factory.mktemp("tok")
    tok = train_bpe_tokenizer(texts, vocab_size=600, min_frequency=2, save_dir=str(d))
    tokens = encode_corpus(tok, texts)
    return tok, tokens, texts, str(d)


class TestTokenizer:
    def test_vocab_and_specials(self, tok_and_tokens):
        tok, tokens, texts, d = tok_and_tokens
        assert tok.token_to_id(EOT) is not None
        assert tok.token_to_id("<|pad|>") is not None
        assert tok.get_vocab_size() <= 600

    def test_eot_after_each_doc(self, tok_and_tokens):
        """train.py:167-170: one EOT id per document."""
        tok, tokens, texts, d = tok_and_tokens
        eot = tok.token_to_id(EOT)
        assert (tokens == eot).sum() == len(texts)
        assert tokens[-1] == eot

    def test_roundtrip(self, tok_and_tokens):
        tok, tokens, texts, d = tok_and_tokens
        enc = tok.encode(texts[0])
        assert tok.decode(enc.ids) == texts[0]

    def test_save_load(self, tok_and_tokens):
        tok, tokens, texts, d = tok_and_tokens
        tok2 = load_tokenizer(d)
        assert tok2.encode(texts[5]).ids == tok.encode(texts[5]).ids

    def test_dtype(self, tok_and_tokens):
        _, tokens, _, _ = tok_and_tokens
        assert tokens.dtype == np.int32


class TestWindows:
    def test_split(self):
        tokens = np.arange(100, dtype=np.int32)
        tr, va = split_tokens(tokens, 0.1)
        assert len(tr) == 90 and len(va) == 10
        np.testing.assert_array_equal(np.concatenate([tr, va]), tokens)

    def test_window_semantics(self):
        """train.py:104-107: window i is tokens[i:i+B], target shifted 1."""
        tokens = np.arange(50, dtype=np.int32)
        ds = TokenWindows(tokens, block_size=8)
        assert len(ds) == 42
        b = ds.batch(np.asarray([0, 5]))
        np.testing.assert_array_equal(np.asarray(b["x"][0]), np.arange(8))
        np.testing.assert_array_equal(np.asarray(b["y"][0]), np.arange(1, 9))
        np.testing.assert_array_equal(np.asarray(b["x"][1]), np.arange(5, 13))
        np.testing.assert_array_equal(np.asarray(b["y"][1]), np.arange(6, 14))

    def test_sequential_batches_cover_prefix(self):
        tokens = np.arange(200, dtype=np.int32)
        ds = TokenWindows(tokens, block_size=4)
        b0 = ds.sequential_batch(0, 8)
        b1 = ds.sequential_batch(1, 8)
        assert int(b0["x"][0, 0]) == 0
        assert int(b1["x"][0, 0]) == 8  # next 8 windows

    def test_random_batches_shape_and_range(self):
        tokens = np.arange(300, dtype=np.int32)
        ds = TokenWindows(tokens, block_size=16)
        rng = np.random.default_rng(0)
        b = ds.random_batches(rng, batch_size=4, n_batches=3)
        assert b["x"].shape == (3, 4, 16) and b["y"].shape == (3, 4, 16)
        # y == x + 1 for this arange corpus everywhere
        np.testing.assert_array_equal(np.asarray(b["y"]), np.asarray(b["x"]) + 1)

    def test_too_short_raises(self):
        with pytest.raises(ValueError):
            TokenWindows(np.arange(5, dtype=np.int32), block_size=8)

    def test_sequential_batch_too_large_raises(self):
        """A tiny val split must fail loudly rather than let the gather
        clamp offsets into silently duplicated eval windows."""
        ds = TokenWindows(np.arange(12, dtype=np.int32), block_size=8)  # 4 windows
        with pytest.raises(ValueError, match="exceeds"):
            ds.sequential_batch(0, 32)
