"""Worker for the REAL 2-process distributed test (test_multihost_2proc.py).

Run as:  python mh2_worker.py <rank> <port> <workdir>

Each worker is one jax process with 4 virtual CPU devices (the parent
sets XLA_FLAGS); ``jax.distributed.initialize`` joins them into one
8-device 2-process runtime — the genuine multi-process regime the
single-process faked-slice tests (test_multihost.py) cannot reach. The
worker runs the FULL trainer (train/trainer.py) twice: sharded steps
over a data×fsdp mesh with a batched eval and checkpoint saves, then a
resume from the rescue checkpoint — train data, eval data, and
checkpoint save/load, the three paths that must survive non-addressable
sharded state.

Platform/collectives config must happen before any backend use: the
container's sitecustomize imports jax (and pins JAX_PLATFORMS) at
interpreter start, so env vars alone are too late — same trick as
tests/conftest.py.
"""

import os
import sys


def main() -> None:
    rank, port, workdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", False)
    # cross-process CPU collectives (the psum/allgather between the two
    # processes) need an explicit implementation; TPU pods don't (ICI/DCN)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=rank
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.local_devices()) == 4, jax.local_devices()
    assert len(jax.devices()) == 8, jax.devices()

    # per-rank cwd: the corpus/tokenizer cache is cwd-relative and both
    # ranks build it independently (deterministic — same seed, same bytes)
    cwd = os.path.join(workdir, f"rank{rank}")
    os.makedirs(cwd, exist_ok=True)
    os.chdir(cwd)

    from jax.experimental import multihost_utils

    from differential_transformer_replication_tpu.config import (
        MeshConfig,
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.trainer import train

    cfg = TrainConfig(
        model=ModelConfig(
            model="diff",
            vocab_size=300,
            n_embd=64,
            n_head=2,
            n_layer=2,
            block_size=32,
            dropout=0.0,
            compute_dtype="float32",
            attention_impl="xla",
        ),
        mesh=MeshConfig(data=4, fsdp=2),
        micro_batch_size=8,
        grad_acc_steps=1,
        max_iters=4,
        eval_interval=2,
        eval_iters=2,
        log_interval=1,
        dataset="synthetic",
        num_train_samples=200,
        vocab_size=300,
        seed=3,
        metrics_path=os.path.join(workdir, "metrics_2proc.jsonl"),
        checkpoint_path=os.path.join(workdir, "best.ckpt"),
        last_checkpoint_path=os.path.join(workdir, "last.ckpt"),
    )
    train(cfg)

    # the primary's rescue-checkpoint write must be on disk before EITHER
    # process tries to resume from it
    multihost_utils.sync_global_devices("ckpt_written")

    # resume from the rescue checkpoint and continue to 6 iters: exercises
    # load -> collective gather of the sharded target -> re-placement onto
    # the 2-process mesh
    cfg2 = cfg.replace(
        max_iters=6,
        resume_from=os.path.join(workdir, "last.ckpt"),
        metrics_path=os.path.join(workdir, "metrics_2proc_resume.jsonl"),
    )
    train(cfg2)

    with open(os.path.join(workdir, f"done_{rank}"), "w") as f:
        f.write("ok")


if __name__ == "__main__":
    main()
