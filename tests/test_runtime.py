"""Runtime integration tests: end-to-end tiny training run, checkpoint
round-trip + resume, save_pretrained/from_pretrained for all families,
metrics output."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.train import (
    create_train_state,
    from_pretrained,
    load_checkpoint,
    save_checkpoint,
    save_pretrained,
    train,
)

TINY_MODEL = dict(vocab_size=256, n_embd=32, n_head=2, n_layer=2, block_size=16,
                  dropout=0.0, compute_dtype="float32")


def tiny_cfg(tmp_path, **kw):
    defaults = dict(
        vocab_size=256,
        dataset="synthetic",
        num_train_samples=200,
        micro_batch_size=4,
        grad_acc_steps=1,
        max_iters=30,
        eval_interval=15,
        eval_iters=3,
        log_interval=5,
        learning_rate=3e-3,
        min_lr=3e-4,
        warmup_iters=5,
        control_head_multiplier=1,
        tokenizer_dir=str(tmp_path / "tokenizer"),
        checkpoint_path=str(tmp_path / "ckpt"),
        last_checkpoint_path=str(tmp_path / "last_ckpt"),
        metrics_path=str(tmp_path / "metrics.jsonl"),
        seed=7,
    )
    model_kw = kw.pop("model_kw", {})
    return TrainConfig(
        model=ModelConfig(model=kw.pop("model", "diff"), **{**TINY_MODEL, **model_kw}),
        **{**defaults, **kw},
    )


class TestEndToEnd:
    def test_full_train_run(self, tmp_path, capsys):
        """The minimum end-to-end slice (SURVEY.md section 7.3): synthetic
        corpus -> BPE -> windows -> jitted steps; loss decreases; best
        checkpoint written; metrics emitted at the reference cadence."""
        cfg = tiny_cfg(tmp_path)
        state = train(cfg)
        assert int(state["step"]) == 30
        captured = capsys.readouterr().out
        assert "iter 5: loss" in captured  # log_interval cadence
        assert "step 15: train loss" in captured  # eval cadence
        assert os.path.isdir(cfg.checkpoint_path)

        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        step_lines = [l for l in lines if "loss" in l]
        eval_lines = [l for l in lines if "val_loss" in l]
        assert len(step_lines) == 6 and len(eval_lines) == 2
        assert {"iter", "loss", "learning_rate", "ts"} <= set(step_lines[0])
        # platforms without memory stats (the CPU the suite pins via
        # conftest) OMIT the key — never a misleading 0.0; platforms
        # with stats log a real positive value
        from differential_transformer_replication_tpu.train.metrics import (
            device_memory_mb,
        )

        if device_memory_mb() is None:
            assert "gpu_memory" not in step_lines[0]
        else:
            assert step_lines[0]["gpu_memory"] > 0
        # one run_header identity record opens the stream
        assert lines[0].get("record") == "run_header"
        assert {"config_hash", "jax_version", "process_count"} <= set(lines[0])
        # loss must decrease over the run
        assert step_lines[-1]["loss"] < step_lines[0]["loss"]

    def test_train_on_mesh(self, tmp_path):
        """train() with mesh.n_devices > 1 must take the sharded path end
        to end (the CLI's --data-parallel/--tensor-parallel wiring)."""
        from differential_transformer_replication_tpu.config import MeshConfig

        cfg = tiny_cfg(
            tmp_path,
            max_iters=10,
            eval_interval=5,
            micro_batch_size=4,
            model_kw=dict(vocab_size=256, n_head=2),
        ).replace(mesh=MeshConfig(data=2, tensor=2))
        state = train(cfg)
        assert int(jax.device_get(state["step"])) == 10

    def test_resume_continues(self, tmp_path):
        cfg = tiny_cfg(tmp_path, max_iters=15, eval_interval=10)
        train(cfg)
        cfg2 = cfg.replace(max_iters=20, resume_from=cfg.checkpoint_path)
        state = train(cfg2)
        assert int(state["step"]) == 20

    def test_last_checkpoint_written_and_resumable(self, tmp_path):
        """The preemption-safety checkpoint (SURVEY.md section 5.3): the
        trainer writes a resumable last-state checkpoint on exit — unlike
        the best-val checkpoint it reflects the FINAL step, so resume
        continues exactly where the run stopped."""
        import os

        from differential_transformer_replication_tpu.train.checkpoint import (
            load_checkpoint,
        )
        from differential_transformer_replication_tpu.train.step import (
            create_train_state,
        )

        cfg = tiny_cfg(tmp_path, max_iters=12, eval_interval=10)
        train(cfg)
        assert os.path.isfile(
            os.path.join(cfg.last_checkpoint_path, "state.msgpack")
        )
        target = jax.device_get(create_train_state(jax.random.PRNGKey(0), cfg))
        restored, _ = load_checkpoint(cfg.last_checkpoint_path, cfg, target)
        # best-val was written at iter 10; last reflects the final step 12
        assert int(restored["step"]) == 12
        cfg2 = cfg.replace(max_iters=16, resume_from=cfg.last_checkpoint_path)
        state = train(cfg2)
        assert int(state["step"]) == 16


class TestCheckpoint:
    def test_train_checkpoint_roundtrip(self, tmp_path):
        cfg = tiny_cfg(tmp_path)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        state["step"] = jnp.asarray(17, jnp.int32)
        save_checkpoint(str(tmp_path / "c"), state, 1.23, cfg)
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        restored, best = load_checkpoint(str(tmp_path / "c"), cfg, target)
        assert best == pytest.approx(1.23)
        assert int(restored["step"]) == 17
        for a, b in zip(
            jax.tree_util.tree_leaves(state["params"]),
            jax.tree_util.tree_leaves(restored["params"]),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
    def test_save_pretrained_all_families(self, tmp_path, kind):
        """Generalizes Ndiff_transformer.py:243-265 to every family: the
        checkpoint is self-describing — from_pretrained needs no config."""
        mc = ModelConfig(model=kind, **TINY_MODEL)
        params = init_model(jax.random.PRNGKey(0), mc)
        save_pretrained(str(tmp_path / kind), params, mc)
        params2, mc2 = from_pretrained(str(tmp_path / kind))
        assert mc2 == mc
        idx = jnp.arange(8)[None]
        l1, _ = model_forward(params, idx, mc)
        l2, _ = model_forward(params2, idx, mc2)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


class TestCheckpointThrottle:
    def test_throttled_best_save_flushes_at_exit(self, tmp_path, capsys):
        """checkpoint_min_interval_s larger than the run: the FIRST
        improvement writes immediately (the throttle clock is seeded one
        interval in the past), every later improvement only snapshots
        on-device, and the pending snapshot is flushed at exit AFTER the
        rescue save — so best.ckpt always ends identical to the
        write-every-improvement behavior (round-4 finding: a recipe-scale
        best write costs ~3 min on a tunneled chip and early training
        improves on every eval)."""
        import numpy as np

        from differential_transformer_replication_tpu.train import (
            load_checkpoint,
        )
        from differential_transformer_replication_tpu.train.step import (
            create_train_state,
        )

        cfg = tiny_cfg(tmp_path, checkpoint_min_interval_s=1e9)
        state = train(cfg)
        out = capsys.readouterr().out
        improvements = out.count("Saving best model")
        assert improvements >= 1
        if improvements >= 2:
            # the 2nd+ improvements were deferred; their snapshot must
            # have been flushed at exit
            assert "writing pending best checkpoint" in out
        else:  # pragma: no cover - seed-dependent fallback
            assert "writing pending best checkpoint" not in out
        assert os.path.isdir(cfg.checkpoint_path)
        target = create_train_state(jax.random.PRNGKey(0), cfg)
        restored, best_val = load_checkpoint(cfg.checkpoint_path, cfg, target)
        assert np.isfinite(best_val)
        # the snapshot is from a best-eval iteration, not necessarily the
        # final step — but it must be a real trained state
        assert int(restored["step"]) > 0

    def test_zero_interval_keeps_reference_behavior(self, tmp_path, capsys):
        """interval 0 (default): every improvement writes immediately and
        no pending flush remains at exit (train.py:307-317 parity)."""
        cfg = tiny_cfg(tmp_path)  # default interval 0
        train(cfg)
        out = capsys.readouterr().out
        assert "Saving best model" in out
        assert "writing pending best checkpoint" not in out


class TestTokenizerFingerprint:
    def test_checkpoint_records_and_guard_verifies(self, tmp_path):
        """Checkpoints record the tokenizer's content fingerprint, and
        check_tokenizer_matches rejects a SAME-SIZE different tokenizer
        (vocab-size equality alone cannot catch a clobbered shared
        tokenizer dir — every run targets the same vocab size)."""
        import json as _json
        import os

        import pytest as _pytest

        from differential_transformer_replication_tpu.data.tokenizer import (
            check_tokenizer_matches,
            load_tokenizer,
            tokenizer_fingerprint,
        )

        cfg = tiny_cfg(tmp_path, max_iters=6, eval_interval=5)
        train(cfg)
        meta = _json.load(
            open(os.path.join(cfg.checkpoint_path, "meta.json"))
        )
        fp = meta.get("tokenizer_fingerprint")
        assert fp, "checkpoint meta must record the tokenizer fingerprint"

        cache = next(
            d for d in os.listdir(cfg.tokenizer_dir) if d.startswith("cache-")
        )
        tok = load_tokenizer(os.path.join(cfg.tokenizer_dir, cache))
        assert tokenizer_fingerprint(tok) == fp
        # matching tokenizer passes both checks
        check_tokenizer_matches(tok, tok.get_vocab_size(), fp)
        # same size, different content -> fail loud
        with _pytest.raises(SystemExit, match="fingerprint"):
            check_tokenizer_matches(tok, tok.get_vocab_size(), "0" * 16)
        # wrong size -> fail loud regardless of fingerprint
        with _pytest.raises(SystemExit, match="vocab"):
            check_tokenizer_matches(tok, tok.get_vocab_size() + 1)
