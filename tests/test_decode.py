"""KV-cache decode parity: the chunked cache path must reproduce the full
forward's logits exactly (same math, different schedule) for all three
model families, in prefill and in token-by-token decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import (
    generate,
    init_model,
    model_forward,
)
from differential_transformer_replication_tpu.models.decode import (
    forward_chunk,
    generate_cached,
    init_cache,
)


def _cfg(kind):
    return ModelConfig(
        model=kind, vocab_size=97, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_prefill_matches_full_forward(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    ref, _ = model_forward(params, idx, cfg)
    cache = init_cache(cfg, 2)
    got, _ = forward_chunk(params, idx, 0, cache, cfg)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_incremental_decode_matches_full_forward(kind):
    """Teacher-forced: prefill 8 tokens, then feed 6 more one at a time;
    at every step the cached logits must equal a from-scratch forward
    over the growing prefix."""
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(2), (2, 14), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2)
    logits, cache = forward_chunk(params, seq[:, :8], 0, cache, cfg)
    ref_full, _ = model_forward(params, seq[:, :8], cfg)
    np.testing.assert_allclose(logits[:, -1], ref_full[:, -1], rtol=1e-4, atol=1e-4)
    for t in range(8, 14):
        logits, cache = forward_chunk(params, seq[:, t : t + 1], t, cache, cfg)
        ref_full, _ = model_forward(params, seq[:, : t + 1], cfg)
        np.testing.assert_allclose(
            logits[:, -1], ref_full[:, -1], rtol=1e-4, atol=1e-4,
            err_msg=f"divergence at position {t}",
        )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_generate_cached_contract(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    out = generate_cached(params, idx, cfg, 10, jax.random.PRNGKey(4))
    assert out.shape == (2, 15)
    np.testing.assert_array_equal(out[:, :5], idx)  # prompt preserved
    assert int(out.max()) < cfg.vocab_size and int(out.min()) >= 0


def test_generate_cached_rejects_overflow_for_diff_only():
    """The diff family's learned absolute position table cannot roll with
    a KV cache (each window slide re-embeds every cached position), so it
    keeps the hard bound; the RoPE families ride the ring cache past
    block_size (tests below)."""
    cfg = _cfg("diff")
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError):
        generate_cached(params, idx, cfg, 10, jax.random.PRNGKey(0))


def test_generate_and_cached_agree_on_argmax_path():
    """With near-deterministic logits the two generators walk the same
    sequence: compare greedy continuations computed from each path's
    logits rather than sampled tokens (sampling consumes rng differently).
    Here: decode 5 steps teacher-forced on generate()'s output and check
    the cached path assigns the same argmax at every position."""
    cfg = _cfg("control")
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, cfg.vocab_size)
    full = generate(params, idx, cfg, 5, jax.random.PRNGKey(6))  # (1, 9)
    cache = init_cache(cfg, 1)
    logits_c, cache = forward_chunk(params, full[:, :4], 0, cache, cfg)
    for t in range(4, 9):
        ref_logits, _ = model_forward(params, full[:, : t], cfg)
        np.testing.assert_array_equal(
            jnp.argmax(logits_c[:, -1], -1), jnp.argmax(ref_logits[:, -1], -1)
        )
        if t < 8:
            logits_c, cache = forward_chunk(params, full[:, t : t + 1], t, cache, cfg)


def test_forward_chunk_rejects_invalid_chunks():
    """Concrete positions fail loudly where the cache cannot represent
    them: any past-block_size position for diff (absolute position
    table), and ring-boundary-WRAPPING multi-token chunks for everyone
    (the slice write would clamp); a single token at pos == block_size
    is the valid rolling case for RoPE families."""
    params_d = init_model(jax.random.PRNGKey(0), _cfg("diff"))
    cache_d = init_cache(_cfg("diff"), 1)
    tok = jnp.zeros((1, 1), jnp.int32)
    with pytest.raises(ValueError):
        forward_chunk(params_d, tok, _cfg("diff").block_size, cache_d, _cfg("diff"))

    cfg = _cfg("control")
    params = init_model(jax.random.PRNGKey(0), cfg)
    cache = init_cache(cfg, 1)
    with pytest.raises(ValueError):  # 28+8 wraps the 32-slot ring
        forward_chunk(params, jnp.zeros((1, 8), jnp.int32), 28, cache, cfg)
    # rolling single-token writes are legal past block_size
    logits, _ = forward_chunk(
        params, tok, cfg.block_size, cache, cfg, rope_len=cfg.block_size + 1
    )
    assert bool(jnp.isfinite(logits).all())


def _cfg1(kind):
    """Single-layer variant: the only depth at which the reference's
    crop-recompute and sliding-window caching coincide exactly past the
    block boundary (at depth >= 2 the crop changes every remaining
    position's deep activations each step — Omega(M^2)/token by
    construction, models/decode.py module docstring)."""
    return ModelConfig(
        model=kind, vocab_size=97, n_embd=32, n_head=2, n_layer=1,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@pytest.mark.parametrize("kind", ["control", "ndiff"])
def test_rolling_decode_matches_windowed_forward_single_layer(kind):
    """Past block_size the ring cache equals the reference's crop
    semantics (control.py:163-171) EXACTLY at depth 1: teacher-force a
    sequence of 2.5x block_size one token at a time and compare every
    step's logits with a from-scratch forward over the cropped last
    block_size tokens. RoPE's relative-position property makes the
    absolute-position cache and the rebased crop mathematically equal."""
    cfg = _cfg1(kind)  # block_size 32
    M = cfg.block_size
    params = init_model(jax.random.PRNGKey(0), cfg)
    total = 2 * M + M // 2
    seq = jax.random.randint(jax.random.PRNGKey(7), (2, total), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2)
    logits, cache = forward_chunk(
        params, seq[:, :8], 0, cache, cfg, rope_len=total
    )
    for t in range(8, total):
        logits, cache = forward_chunk(
            params, seq[:, t : t + 1], t, cache, cfg, rope_len=total
        )
        lo = max(0, t + 1 - M)
        ref_full, _ = model_forward(params, seq[:, lo : t + 1], cfg)
        np.testing.assert_allclose(
            logits[:, -1], ref_full[:, -1], rtol=2e-4, atol=2e-4,
            err_msg=f"divergence at position {t} (window [{lo}, {t}])",
        )


@pytest.mark.parametrize("kind", ["control", "ndiff"])
def test_ring_indexing_matches_append_oracle(kind):
    """Deep-model check of the ring arithmetic itself: an oracle with a
    cache big enough to NEVER wrap (block_size = whole sequence) plus an
    explicit ``window`` visibility clip implements the same
    sliding-window semantics with trivial append indexing; the ring path
    must match it through two full wraps. This isolates slot/mask bugs
    from the (expected, documented) semantic divergence vs the crop
    recompute at depth >= 2."""
    cfg = _cfg(kind)  # 2 layers, block_size 32
    M = cfg.block_size
    params = init_model(jax.random.PRNGKey(0), cfg)
    total = 2 * M + 8
    seq = jax.random.randint(jax.random.PRNGKey(11), (1, total), 0, cfg.vocab_size)

    def run(run_cfg, window):
        cache = init_cache(run_cfg, 1)
        out = []
        logits, cache = forward_chunk(
            params, seq[:, :8], 0, cache, run_cfg, rope_len=total, window=window
        )
        out.append(logits[:, -1])
        for t in range(8, total):
            logits, cache = forward_chunk(
                params, seq[:, t : t + 1], t, cache, run_cfg,
                rope_len=total, window=window,
            )
            out.append(logits[:, -1])
        return out

    ring = run(cfg, 0)  # ring of M slots, default window
    oracle = run(cfg.replace(block_size=total), M)  # append cache + clip
    for i, (r, o) in enumerate(zip(ring, oracle)):
        np.testing.assert_allclose(
            np.asarray(r), np.asarray(o), rtol=1e-5, atol=1e-5,
            err_msg=f"ring/oracle divergence at step {i}",
        )


def test_generate_cached_rolls_past_block_size_greedy_parity():
    """End-to-end at depth 1 (where cache and crop semantics coincide):
    generate_cached past block_size walks the same greedy sequence as the
    windowed generate (which recomputes the cropped O(T^2) forward per
    token), including a prompt longer than block_size (cropped like
    control.py:165)."""
    cfg = _cfg1("control")  # 1 layer, block_size 32
    params = init_model(jax.random.PRNGKey(0), cfg)
    rng = jax.random.PRNGKey(8)
    idx = jax.random.randint(jax.random.PRNGKey(9), (2, 6), 0, cfg.vocab_size)
    full = generate(params, idx, cfg, 60, rng, temperature=0.0)
    cached = generate_cached(params, idx, cfg, 60, rng, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    # long prompt: both paths crop to the last block_size tokens
    long_idx = jax.random.randint(
        jax.random.PRNGKey(10), (1, 40), 0, cfg.vocab_size
    )
    cropped = generate(
        params, long_idx[:, -cfg.block_size:], cfg, 12, rng, temperature=0.0
    )
    cached_long = generate_cached(params, long_idx, cfg, 12, rng, temperature=0.0)
    np.testing.assert_array_equal(
        np.asarray(cropped[:, -12:]), np.asarray(cached_long[:, -12:])
    )


def test_generate_cached_deep_model_rolls_finite():
    """Depth >= 2 past the boundary: the documented sliding-window
    semantics — outputs finite, prompt preserved, in-vocab, and the
    in-window prefix (where cache == crop exactly) matches the windowed
    generate under greedy decoding."""
    cfg = _cfg("control")  # 2 layers, block_size 32
    params = init_model(jax.random.PRNGKey(0), cfg)
    rng = jax.random.PRNGKey(12)
    idx = jax.random.randint(jax.random.PRNGKey(13), (2, 6), 0, cfg.vocab_size)
    out = generate_cached(params, idx, cfg, 50, rng, temperature=0.0)
    assert out.shape == (2, 56)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(idx))
    assert int(out.max()) < cfg.vocab_size and int(out.min()) >= 0
    ref = generate(params, idx, cfg, 50, rng, temperature=0.0)
    # identical while the window still starts at 0 (positions < block_size)
    np.testing.assert_array_equal(
        np.asarray(ref[:, : cfg.block_size]), np.asarray(out[:, : cfg.block_size])
    )


class TestSamplingOptions:
    """temperature/top_k extensions (models/generate.py:sample_token) —
    defaults must be bit-identical to the reference contract."""

    def test_defaults_bit_identical_to_reference_contract(self):
        from differential_transformer_replication_tpu.models.generate import (
            sample_token,
        )

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)
        key = jax.random.PRNGKey(1)
        ref = jax.random.categorical(key, logits, axis=-1)
        np.testing.assert_array_equal(np.asarray(sample_token(key, logits)),
                                      np.asarray(ref))

    def test_greedy_and_topk(self):
        from differential_transformer_replication_tpu.models.generate import (
            sample_token,
        )

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)
        key = jax.random.PRNGKey(1)
        # temperature 0 -> argmax; top_k=1 -> argmax regardless of key
        np.testing.assert_array_equal(
            np.asarray(sample_token(key, logits, temperature=0.0)),
            np.asarray(jnp.argmax(logits, -1)),
        )
        np.testing.assert_array_equal(
            np.asarray(sample_token(key, logits, top_k=1)),
            np.asarray(jnp.argmax(logits, -1)),
        )
        # top_k=5: every draw lands in the per-row top-5 set
        topk = jax.lax.top_k(logits, 5)[1]
        for s in range(20):
            draws = sample_token(jax.random.PRNGKey(s), logits, top_k=5)
            for b in range(4):
                assert int(draws[b]) in set(np.asarray(topk[b]).tolist())

    def test_generate_paths_accept_options(self):
        cfg = _cfg("control")
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0, cfg.vocab_size)
        rng = jax.random.PRNGKey(6)
        g1 = generate(params, idx, cfg, 5, rng, temperature=0.0)
        g2 = generate_cached(params, idx, cfg, 5, rng, temperature=0.0)
        # greedy decode is deterministic, so windowed and cached paths agree
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        g3 = generate(params, idx, cfg, 5, rng, temperature=0.7, top_k=8)
        assert g3.shape == (2, 9)
