"""KV-cache decode parity: the chunked cache path must reproduce the full
forward's logits exactly (same math, different schedule) for all three
model families, in prefill and in token-by-token decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import (
    generate,
    init_model,
    model_forward,
)
from differential_transformer_replication_tpu.models.decode import (
    forward_chunk,
    generate_cached,
    init_cache,
)


def _cfg(kind):
    return ModelConfig(
        model=kind, vocab_size=97, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_prefill_matches_full_forward(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    ref, _ = model_forward(params, idx, cfg)
    cache = init_cache(cfg, 2)
    got, _ = forward_chunk(params, idx, 0, cache, cfg)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_incremental_decode_matches_full_forward(kind):
    """Teacher-forced: prefill 8 tokens, then feed 6 more one at a time;
    at every step the cached logits must equal a from-scratch forward
    over the growing prefix."""
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(2), (2, 14), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2)
    logits, cache = forward_chunk(params, seq[:, :8], 0, cache, cfg)
    ref_full, _ = model_forward(params, seq[:, :8], cfg)
    np.testing.assert_allclose(logits[:, -1], ref_full[:, -1], rtol=1e-4, atol=1e-4)
    for t in range(8, 14):
        logits, cache = forward_chunk(params, seq[:, t : t + 1], t, cache, cfg)
        ref_full, _ = model_forward(params, seq[:, : t + 1], cfg)
        np.testing.assert_allclose(
            logits[:, -1], ref_full[:, -1], rtol=1e-4, atol=1e-4,
            err_msg=f"divergence at position {t}",
        )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_generate_cached_contract(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    out = generate_cached(params, idx, cfg, 10, jax.random.PRNGKey(4))
    assert out.shape == (2, 15)
    np.testing.assert_array_equal(out[:, :5], idx)  # prompt preserved
    assert int(out.max()) < cfg.vocab_size and int(out.min()) >= 0


def test_generate_cached_rejects_overflow():
    cfg = _cfg("diff")
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError):
        generate_cached(params, idx, cfg, 10, jax.random.PRNGKey(0))


def test_generate_and_cached_agree_on_argmax_path():
    """With near-deterministic logits the two generators walk the same
    sequence: compare greedy continuations computed from each path's
    logits rather than sampled tokens (sampling consumes rng differently).
    Here: decode 5 steps teacher-forced on generate()'s output and check
    the cached path assigns the same argmax at every position."""
    cfg = _cfg("control")
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, cfg.vocab_size)
    full = generate(params, idx, cfg, 5, jax.random.PRNGKey(6))  # (1, 9)
    cache = init_cache(cfg, 1)
    logits_c, cache = forward_chunk(params, full[:, :4], 0, cache, cfg)
    for t in range(4, 9):
        ref_logits, _ = model_forward(params, full[:, : t], cfg)
        np.testing.assert_array_equal(
            jnp.argmax(logits_c[:, -1], -1), jnp.argmax(ref_logits[:, -1], -1)
        )
        if t < 8:
            logits_c, cache = forward_chunk(params, full[:, t : t + 1], t, cache, cfg)


def test_forward_chunk_rejects_cache_overflow():
    """Concrete positions past block_size fail loudly instead of letting
    dynamic_update_slice clamp and corrupt the last cache slot."""
    cfg = _cfg("control")
    params = init_model(jax.random.PRNGKey(0), cfg)
    cache = init_cache(cfg, 1)
    tok = jnp.zeros((1, 1), jnp.int32)
    with pytest.raises(ValueError):
        forward_chunk(params, tok, cfg.block_size, cache, cfg)
    with pytest.raises(ValueError):
        forward_chunk(params, jnp.zeros((1, 8), jnp.int32), 28, cache, cfg)


class TestSamplingOptions:
    """temperature/top_k extensions (models/generate.py:sample_token) —
    defaults must be bit-identical to the reference contract."""

    def test_defaults_bit_identical_to_reference_contract(self):
        from differential_transformer_replication_tpu.models.generate import (
            sample_token,
        )

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)
        key = jax.random.PRNGKey(1)
        ref = jax.random.categorical(key, logits, axis=-1)
        np.testing.assert_array_equal(np.asarray(sample_token(key, logits)),
                                      np.asarray(ref))

    def test_greedy_and_topk(self):
        from differential_transformer_replication_tpu.models.generate import (
            sample_token,
        )

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)
        key = jax.random.PRNGKey(1)
        # temperature 0 -> argmax; top_k=1 -> argmax regardless of key
        np.testing.assert_array_equal(
            np.asarray(sample_token(key, logits, temperature=0.0)),
            np.asarray(jnp.argmax(logits, -1)),
        )
        np.testing.assert_array_equal(
            np.asarray(sample_token(key, logits, top_k=1)),
            np.asarray(jnp.argmax(logits, -1)),
        )
        # top_k=5: every draw lands in the per-row top-5 set
        topk = jax.lax.top_k(logits, 5)[1]
        for s in range(20):
            draws = sample_token(jax.random.PRNGKey(s), logits, top_k=5)
            for b in range(4):
                assert int(draws[b]) in set(np.asarray(topk[b]).tolist())

    def test_generate_paths_accept_options(self):
        cfg = _cfg("control")
        params = init_model(jax.random.PRNGKey(0), cfg)
        idx = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0, cfg.vocab_size)
        rng = jax.random.PRNGKey(6)
        g1 = generate(params, idx, cfg, 5, rng, temperature=0.0)
        g2 = generate_cached(params, idx, cfg, 5, rng, temperature=0.0)
        # greedy decode is deterministic, so windowed and cached paths agree
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        g3 = generate(params, idx, cfg, 5, rng, temperature=0.7, top_k=8)
        assert g3.shape == (2, 9)
