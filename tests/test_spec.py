"""Speculative decoding subsystem (serving/spec.py + the fused
multi-row verify step).

The load-bearing contracts:

- **Greedy bit-parity**: a spec-enabled engine's greedy output is
  IDENTICAL to non-spec ``generate_cached`` for all three target
  families, both decode impls, both KV dtypes, contiguous and paged
  pools, both verify formulations — speculation is a scheduler over
  the same math, never a different model. An arbitrarily bad drafter
  (random weights, poisoned pool, 0%-acceptance storm) can only cost
  throughput, never correctness.
- **The compile ladder**: mixed spec/non-spec traffic and varying
  per-request draft lengths ride runtime arrays through a FIXED set of
  compiled step programs — decode stays at 1 entry and the spec rung
  within its two accept variants, RecompileSentinel-gated.
- **Lock discipline**: the drafters are lock-owning classes shared
  between the engine thread and /health readers; the GL301 mutation
  test proves graftlint actually guards their state.
"""

import json
import subprocess
import sys
from functools import lru_cache
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    ModelDrafter,
    NGramDrafter,
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.spec import (
    DraftSlot,
    build_drafter,
)
from differential_transformer_replication_tpu.utils import faults

REPO = Path(__file__).resolve().parents[1]


def _cfg(kind, impl="xla", kvd="auto", vocab=61, n_embd=32, n_layer=2,
         block=32):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=n_embd, n_head=2,
        n_layer=n_layer, block_size=block, dropout=0.0, n_terms=3,
        compute_dtype="float32", decode_attention_impl=impl,
        kv_cache_dtype=kvd,
    )


@lru_cache(maxsize=None)
def _setup(kind, impl="xla", kvd="auto"):
    cfg = _cfg(kind, impl, kvd)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=n).tolist() for n in lens]


@lru_cache(maxsize=None)
def _ref_greedy_all(kind, impl, kvd, lens, n, seed=1):
    cfg, params = _setup(kind, impl, kvd)
    outs = []
    for p in _prompts(list(lens), cfg.vocab_size, seed):
        out = generate_cached(
            params, jnp.asarray(p, jnp.int32)[None], cfg, n,
            jax.random.PRNGKey(0), temperature=0.0,
        )
        outs.append(np.asarray(out)[0, len(p):].tolist())
    return outs


def _spec_serving(**kw):
    base = dict(num_slots=2, prefill_chunk=4, prefill_budget=8,
                spec_mode="ngram", spec_draft_len=4)
    base.update(kw)
    return ServingConfig(**base)


LENS = (3, 9, 14, 6)


class TestNGramDrafter:
    def _slot(self, toks, cap=4, index=0):
        return DraftSlot(index, toks, len(toks) - 1, cap)

    def test_lookup_proposes_continuation(self):
        d = NGramDrafter()
        out = d.propose_all([self._slot([1, 2, 3, 4, 2, 3])])
        # suffix (2, 3) matched at positions 1..2 -> continuation
        assert out == {0: [4, 2, 3]}

    def test_tail_self_match_is_excluded(self):
        # the tail trigram matches ITSELF at end-of-history; only an
        # EARLIER occurrence may propose
        d = NGramDrafter()
        assert d.propose_all([self._slot([5, 6, 7])]) == {}
        out = d.propose_all([self._slot([5, 6, 7, 5, 6])])
        assert out == {0: [7, 5, 6]}

    def test_most_recent_occurrence_wins(self):
        d = NGramDrafter()
        out = d.propose_all([self._slot([1, 9, 1, 8, 1], cap=1)])
        # 1-gram (1,): latest non-tail occurrence at index 2 -> 8
        assert out == {0: [8]}

    def test_cap_and_zero_cap(self):
        d = NGramDrafter()
        toks = [1, 2, 1, 2, 1, 2]
        out = d.propose_all([self._slot(toks, cap=2)])
        assert len(out[0]) == 2
        assert d.propose_all([self._slot(toks, cap=0)]) == {}

    def test_incremental_index_and_slot_reuse(self):
        d = NGramDrafter()
        d.propose_all([self._slot([1, 2, 3])])
        out = d.propose_all([self._slot([1, 2, 3, 1, 2])])
        assert out == {0: [3, 1, 2]}
        # slot reused by a SHORTER history: the map must rebuild
        out = d.propose_all([self._slot([7, 8])])
        assert out == {}
        d.release(0)
        assert d.propose_all([self._slot([1, 2, 3, 1, 2])]) == {
            0: [3, 1, 2]
        }

    def test_stats_counts_proposed(self):
        d = NGramDrafter()
        # tail trigram (1,2,1) matched at positions 0..2 -> the
        # continuation [2, 1] (history ends before the cap fills)
        out = d.propose_all([self._slot([1, 2, 1, 2, 1], cap=3)])
        assert out == {0: [2, 1]}
        st = d.stats()
        assert st["kind"] == "ngram"
        assert st["proposed_total"] == 2
        assert st["drafter_crashes_total"] == 0


# representative combos in the quick tier; the full matrix rides the
# slow tier (conftest honors explicit slow marks)
_QUICK_COMBOS = [
    ("control", "xla", "auto", 0, "exact"),
    ("control", "xla", "bf16", 8, "batched"),
    ("control", "pallas", "int8", 0, "batched"),
    ("control", "pallas", "auto", 8, "exact"),
    ("diff", "xla", "int8", 8, "exact"),
    ("ndiff", "pallas", "bf16", 0, "batched"),
]
_SLOW_COMBOS = [
    (kind, impl, kvd, page, verify)
    for kind in ("control", "diff", "ndiff")
    for impl in ("xla", "pallas")
    for kvd in ("auto", "bf16", "int8")
    for page in (0, 8)
    for verify in ("exact", "batched")
    if (kind, impl, kvd, page, verify) not in _QUICK_COMBOS
]


@pytest.mark.parametrize(
    "kind,impl,kvd,page,verify",
    _QUICK_COMBOS + [
        pytest.param(*c, marks=pytest.mark.slow) for c in _SLOW_COMBOS
    ],
)
def test_spec_greedy_bit_identical_to_generate_cached(
    kind, impl, kvd, page, verify
):
    """THE parity battery: ngram-spec greedy output through a 2-slot
    pool (queueing + slot reuse) equals sequential generate_cached
    for every family x impl x KV dtype x pool layout x verify mode."""
    cfg, params = _setup(kind, impl, kvd)
    prompts = _prompts(LENS, cfg.vocab_size)
    eng = ServingEngine(
        params, cfg,
        _spec_serving(kv_page_size=page, spec_verify=verify),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    refs = _ref_greedy_all(kind, impl, kvd, LENS, 8)
    for o, r in zip(outs, refs):
        assert o.tokens == r
    # something actually got drafted (the repetitive greedy outputs
    # feed the prompt-lookup) and the accounting is consistent
    st = eng.spec_stats()
    assert st["proposed"] >= st["accepted"] >= 0
    assert sum(o.spec_proposed for o in outs) == st["proposed"]
    assert sum(o.spec_accepted for o in outs) == st["accepted"]


def test_model_drafter_self_params_accepts_everything():
    """A drafter sharing the target's params proposes exactly the
    target's greedy continuations: acceptance 1.0, output unchanged —
    the upper bound of the verify machinery."""
    cfg, params = _setup("control")
    prompts = _prompts(LENS, cfg.vocab_size)
    eng = ServingEngine(
        params, cfg, _spec_serving(spec_mode="model"),
        spec_drafter=(params, cfg),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    refs = _ref_greedy_all("control", "xla", "auto", LENS, 8)
    for o, r in zip(outs, refs):
        assert o.tokens == r
    st = eng.spec_stats()
    assert st["acceptance_rate"] == 1.0
    assert st["proposed"] > 0
    assert st["drafter"]["kind"] == "model"


def test_random_control_drafter_beside_diff_target_stays_exact():
    """The paper's pairing with a RANDOM-INIT drafter: near-zero
    acceptance, bit-exact output — a bad drafter costs only
    throughput."""
    cfg, params = _setup("diff")
    d_cfg = _cfg("control", n_embd=16, n_layer=1, vocab=61)
    d_params = init_model(jax.random.PRNGKey(7), d_cfg)
    prompts = _prompts(LENS, cfg.vocab_size)
    eng = ServingEngine(
        params, cfg, _spec_serving(spec_mode="model"),
        spec_drafter=(d_params, d_cfg),
    )
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    refs = _ref_greedy_all("diff", "xla", "auto", LENS, 8)
    for o, r in zip(outs, refs):
        assert o.tokens == r
    assert eng.spec_stats()["proposed"] > 0


def test_drafter_vocab_mismatch_fails_loudly():
    cfg, params = _setup("control")
    d_cfg = _cfg("control", vocab=97)
    d_params = init_model(jax.random.PRNGKey(1), d_cfg)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(
            params, cfg, _spec_serving(spec_mode="model"),
            spec_drafter=(d_params, d_cfg),
        )


def test_exact_and_batched_verify_agree_at_test_scale():
    cfg, params = _setup("control")
    prompts = _prompts(LENS, cfg.vocab_size)

    def run(verify):
        eng = ServingEngine(
            params, cfg, _spec_serving(spec_verify=verify)
        )
        return [
            o.tokens
            for o in eng.generate(prompts, max_new_tokens=8,
                                  temperature=0.0)
        ]

    assert run("exact") == run("batched")


@pytest.mark.slow
def test_exact_verify_bit_identical_at_larger_width():
    """The scale-robustness pin the EXACT mode exists for: at widths
    where batched multi-row matmuls reassociate their reductions
    (contraction >= 512), the unrolled verify still bit-matches
    generate_cached."""
    cfg = _cfg("diff", vocab=512, n_embd=128, n_layer=3, block=128)
    params = init_model(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = []
    for _ in range(6):
        n = int(rng.integers(6, 15))
        period = int(rng.integers(2, 5))
        cyc = rng.integers(0, 512, size=period).tolist()
        prompts.append((cyc * (n // period + 1))[:n])
    eng = ServingEngine(
        params, cfg,
        _spec_serving(num_slots=4, spec_verify="exact",
                      spec_draft_len=6),
    )
    outs = eng.generate(prompts, max_new_tokens=32, temperature=0.0)
    for p, o in zip(prompts, outs):
        ref = generate_cached(
            params, jnp.asarray(p, jnp.int32)[None], cfg, 32,
            jax.random.PRNGKey(0), temperature=0.0,
        )
        assert o.tokens == np.asarray(ref)[0, len(p):].tolist()


def test_sampled_determinism_across_batch_compositions():
    """Spec-on sampled output stays a pure function of (params,
    prompt, sampling params): the fold_in key chains see neither slot
    assignment nor pool size nor admission order."""
    cfg, params = _setup("control")
    prompts = _prompts((4, 9, 6), cfg.vocab_size, seed=3)

    def run(num_slots, order):
        eng = ServingEngine(
            params, cfg, _spec_serving(num_slots=num_slots)
        )
        ids = {}
        for i in order:
            ids[eng.submit(prompts[i], temperature=1.0, top_k=5,
                           seed=7 + i, max_new_tokens=6)] = i
        return {ids[o.request_id]: o.tokens for o in eng.run()}

    assert run(2, [0, 1, 2]) == run(3, [2, 0, 1])


def test_per_request_draft_len_caps_and_disables():
    """SamplingParams.draft_len rides as a runtime cap: 0 disables
    speculation for that request alone; mixed traffic shares the one
    compiled rung."""
    cfg, params = _setup("control")
    prompts = _prompts((5, 5), cfg.vocab_size, seed=2)
    eng = ServingEngine(params, cfg, _spec_serving(num_slots=2))
    r0 = eng.submit(prompts[0], max_new_tokens=8, temperature=0.0,
                    draft_len=0)
    r1 = eng.submit(prompts[1], max_new_tokens=8, temperature=0.0)
    by_id = {o.request_id: o for o in eng.run()}
    assert by_id[r0].spec_proposed == 0
    refs = {
        rid: np.asarray(generate_cached(
            params, jnp.asarray(p, jnp.int32)[None], cfg, 8,
            jax.random.PRNGKey(0), temperature=0.0,
        ))[0, len(p):].tolist()
        for rid, p in ((r0, prompts[0]), (r1, prompts[1]))
    }
    assert by_id[r0].tokens == refs[r0]
    assert by_id[r1].tokens == refs[r1]


class TestCompileLadder:
    def test_decode_compiles_stay_within_the_ladder(self):
        """THE compile pin: spec/non-spec mixes, greedy and sampled
        requests, and per-request draft lengths varying 0..k must add
        NOTHING beyond the fixed ladder — decode 1 entry, the spec
        rung at most its two accept variants — and a second wave of
        different mixes compiles ZERO new programs."""
        from differential_transformer_replication_tpu.analysis.sanitizers import (
            RecompileSentinel,
        )

        # a PRIVATE config: the jitted closures are module-cached per
        # (cfg, shapes), so sharing _setup's cfg with other tests
        # would count their pool sizes as extra cache entries
        cfg = _cfg("control", vocab=67)
        params = init_model(jax.random.PRNGKey(0), cfg)
        prompts = _prompts((3, 7, 5, 9, 4, 6), cfg.vocab_size, seed=5)
        eng = ServingEngine(params, cfg, _spec_serving(num_slots=3))
        # first wave: greedy spec + sampled spec + per-request caps
        eng.generate(prompts[:2], max_new_tokens=8, temperature=0.0)
        eng.generate(prompts[2:4], max_new_tokens=6, temperature=1.0,
                     seed=3)
        eng.generate([prompts[4]], max_new_tokens=6, temperature=0.0,
                     draft_len=2)
        stats = eng.compile_stats()
        assert stats["decode"] == 1
        assert stats["spec_decode"] <= 2  # greedy + sampled variants
        # second wave, different mixes: zero new compiles
        with RecompileSentinel(budget=0, name="spec-ladder-window"):
            eng.generate([prompts[5]], max_new_tokens=5,
                         temperature=0.0, draft_len=1)
            eng.generate([prompts[0]], max_new_tokens=5,
                         temperature=1.0, seed=9)
        stats2 = eng.compile_stats()
        assert stats2["decode"] == 1
        assert stats2["spec_decode"] == stats["spec_decode"]

    def test_restart_adds_zero_recompiles(self):
        """A supervised crash-rebuild with spec on reuses every
        module-cached closure — drafter pool included."""
        from differential_transformer_replication_tpu.analysis.sanitizers import (
            RecompileSentinel,
        )

        cfg, params = _setup("control")
        prompts = _prompts((5, 8), cfg.vocab_size, seed=6)
        eng = ServingEngine(
            params, cfg, _spec_serving(spec_mode="model"),
            spec_drafter=(params, cfg),
        )
        eng.generate(prompts, max_new_tokens=6, temperature=0.0)
        with RecompileSentinel(budget=0, name="spec-restart-window"):
            lost = eng.reset_after_crash()
            assert lost == []
            outs = eng.generate(prompts, max_new_tokens=6,
                                temperature=0.0)
        refs = [
            np.asarray(generate_cached(
                params, jnp.asarray(p, jnp.int32)[None], cfg, 6,
                jax.random.PRNGKey(0), temperature=0.0,
            ))[0, len(p):].tolist()
            for p in prompts
        ]
        assert [o.tokens for o in outs] == refs


class TestFaults:
    def setup_method(self):
        faults.reset()

    def teardown_method(self):
        faults.reset()

    def test_drafter_crash_falls_back_never_garbage(self):
        """spec_drafter_crash@N poisons the drafter pool: its
        finite-logits guard trips, the pool rebuilds from params, the
        engine decodes non-spec that iteration — output stays
        bit-exact and the crash is counted."""
        cfg, params = _setup("control")
        prompts = _prompts(LENS, cfg.vocab_size)
        faults.arm("spec_drafter_crash@2")
        eng = ServingEngine(
            params, cfg, _spec_serving(spec_mode="model"),
            spec_drafter=(params, cfg),
        )
        outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        refs = _ref_greedy_all("control", "xla", "auto", LENS, 8)
        for o, r in zip(outs, refs):
            assert o.tokens == r
        st = eng.spec_stats()
        assert st["drafter_crashes"] == 1
        assert st["drafter"]["drafter_crashes_total"] == 1
        # the drafter recovered: proposals resumed after the rebuild
        assert st["proposed"] > 0

    def test_reject_storm_degrades_to_non_spec(self):
        """spec_reject_storm@A-B forces 0% acceptance through the
        window: one token per slot per step (the non-spec floor),
        outputs still bit-exact, proposals counted but none accepted."""
        cfg, params = _setup("control")
        prompts = _prompts(LENS, cfg.vocab_size)
        faults.arm("spec_reject_storm@0-1000")
        eng = ServingEngine(params, cfg, _spec_serving())
        outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        refs = _ref_greedy_all("control", "xla", "auto", LENS, 8)
        for o, r in zip(outs, refs):
            assert o.tokens == r
        st = eng.spec_stats()
        assert st["proposed"] > 0
        assert st["accepted"] == 0

    def test_storm_throughput_floor_is_one_token_per_step(self):
        cfg, params = _setup("control")
        faults.arm("spec_reject_storm@0-1000")
        eng = ServingEngine(params, cfg, _spec_serving(num_slots=1))
        eng.submit(_prompts((4,), cfg.vocab_size)[0], max_new_tokens=6,
                   temperature=0.0)
        it0 = eng.stats["iterations"]
        eng.run()
        # the first token rides the prefill chunk; the remaining 5 take
        # >= 5 decode iterations — nothing speculative survived the storm
        assert eng.stats["iterations"] - it0 >= 5


class TestObservability:
    def test_health_spec_snapshot_and_metrics(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _spec_serving())
        eng.generate(_prompts((5, 7), cfg.vocab_size),
                     max_new_tokens=8, temperature=0.0)
        st = eng.spec_stats()
        for key in ("mode", "verify", "draft_len", "proposed",
                    "accepted", "acceptance_rate", "drafter_crashes",
                    "drafter"):
            assert key in st
        body = eng.registry.render()
        for needle in (
            "serving_spec_proposed_tokens_total",
            "serving_spec_accepted_tokens_total",
            "serving_spec_acceptance_rate",
            "serving_spec_draft_len",
            'serving_spec_mode{mode="ngram"}',
        ):
            assert needle in body, needle

    def test_non_spec_engine_reports_none(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(num_slots=2))
        assert eng.spec_stats() is None

    def test_model_drafter_bytes_gauge(self):
        cfg, params = _setup("control")
        eng = ServingEngine(
            params, cfg, _spec_serving(spec_mode="model"),
            spec_drafter=(params, cfg),
        )
        body = eng.registry.render()
        assert "serving_spec_drafter_kv_bytes" in body
        assert eng._drafter.bytes_total() > 0


class TestModelDrafterState:
    def test_commit_rewinds_past_rejections(self):
        cfg, params = _setup("control")
        d = ModelDrafter(params, cfg, num_slots=1, rope_len=32)
        toks = _prompts((6,), cfg.vocab_size)[0] + [1]
        d.propose_all([DraftSlot(0, toks, 6, 3)])
        assert d._next[0] == 9  # fed positions 6..8
        d.commit(0, 7)  # only the first draft token accepted
        assert d._next[0] == 7
        d.release(0)
        assert d._next[0] == 0

    def test_poison_then_propose_rebuilds(self):
        cfg, params = _setup("control")
        d = ModelDrafter(params, cfg, num_slots=1, rope_len=32)
        toks = _prompts((6,), cfg.vocab_size)[0] + [1]
        d.poison()
        assert d.propose_all([DraftSlot(0, toks, 6, 3)]) == {}
        assert d.stats()["drafter_crashes_total"] == 1
        # rebuilt: the very next round proposes again
        out = d.propose_all([DraftSlot(0, toks, 6, 3)])
        assert len(out.get(0, [])) == 3

    def test_build_drafter_modes(self):
        cfg, params = _setup("control")
        assert build_drafter(ServingConfig(num_slots=2), cfg, 32) is None
        ng = build_drafter(
            ServingConfig(num_slots=2, spec_mode="ngram"), cfg, 32
        )
        assert isinstance(ng, NGramDrafter)
        with pytest.raises(ValueError, match="spec_drafter_ckpt"):
            build_drafter(
                ServingConfig(num_slots=2, spec_mode="model"), cfg, 32
            )


class TestGL301CoversSpecDrafters:
    """Mutation test for the drafters' lock discipline
    (serving/spec.py): both drafters are lock-owning classes shared
    between the engine thread and /health readers, so GL301 is the
    machine check that their cursor/suffix-map/counter writes stay
    under ``self._lock``. Planting exactly that bug — the commit-path
    cursor write hoisted OUT of the lock — in the real module source
    MUST fire; the unmutated module must stay clean."""

    SPEC = (
        REPO / "differential_transformer_replication_tpu" / "serving"
        / "spec.py"
    )
    ANCHOR = (
        "        with self._lock:\n"
        "            self._next[index] = min(self._next[index], new_pos)"
    )

    def _copy(self, tmp_path, src):
        # keep the serving/ path component: GL301 is a serving-dir rule
        path = tmp_path / "serving" / "spec.py"
        path.parent.mkdir(parents=True)
        path.write_text(src)
        return path

    def _lint(self, path, rules):
        sys.path.insert(0, str(REPO))
        from differential_transformer_replication_tpu.analysis.lint import (
            lint_paths,
        )

        return lint_paths([str(path)], rules=rules)

    def test_unmutated_spec_is_lock_clean(self, tmp_path):
        path = self._copy(tmp_path, self.SPEC.read_text())
        result = self._lint(path, ["GL301", "GL601", "GL602"])
        assert [f.rule for f in result.active] == []

    def test_planted_off_lock_cursor_write_fires(self, tmp_path):
        src = self.SPEC.read_text()
        assert self.ANCHOR in src, (
            "mutation anchor vanished — ModelDrafter.commit's lock "
            "block moved; update the anchor so this mutation test "
            "keeps guarding it"
        )
        mutated = src.replace(
            self.ANCHOR,
            "        self._crashes += 1  # planted: off-lock write\n"
            + self.ANCHOR,
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == ["GL301"]
        (finding,) = result.active
        assert "_crashes" in finding.message

    def test_planted_write_under_lock_stays_clean(self, tmp_path):
        src = self.SPEC.read_text()
        mutated = src.replace(
            self.ANCHOR,
            "        with self._lock:\n"
            "            self._crashes += 0  # inside the lock: fine\n"
            "            self._next[index] = min(self._next[index], "
            "new_pos)",
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == []


class TestTools:
    def test_spec_sweep_smoke(self):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "spec_sweep.py"),
             "--smoke"],
            capture_output=True, text=True, timeout=600,
            env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                 "HOME": "/tmp"},
            cwd=str(REPO),
        )
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln]
        assert len(lines) >= 3
        for ln in lines:
            assert ln["metric"] == "spec_sweep_case"
            if ln["spec_verify"] == "exact":
                assert ln["greedy_token_match_rate"] == 1.0
        assert any(ln["drafter"] == "self"
                   and ln["acceptance_rate"] == 1.0 for ln in lines)

    @pytest.mark.slow
    def test_serve_bench_spec_smoke(self):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "serve_bench.py"),
             "--smoke", "--spec", "ngram"],
            capture_output=True, text=True, timeout=600,
            env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                 "HOME": "/tmp"},
            cwd=str(REPO),
        )
        assert r.returncode == 0, r.stderr[-2000:]
        line = json.loads(r.stdout.strip().splitlines()[-1])
        assert line["metric"] == "serving_spec_output_tokens_per_sec"
        assert line["compiles_in_window"] == 0
        assert line["greedy_token_match_rate"] == 1.0
        assert line["spec_acceptance_rate"] > 0
        assert line["spec_tok_per_s"] > 0
        assert line["baseline_tok_per_s"] > 0


class TestPagedSpecInterplay:
    def test_paged_spec_releases_pages_and_caches_prefixes(self):
        """Spec on the paged pool: retirement still donates prompt
        pages to the radix cache, and a second request sharing the
        prefix both hits the cache AND speculates — all pages
        accounted."""
        cfg, params = _setup("control")
        serving = _spec_serving(kv_page_size=8, num_slots=2)
        eng = ServingEngine(params, cfg, serving)
        prompt = _prompts((12,), cfg.vocab_size)[0]
        eng.generate([prompt], max_new_tokens=6, temperature=0.0)
        st1 = eng.page_stats()
        assert st1["cached"] > 0  # prompt pages donated
        outs = eng.generate([prompt + [3]], max_new_tokens=6,
                            temperature=0.0)
        st2 = eng.page_stats()
        assert st2["hits_total"] >= 1
        assert outs[0].finish_reason == "length"
        # pool fully released after retirement
        assert st2["free"] + st2["cached"] == st2["total"]
