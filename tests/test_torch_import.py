"""Cross-implementation parity: the reference's OWN forward pass vs ours.

These tests import the reference's torch models from /root/reference
(read-only; imported for comparison, never copied), randomly initialize
them, map their state_dicts into this framework's params via
utils/torch_import.py, and assert the two implementations produce the
same logits and loss on the same tokens — the strongest form of the
replication claim, covering every quirk at once (lambda schedule, norm
axis, 0.2 scale, RoPE formulation, head merging, FFN wiring).

Skipped automatically when /root/reference or torch is unavailable.
"""

import os
import sys

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

REFERENCE = "/root/reference"
if not os.path.isdir(REFERENCE):  # pragma: no cover
    pytest.skip("reference repo not mounted", allow_module_level=True)
sys.path.insert(0, REFERENCE)

from differential_transformer_replication_tpu.models import model_forward  # noqa: E402
from differential_transformer_replication_tpu.utils.torch_import import (  # noqa: E402
    import_reference_state_dict,
    infer_model_config,
)

DIMS = dict(vocab_size=64, n_embd=32, n_head=2, n_layer=3, block_size=16, dropout=0.0)


def _reference_model(kind):
    torch.manual_seed(0)
    if kind == "control":
        from control import StandardTransformer

        return StandardTransformer(**DIMS)
    if kind == "diff":
        from diff_transformer import DiffTransformer

        return DiffTransformer(**DIMS)
    from Ndiff_transformer import AlternatingDiffTransformer

    return AlternatingDiffTransformer(**DIMS, n_terms=3)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_logits_and_loss_match_reference(kind):
    ref = _reference_model(kind).eval()
    sd = ref.state_dict()

    cfg = infer_model_config(sd)
    assert cfg.model == kind
    assert (cfg.vocab_size, cfg.n_embd, cfg.n_layer, cfg.block_size) == (
        64, 32, 3, 16,
    )
    params, _ = import_reference_state_dict(sd, cfg)
    cfg = cfg.replace(compute_dtype="float32")

    rng = np.random.default_rng(7)
    x = rng.integers(0, 64, (2, 16))
    y = rng.integers(0, 64, (2, 16))

    with torch.no_grad():
        ref_logits, ref_loss = ref(
            torch.from_numpy(x).long(), torch.from_numpy(y).long()
        )

    logits, loss = model_forward(
        params, jax.numpy.asarray(x), cfg, targets=jax.numpy.asarray(y)
    )

    np.testing.assert_allclose(
        np.asarray(logits),
        ref_logits.detach().numpy().reshape(np.asarray(logits).shape),
        atol=2e-5,
    )
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_parity_with_nonzero_lambdas():
    """Zero-init lambdas make the dynamic schedule the whole story; push
    them off zero so the learned exp(lq*lk) terms are exercised too."""
    ref = _reference_model("diff").eval()
    with torch.no_grad():
        for blk in ref.blocks:
            for head in blk.diff_attn.heads:
                head.lambda_q1.uniform_(-0.5, 0.5)
                head.lambda_k1.uniform_(-0.5, 0.5)
                head.lambda_q2.uniform_(-0.5, 0.5)
                head.lambda_k2.uniform_(-0.5, 0.5)
    params, cfg = import_reference_state_dict(ref.state_dict())
    cfg = cfg.replace(compute_dtype="float32")
    x = np.random.default_rng(3).integers(0, 64, (2, 16))
    with torch.no_grad():
        ref_logits, _ = ref(torch.from_numpy(x).long())
    logits, _ = model_forward(params, jax.numpy.asarray(x), cfg)
    got = np.asarray(logits)
    np.testing.assert_allclose(
        got, ref_logits.detach().numpy().reshape(got.shape), atol=2e-5
    )


def test_load_best_model_blob(tmp_path):
    """The reference's best_model.pt structure (train.py:309-316) loads
    through load_reference_checkpoint."""
    from differential_transformer_replication_tpu.utils.torch_import import (
        load_reference_checkpoint,
    )

    ref = _reference_model("control").eval()
    path = str(tmp_path / "best_model.pt")
    torch.save({"model_state_dict": ref.state_dict(), "iter_num": 5}, path)
    params, cfg = load_reference_checkpoint(path)
    assert cfg.model == "control"
    x = np.random.default_rng(5).integers(0, 64, (1, 16))
    with torch.no_grad():
        ref_logits, _ = ref(torch.from_numpy(x).long())
    logits, _ = model_forward(
        params, jax.numpy.asarray(x), cfg.replace(compute_dtype="float32")
    )
    got = np.asarray(logits)
    np.testing.assert_allclose(
        got, ref_logits.detach().numpy().reshape(got.shape), atol=2e-5
    )
