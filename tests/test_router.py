"""Multi-replica serving router (ISSUE 6): health-aware load
balancing, failover, hedging, affinity, rolling restarts.

The load-bearing contracts:

- the picker only ever chooses UP replicas, prefers less-loaded ones
  (power-of-two-choices over queue/slot/KV scores + live in-flight),
  and honors ``session_id`` affinity with re-pinning when the pinned
  replica dies;
- ejection takes ``eject_after`` consecutive transport failures,
  re-admission takes ``readmit_after`` consecutive good probes (a
  flapping replica cannot oscillate into rotation), and a DRAINING
  replica leaves rotation connection-free without ever being ejected;
- retriable replica replies (503 queue_full / shutting_down /
  engine_crash, unreachable transport) fail over to a DIFFERENT
  replica under the request's deadline budget; non-recoverable codes
  (504 deadline, timeout, engine_failed) pass through once, untouched;
- Retry-After values the router honors or propagates are capped;
- the retry client budgets total elapsed time against ``deadline_s``
  (satellite: serving/retry.py);
- every server error reply carries a machine-readable ``code``
  (satellite: serving/server.py), because ALL of the above keys off it.

Quick tier: pure state-machine/picker tests plus canned-HTTP-replica
tests (no jax, no engine). Slow tier: the rolling-restart chaos test
over two real replica subprocesses via tools/fleet.py.
"""

import importlib.util
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from differential_transformer_replication_tpu.config import (
    RouterConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.serving.retry import (
    http_post_json_with_retries,
)
from differential_transformer_replication_tpu.serving.router import (
    DRAINING,
    EJECTED,
    NOT_READY,
    UP,
    Replica,
    Router,
    parse_replica_scores,
    serve_router,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _load_fleet():
    spec = importlib.util.spec_from_file_location(
        "fleet", os.path.join(TOOLS, "fleet.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_backoff_s", 0.05)
    kw.setdefault("probe_backoff_max_s", 0.4)
    kw.setdefault("retry_base_s", 0.001)
    kw.setdefault("retry_cap_s", 0.01)
    # unit tests want deterministic immediate shedding; the chaos test
    # opts back into the wait that bridges rolling-restart windows
    kw.setdefault("wait_for_replica_s", 0.0)
    return RouterConfig(**kw)


def _router(n=2, cfg=None, start=False, **kw):
    r = Router(
        [f"http://127.0.0.1:{19000 + i}" for i in range(n)],
        cfg or _cfg(), rng=random.Random(0), **kw,
    )
    if start:
        r.start()
    return r


def _mark_up(*replicas, now=0.0):
    for r in replicas:
        r.note_probe_success(True, "healthy", {}, now=now)


# -- fault-spec parsing -------------------------------------------------


class TestRouterFaultSpec:
    def test_point_kinds_parse_and_one_shot(self):
        faults.arm("router_probe_fail,router_pick_raise@2")
        assert faults.armed()
        with pytest.raises(faults.FaultInjected, match="router_probe_fail"):
            faults.check("router_probe_fail")
        faults.check("router_probe_fail")  # one-shot: disarmed
        faults.check("router_pick_raise")  # 1st call: armed for 2nd
        with pytest.raises(faults.FaultInjected, match="router_pick_raise"):
            faults.check("router_pick_raise")

    def test_replica_hang_uses_router_env(self, monkeypatch):
        monkeypatch.setenv(faults.ROUTER_HANG_ENV_VAR, "0.12")
        monkeypatch.setenv(faults.CKPT_HANG_ENV_VAR, "9.0")  # must NOT apply
        faults.arm("router_replica_hang")
        t0 = time.perf_counter()
        faults.stall("router_replica_hang")
        dt = time.perf_counter() - t0
        assert 0.1 <= dt < 1.0
        t0 = time.perf_counter()
        faults.stall("router_replica_hang")  # disarmed
        assert time.perf_counter() - t0 < 0.05


# -- retry client deadline budget (satellite) ---------------------------


class _Canned(BaseHTTPRequestHandler):
    """One-endpoint server replying from the class-level script."""

    script = []  # list of (status, body_dict, headers_dict)
    hits = None

    def do_POST(self):
        i = min(len(self.script) - 1, self.hits["n"])
        self.hits["n"] += 1
        status, body, headers = self.script[i]
        # bytes bodies ship verbatim (truncated/garbage-reply tests)
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


def _canned_server(script):
    hits = {"n": 0}
    handler = type("H", (_Canned,), {"script": script, "hits": hits})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}", hits


class TestRetryDeadlineBudget:
    def test_deadline_stops_retry_after_sequence(self):
        """A Retry-After longer than the remaining deadline must not be
        slept through — the server would answer 504 anyway."""
        httpd, url, hits = _canned_server([
            (503, {"code": "queue_full"}, {"Retry-After": "10"}),
        ])
        try:
            clock = {"t": 0.0}
            sleeps = []

            def fake_sleep(s):
                sleeps.append(s)
                clock["t"] += s

            status, body, retries = http_post_json_with_retries(
                url, {}, max_retries=5, sleep=fake_sleep,
                deadline_s=1.0, clock=lambda: clock["t"],
                retry_after_cap=30.0,
            )
            # elapsed(0) + honored Retry-After(10) >= deadline(1): the
            # typed 503 surfaces immediately, zero sleeps burned
            assert status == 503 and body["code"] == "queue_full"
            assert retries == 0 and sleeps == [] and hits["n"] == 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_retry_after_capped(self):
        """An absurd Retry-After is capped, not honored verbatim."""
        httpd, url, hits = _canned_server([
            (503, {"code": "queue_full"}, {"Retry-After": "500"}),
            (200, {"ok": True}, {}),
        ])
        try:
            sleeps = []
            status, body, retries = http_post_json_with_retries(
                url, {}, max_retries=3, base=0.001, cap=0.002,
                sleep=sleeps.append, retry_after_cap=0.05,
                rng=random.Random(0),
            )
            assert status == 200 and retries == 1
            assert len(sleeps) == 1 and sleeps[0] <= 0.06
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_no_deadline_keeps_retrying_as_before(self):
        httpd, url, hits = _canned_server([
            (503, {"code": "queue_full"}, {"Retry-After": "0.01"}),
            (503, {"code": "queue_full"}, {"Retry-After": "0.01"}),
            (200, {"ok": True}, {}),
        ])
        try:
            status, body, retries = http_post_json_with_retries(
                url, {}, max_retries=5, base=0.001, cap=0.002,
                sleep=lambda s: None, rng=random.Random(0),
            )
            assert status == 200 and retries == 2 and hits["n"] == 3
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_garbage_200_body_is_retried_like_transport_death(self):
        """A 200 whose body is truncated/garbled (server killed
        mid-response) retries instead of raising out of the client
        and killing the caller's worker thread."""
        httpd, url, hits = _canned_server([
            (200, b'{"tokens": [1,', {}),  # truncated JSON
            (200, {"ok": True}, {}),
        ])
        try:
            status, body, retries = http_post_json_with_retries(
                url, {}, max_retries=3, base=0.001, cap=0.002,
                sleep=lambda s: None, rng=random.Random(0),
            )
            assert status == 200 and body == {"ok": True}
            assert retries == 1 and hits["n"] == 2
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_transport_error_respects_deadline(self):
        clock = {"t": 0.0}

        def fake_sleep(s):
            clock["t"] += s

        with pytest.raises(OSError) as ei:
            # nothing listens on this port: every attempt is a
            # transport error; the deadline cuts the retry budget short
            http_post_json_with_retries(
                "http://127.0.0.1:9", {}, timeout=0.2, max_retries=50,
                base=0.5, cap=0.5, sleep=fake_sleep,
                deadline_s=1.0, clock=lambda: clock["t"],
                rng=random.Random(0),
            )
        assert getattr(ei.value, "retry_attempts", None) is not None
        assert ei.value.retry_attempts < 50


# -- metrics parsing ----------------------------------------------------


def test_parse_replica_scores_picks_gauges_and_skips_noise():
    text = "\n".join([
        "# HELP serving_queue_depth Requests waiting for a slot.",
        "# TYPE serving_queue_depth gauge",
        "serving_queue_depth 3",
        "serving_slot_occupancy 2",
        "serving_slots 8",
        "serving_kv_utilization 0.25",
        'serving_requests_finished_total{reason="length"} 17',
        "garbage line with too many parts",
        "serving_queue_wait_seconds_sum 1.5",
    ])
    assert parse_replica_scores(text) == {
        "queue_depth": 3.0, "slot_occupancy": 2.0,
        "slots": 8.0, "kv_utilization": 0.25,
    }


# -- replica health state machine ---------------------------------------


class TestReplicaStateMachine:
    def test_ejection_after_consecutive_failures_and_backoff_growth(self):
        cfg = _cfg(eject_after=3)
        r = Replica("http://x:1", cfg)
        assert r.state == "unknown" and not r.eligible()
        _mark_up(r)
        assert r.eligible()
        assert r.note_failure(now=1.0) is False
        assert r.note_failure(now=2.0) is False
        assert r.eligible()  # below the ejection threshold: still UP
        assert r.note_failure(now=3.0) is True  # newly ejected
        assert r.state == EJECTED and not r.eligible()
        assert r.note_failure(now=4.0) is False  # already ejected
        # probe backoff doubled each failure, capped
        assert r.probe_backoff == pytest.approx(
            min(cfg.probe_backoff_s * 2 ** 4, cfg.probe_backoff_max_s)
        )

    def test_slow_readmission_needs_consecutive_good_probes(self):
        cfg = _cfg(eject_after=1, readmit_after=2)
        r = Replica("http://x:1", cfg)
        _mark_up(r)
        assert r.note_failure(now=1.0) is True
        assert r.state == EJECTED
        r.note_probe_success(True, "healthy", {}, now=2.0)
        assert r.state == EJECTED  # one good probe is not enough
        r.note_failure(now=3.0)  # flap: streak resets
        r.note_probe_success(True, "healthy", {}, now=4.0)
        assert r.state == EJECTED
        r.note_probe_success(True, "healthy", {}, now=5.0)
        assert r.state == UP and r.eligible()  # re-admitted

    def test_ejected_stays_ejected_through_not_ready_probes(self):
        """A relaunched-but-booting replica answering 'restarting'
        must not launder an EJECTED replica into NOT_READY, which a
        single good probe would flip straight to UP — slow
        re-admission applies from ejection until readmit_after
        consecutive READY probes, whatever happened in between."""
        cfg = _cfg(eject_after=1, readmit_after=2)
        r = Replica("http://x:1", cfg)
        _mark_up(r)
        assert r.note_failure(now=1.0) is True
        assert r.state == EJECTED
        r.note_probe_success(False, "restarting", {}, now=2.0)
        assert r.state == EJECTED  # reachable-not-ready != recovered
        r.note_probe_success(True, "healthy", {}, now=3.0)
        assert r.state == EJECTED  # still one short of readmit_after
        r.note_probe_success(True, "healthy", {}, now=4.0)
        assert r.state == UP

    def test_draining_removes_without_ejecting(self):
        r = Replica("http://x:1", _cfg())
        _mark_up(r)
        r.note_probe_success(False, "draining", {}, now=1.0)
        assert r.state == DRAINING and not r.eligible()
        assert r.ejections == 0 and r.consec_fail == 0
        r.note_probe_success(False, "restarting", {}, now=2.0)
        assert r.state == NOT_READY
        r.note_probe_success(True, "healthy", {}, now=3.0)
        assert r.state == UP  # back instantly: it was never ejected

    def test_scores_ride_probes_into_the_score(self):
        cfg = _cfg(queue_weight=1.0, slot_weight=1.0, kv_weight=0.5)
        r = Replica("http://x:1", cfg)
        r.note_probe_success(True, "healthy", {
            "queue_depth": 4.0, "slot_occupancy": 2.0,
            "slots": 8.0, "kv_utilization": 0.5,
        }, now=1.0)
        assert r.score() == pytest.approx(4 / 8 + 2 / 8 + 0.5 * 0.5)
        with r.lock:
            r.inflight = 8
        assert r.score() == pytest.approx(4 / 8 + 2 / 8 + 0.25 + 1.0)


# -- picker -------------------------------------------------------------


class TestPicker:
    def test_only_up_replicas_are_picked(self):
        router = _router(3)
        a, b, c = router.replicas
        _mark_up(a)
        b.note_probe_success(False, "draining", {}, now=0.0)
        c.note_failure(now=0.0)
        c.note_failure(now=0.0)
        c.note_failure(now=0.0)
        assert c.state == EJECTED
        for _ in range(20):
            assert router.pick() is a

    def test_p2c_prefers_lower_score(self):
        router = _router(2)
        a, b = router.replicas
        a.note_probe_success(True, "healthy",
                             {"queue_depth": 10.0, "slots": 1.0}, now=0.0)
        b.note_probe_success(True, "healthy",
                             {"queue_depth": 0.0, "slots": 1.0}, now=0.0)
        picks = [router.pick() for _ in range(50)]
        # with exactly 2 eligible, p2c compares them every time: the
        # loaded replica must never win
        assert all(p is b for p in picks)

    def test_exclude_forces_failover_target(self):
        router = _router(2)
        a, b = router.replicas
        _mark_up(a, b)
        assert router.pick(exclude=(a.url,)) is b
        assert router.pick(exclude=(a.url, b.url)) is None

    def test_no_eligible_returns_none(self):
        router = _router(2)
        assert router.pick() is None  # never probed: unknown

    def test_affinity_sticks_and_fails_over_with_repin(self):
        router = _router(2)
        a, b = router.replicas
        _mark_up(a, b)
        first = router.pick(session_id="s1")
        for _ in range(10):
            assert router.pick(session_id="s1") is first
        # the pinned replica dies: the session re-pins elsewhere
        other = b if first is a else a
        for _ in range(3):
            first.note_failure(now=1.0)
        assert first.state == EJECTED
        moved = router.pick(session_id="s1")
        assert moved is other
        assert router._affinity["s1"] is other  # re-pinned, not orphaned
        counter = router._move_counter
        assert counter.value >= 1
        # and sticks to the new home afterwards
        assert router.pick(session_id="s1") is other

    def test_pick_latency_is_observed(self):
        router = _router(2)
        _mark_up(*router.replicas)
        router.pick()
        snap = router._pick_hist.snapshot()
        assert snap["count"] >= 1


# -- failover & taxonomy (handle_generate over canned replicas) ---------


def _two_replica_router(script_a, script_b, cfg=None, **kw):
    """Router over two canned HTTP replicas; probes disabled (tests
    mark replicas UP by hand so state is deterministic)."""
    ha, url_a, hits_a = _canned_server(script_a)
    hb, url_b, hits_b = _canned_server(script_b)
    router = Router([url_a, url_b], cfg or _cfg(), rng=random.Random(0),
                    **kw)
    _mark_up(*router.replicas)
    cleanup = lambda: [  # noqa: E731
        (h.shutdown(), h.server_close()) for h in (ha, hb)
    ]
    return router, (hits_a, hits_b), cleanup


_OK_BODY = {"request_id": 1, "prompt_ids": [1], "tokens": [2, 3],
            "finish_reason": "length", "ttft_ms": 1.0,
            "trace_id": "ab" * 16}


class TestFailover:
    def test_retriable_503_fails_over_to_other_replica(self):
        router, (ha, hb), cleanup = _two_replica_router(
            [(503, {"code": "queue_full"}, {"Retry-After": "0.01"})],
            [(200, dict(_OK_BODY), {})],
        )
        try:
            # force the first pick onto the 503 replica
            router._affinity["s"] = router.replicas[0]
            status, body, headers = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200
            assert body["replica"] == router.replicas[1].name
            assert body["attempts"] == 2
            assert body["hedged"] is False
            assert router._retry_counter.value == 1
        finally:
            cleanup()

    def test_transient_failover_does_not_repin_healthy_session(self):
        """One queue_full blip on the pinned replica serves THIS
        request elsewhere but keeps the pin — the next request goes
        back home (prefix-cache locality survives backpressure)."""
        router, (ha, hb), cleanup = _two_replica_router(
            [(503, {"code": "queue_full"}, {"Retry-After": "0.01"}),
             (200, dict(_OK_BODY, tokens=[7]), {})],
            [(200, dict(_OK_BODY), {})],
        )
        try:
            a, b = router.replicas
            router._affinity["s"] = a
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200 and body["replica"] == b.name
            assert router._affinity["s"] is a  # pin survived the blip
            assert router._move_counter.value == 0
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200 and body["replica"] == a.name  # home
        finally:
            cleanup()

    def test_non_retriable_codes_pass_through_once(self):
        for code, status in (("engine_failed", 503), ("timeout", 503),
                             ("deadline", 504)):
            router, (ha, hb), cleanup = _two_replica_router(
                [(status, {"code": code}, {})],
                [(200, dict(_OK_BODY), {})],
            )
            try:
                router._affinity["s"] = router.replicas[0]
                got_status, body, headers = router.handle_generate(
                    {"prompt_ids": [1], "session_id": "s"}
                )
                assert got_status == status and body["code"] == code
                assert body["replica"] == router.replicas[0].name
                assert hb["n"] == 0  # never touched the healthy one
            finally:
                cleanup()

    def test_exhausted_failover_returns_last_503_with_capped_retry_after(self):
        cfg = _cfg(max_attempts=2, retry_after_cap_s=2.0)
        router, (ha, hb), cleanup = _two_replica_router(
            [(503, {"code": "queue_full"}, {"Retry-After": "60"})],
            [(503, {"code": "shutting_down"}, {"Retry-After": "60"})],
            cfg=cfg, sleep=lambda s: None,
        )
        try:
            status, body, headers = router.handle_generate(
                {"prompt_ids": [1]}
            )
            assert status == 503
            assert body["code"] in ("queue_full", "shutting_down")
            # propagated Retry-After is capped, not the replica's 60s
            assert float(headers["Retry-After"]) <= 2.0
        finally:
            cleanup()

    def test_sheds_with_retry_after_when_nothing_eligible(self):
        router = _router(2, cfg=_cfg(shed_retry_after_s=3.0))
        status, body, headers = router.handle_generate(
            {"prompt_ids": [1]}
        )
        assert status == 503 and body["code"] == "no_replica"
        assert headers["Retry-After"] == "3"
        assert router._shed_counter.value == 1

    def test_total_fleet_ejection_sheds_typed_then_full_recovery_serves(
        self
    ):
        """The worst fleet state: EVERY replica ejected simultaneously
        (a shared dependency died — same rack, same backend). Requests
        must shed as a typed 503 ``no_replica`` with a Retry-After the
        client can obey — never hang, never 500 — and once the whole
        fleet passes its slow re-admission, the SAME router serves
        again with the shed counter frozen."""
        cfg = _cfg(eject_after=1, readmit_after=2, shed_retry_after_s=2.0)
        router, (ha, hb), cleanup = _two_replica_router(
            [(200, dict(_OK_BODY), {})],
            [(200, dict(_OK_BODY), {})],
            cfg=cfg,
        )
        try:
            a, b = router.replicas
            # the shared dependency dies: both replicas strike out at
            # once and the fleet is empty
            a.note_failure(now=1.0)
            b.note_failure(now=1.0)
            assert a.state == EJECTED and b.state == EJECTED
            assert router.eligible_count() == 0
            for _ in range(3):
                status, body, headers = router.handle_generate(
                    {"prompt_ids": [1]}
                )
                assert status == 503
                assert body["code"] == "no_replica"
                assert headers["Retry-After"] == "2"
            assert router._shed_counter.value == 3
            # recovery: one good probe is NOT enough (slow re-admission
            # holds fleet-wide, not just per replica)...
            a.note_probe_success(True, "healthy", {}, now=2.0)
            b.note_probe_success(True, "healthy", {}, now=2.0)
            assert router.eligible_count() == 0
            status, body, _ = router.handle_generate({"prompt_ids": [1]})
            assert status == 503 and body["code"] == "no_replica"
            # ...the second consecutive good probe re-admits the fleet
            a.note_probe_success(True, "healthy", {}, now=3.0)
            b.note_probe_success(True, "healthy", {}, now=3.0)
            assert router.eligible_count() == 2
            status, body, _ = router.handle_generate({"prompt_ids": [1]})
            assert status == 200
            assert body["replica"] in (a.name, b.name)
            assert router._shed_counter.value == 4  # frozen post-recovery
        finally:
            cleanup()

    def test_unreachable_replica_fails_over_and_counts_strike(self):
        # replica 0 is a dead port; replica 1 answers
        hb, url_b, hits_b = _canned_server([(200, dict(_OK_BODY), {})])
        router = Router(["http://127.0.0.1:9", url_b], _cfg(),
                        rng=random.Random(0))
        _mark_up(*router.replicas)
        try:
            router._affinity["s"] = router.replicas[0]
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200
            assert body["replica"] == router.replicas[1].name
            assert router.replicas[0].consec_fail == 1
        finally:
            hb.shutdown()
            hb.server_close()

    def test_router_deadline_timeout_is_504_without_replica_strike(self):
        """A forward timeout CAUSED by the request's own deadline
        budget maps to a non-retriable 504 `deadline` and must not
        strike (let alone eject) the replica — it was healthy, just
        slower than the caller's patience."""

        class _Slow(BaseHTTPRequestHandler):
            def do_POST(self):
                time.sleep(1.0)
                body = json.dumps(_OK_BODY).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Slow)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        router = Router([url], _cfg(), rng=random.Random(0))
        _mark_up(*router.replicas)
        try:
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "deadline_s": 0.2}
            )
            assert status == 504 and body["code"] == "deadline"
            assert router.replicas[0].consec_fail == 0  # no strike
            assert router.replicas[0].state == UP
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_affinity_map_is_lru_capped(self):
        router = _router(2, cfg=_cfg(affinity_max_sessions=3))
        _mark_up(*router.replicas)
        for i in range(5):
            assert router.pick(session_id=f"s{i}") is not None
        assert len(router._affinity) == 3
        assert "s0" not in router._affinity  # oldest evicted
        assert "s4" in router._affinity
        # touching a surviving session refreshes it
        router.pick(session_id="s2")
        router.pick(session_id="s5")
        assert "s2" in router._affinity and "s3" not in router._affinity

    def test_deadline_budget_bounds_failover(self):
        """With an expired budget the router reports the last typed
        failure instead of burning more attempts."""
        cfg = _cfg(max_attempts=3, retry_base_s=5.0, retry_cap_s=5.0,
                   retry_after_cap_s=5.0)
        router, (ha, hb), cleanup = _two_replica_router(
            [(503, {"code": "queue_full"}, {"Retry-After": "5"})],
            [(503, {"code": "queue_full"}, {"Retry-After": "5"})],
            cfg=cfg,
        )
        try:
            t0 = time.monotonic()
            status, body, headers = router.handle_generate(
                {"prompt_ids": [1], "deadline_s": 0.2}
            )
            # the backoff (>=5s floor) would blow the 0.2s budget: the
            # 503 surfaces without sleeping through it
            assert status == 503 and body["code"] == "queue_full"
            assert time.monotonic() - t0 < 2.0
            assert ha["n"] + hb["n"] == 1
        finally:
            cleanup()


# -- hedging ------------------------------------------------------------


class TestHedging:
    def test_hung_replica_hedges_to_other_and_wins(self, monkeypatch):
        monkeypatch.setenv(faults.ROUTER_HANG_ENV_VAR, "0.6")
        cfg = _cfg(hedge_factor=1.0, hedge_min_s=0.05)
        router, (ha, hb), cleanup = _two_replica_router(
            [(200, dict(_OK_BODY, tokens=[9]), {})],
            [(200, dict(_OK_BODY), {})],
            cfg=cfg,
        )
        try:
            router._affinity["s"] = router.replicas[0]
            faults.arm("router_replica_hang@1")  # 1st forward stalls
            t0 = time.monotonic()
            status, body, _ = router.handle_generate(
                {"prompt_ids": [1], "session_id": "s"}
            )
            assert status == 200
            # the hedge (replica 1) answered while the primary hung
            assert body["hedged"] is True
            assert body["replica"] == router.replicas[1].name
            assert time.monotonic() - t0 < 0.55
            assert router._hedge_counter.value == 1
            assert router._hedge_win_counter.value == 1
        finally:
            cleanup()

    def test_hedging_off_by_default(self):
        router, (ha, hb), cleanup = _two_replica_router(
            [(200, dict(_OK_BODY), {})],
            [(200, dict(_OK_BODY), {})],
        )
        try:
            status, body, _ = router.handle_generate({"prompt_ids": [1]})
            assert status == 200 and body["hedged"] is False
            assert router._hedge_counter.value == 0
        finally:
            cleanup()


# -- router HTTP surface ------------------------------------------------


class TestRouterHTTP:
    def _serve(self, router):
        httpd = serve_router(router, port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"

    def test_generate_health_ready_metrics_roundtrip(self):
        router, (ha, hb), cleanup = _two_replica_router(
            [(200, dict(_OK_BODY), {})],
            [(200, dict(_OK_BODY), {})],
        )
        httpd, url = self._serve(router)
        try:
            status, body, retries = http_post_json_with_retries(
                url + "/generate", {"prompt_ids": [1],
                                    "max_new_tokens": 2},
            )
            assert status == 200 and body["tokens"] == [2, 3]
            assert body["replica"] in (
                router.replicas[0].name, router.replicas[1].name
            )
            with urllib.request.urlopen(url + "/health", timeout=30) as r:
                health = json.load(r)
            assert health["ok"] is True and health["eligible"] == 2
            assert {x["state"] for x in health["replicas"]} == {UP}
            with urllib.request.urlopen(url + "/ready", timeout=30) as r:
                assert json.load(r)["ready"] is True
            with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
                text = r.read().decode()
            assert "router_requests_total" in text
            assert "router_replicas_eligible 2" in text
        finally:
            httpd.shutdown()
            httpd.server_close()
            cleanup()

    def test_ready_503_when_fleet_empty_and_bad_json_is_400(self):
        router = _router(2)  # nothing probed: zero eligible
        httpd, url = self._serve(router)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/ready", timeout=30)
            assert ei.value.code == 503
            assert "Retry-After" in ei.value.headers
            req = urllib.request.Request(
                url + "/generate", data=b"{not json",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 400
            assert json.loads(ei.value.read())["code"] == "bad_request"
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_pick_raise_fault_is_typed_500_and_router_survives(self):
        router, (ha, hb), cleanup = _two_replica_router(
            [(200, dict(_OK_BODY), {})],
            [(200, dict(_OK_BODY), {})],
        )
        httpd, url = self._serve(router)
        try:
            faults.arm("router_pick_raise")
            status, body, _ = http_post_json_with_retries(
                url + "/generate", {"prompt_ids": [1]}, max_retries=0,
            )
            assert status == 500 and body["code"] == "internal"
            # the fault was one-shot; the router keeps serving
            status, body, _ = http_post_json_with_retries(
                url + "/generate", {"prompt_ids": [1]},
            )
            assert status == 200
        finally:
            httpd.shutdown()
            httpd.server_close()
            cleanup()


# -- server error-code satellite ----------------------------------------


class TestServerErrorCodes:
    def _fake_client(self, exc):
        """The minimal surface serving/server.py's handler touches."""

        class _Engine:
            serving = ServingConfig(num_slots=1)

        class _Runner:
            engine = _Engine()
            restarts = 0
            last_step_s = None

            def status(self):
                return "healthy"

            def accepting(self):
                return True

        class _Client:
            runner = _Runner()
            registry = None
            stats = {}

            def status(self):
                return "healthy"

            def generate(self, *a, **kw):
                raise exc

        return _Client()

    def _post(self, url):
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({"prompt_ids": [1],
                             "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    @pytest.mark.parametrize("exc", [
        RuntimeError("runner closed"),
        AttributeError("engine lost an attribute mid-flight"),
        KeyError("missing"),
        OSError("device backend vanished"),
    ])
    def test_unexpected_exceptions_reply_500_with_code(self, exc):
        """Regression (satellite): EVERY error reply carries the
        machine-readable ``code`` the router keys retriability off —
        including 500s from exception types the handler never
        anticipated (previously only RuntimeError was typed; anything
        else fell through to http.server's HTML 500)."""
        from differential_transformer_replication_tpu.serving.server import (
            serve,
        )

        httpd = serve(self._fake_client(exc), port=0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            status, body = self._post(url)
            assert status == 500
            assert body["code"] == "internal"
            assert body["error"]  # human text still present
        finally:
            httpd.shutdown()
            httpd.server_close()


# -- serve_bench per-replica breakdown (satellite) ----------------------


def test_serve_bench_target_mode_reports_per_replica_breakdown(capsys):
    """--target mode needs no jax and no local engine: two canned
    replicas, round-robin, per-replica req/s in the JSON line."""
    ha, url_a, hits_a = _canned_server([(200, dict(_OK_BODY), {})])
    hb, url_b, hits_b = _canned_server([(200, dict(_OK_BODY), {})])
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(TOOLS, "serve_bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    argv = sys.argv
    sys.argv = ["serve_bench.py", "--target", url_a, "--target", url_b,
                "--requests", "8", "--clients", "2", "--min-prompt", "2",
                "--max-prompt", "4", "--new-tokens", "2",
                "--prefill-chunk", "4", "--vocab-size", "97"]
    try:
        bench.main()
    finally:
        sys.argv = argv
        for h in (ha, hb):
            h.shutdown()
            h.server_close()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["http"] is True and line["failed"] == 0
    assert line["n_requests"] == 8
    assert set(line["per_replica"]) == {url_a + "/generate",
                                        url_b + "/generate"}
    for entry in line["per_replica"].values():
        assert entry["ok"] == 4  # strict round-robin over 2 targets
        assert entry["req_per_s"] > 0
        assert {"ok", "errors", "retries", "hedges",
                "req_per_s"} <= set(entry)
    assert "hedges" in line and "no_replica" in line["errors"]
    # p99 exemplars (satellite): slowest requests keyed by the
    # replies' trace_id so a regression is stitch-lookupable
    assert line["slow_exemplars"]
    assert all(e["trace_id"] == "ab" * 16
               for e in line["slow_exemplars"])


# -- probing over live HTTP (ejection + re-admission end to end) --------


class _ReadyHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps({"ready": True, "status": "healthy"}).encode()
        self.send_response(200 if self.path == "/ready" else 404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_probe_fault_point_counts_a_strike_against_healthy_replica():
    """router_probe_fail makes probe failures deterministic: the armed
    probe counts a transport strike even though the replica is fine."""
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _ReadyHandler)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    router = Router([url], _cfg(eject_after=1), rng=random.Random(0))
    replica = router.replicas[0]
    try:
        router.probe(replica)
        assert replica.state == UP
        faults.arm("router_probe_fail")
        router.probe(replica)
        assert replica.state == EJECTED  # eject_after=1: one strike
        assert router._eject_counter.labels(
            replica=replica.name
        ).value == 1
        router.probe(replica)  # fault was one-shot: probes work again
        assert replica.consec_ok == 1
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_slow_probe_does_not_stall_fleet_health_detection():
    """A blackholed replica blocking its probe timeout must not slow
    the probe cadence for the rest of the fleet (probes run
    concurrently, one in flight per replica)."""
    probe_times = []
    times_lock = threading.Lock()

    class _SlowReady(BaseHTTPRequestHandler):
        def do_GET(self):
            time.sleep(0.8)  # blackholed-ish: accepts, answers late
            body = json.dumps({"ready": True, "status": "healthy"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    class _FastReady(_ReadyHandler):
        def do_GET(self):
            if self.path == "/ready":
                with times_lock:
                    probe_times.append(time.monotonic())
            _ReadyHandler.do_GET(self)

    slow = ThreadingHTTPServer(("127.0.0.1", 0), _SlowReady)
    fast = ThreadingHTTPServer(("127.0.0.1", 0), _FastReady)
    for h in (slow, fast):
        threading.Thread(target=h.serve_forever, daemon=True).start()
    cfg = _cfg(probe_interval_s=0.05, probe_timeout_s=2.0)
    router = Router(
        [f"http://127.0.0.1:{slow.server_address[1]}",
         f"http://127.0.0.1:{fast.server_address[1]}"],
        cfg, rng=random.Random(0),
    )
    try:
        router.start()
        time.sleep(1.0)
        with times_lock:
            n = len(probe_times)
        # sequential probing behind the 0.8s-slow replica would manage
        # ~1-2 fast-replica probes in this window; concurrent probing
        # sustains the 0.05s cadence
        assert n >= 5, f"fast replica only probed {n} times"
        assert router.replicas[1].state == UP
    finally:
        router.close()
        slow.shutdown()
        slow.server_close()
        fast.shutdown()
        fast.server_close()


def test_probe_loop_ejects_dead_replica_and_readmits_on_recovery():
    """End-to-end prober: a replica whose process dies gets ejected
    after consecutive failed probes, and the SAME replica is slowly
    re-admitted once it listens again (same port — a restart)."""
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _ReadyHandler)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    cfg = _cfg(probe_interval_s=0.03, probe_backoff_s=0.03,
               probe_backoff_max_s=0.1, eject_after=2, readmit_after=2)
    router = Router([url], cfg, rng=random.Random(0))
    revived = None
    try:
        router.start()
        replica = router.replicas[0]
        deadline = time.time() + 5
        while replica.state != UP and time.time() < deadline:
            time.sleep(0.01)
        assert replica.state == UP
        # the "process" dies: probes hit a closed port -> ejection
        httpd.shutdown()
        httpd.server_close()
        deadline = time.time() + 10
        while replica.state != EJECTED and time.time() < deadline:
            time.sleep(0.01)
        assert replica.state == EJECTED
        assert router.eligible_count() == 0
        # restart on the same port -> slow re-admission back to UP
        revived = ThreadingHTTPServer(("127.0.0.1", port), _ReadyHandler)
        threading.Thread(target=revived.serve_forever,
                         daemon=True).start()
        deadline = time.time() + 10
        while replica.state != UP and time.time() < deadline:
            time.sleep(0.01)
        assert replica.state == UP
        assert router.eligible_count() == 1
    finally:
        router.close()
        if revived is not None:
            revived.shutdown()
            revived.server_close()


# -- chaos (slow tier): rolling restart over a real 2-replica fleet -----


@pytest.mark.slow
def test_chaos_rolling_restart_and_crash_zero_client_failures():
    """Acceptance pin: sustained HTTP load through the router over a
    2-replica fleet (tools/fleet.py) survives (1) a full rolling
    restart and (2) a hard SIGKILL of one replica with ZERO failed
    client requests — plain posts, no client-side retries; all
    failover happens in the router. Every reply is attributable to a
    known replica, and each replica's compile counts stay at the
    pinned values (decode=1: routing added no new shapes)."""
    fleet_mod = _load_fleet()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)

    fleet = fleet_mod.Fleet(
        2,
        server_args=["--num-slots", "2", "--prefill-chunk", "16",
                     "--prefill-budget", "32", "--drain-timeout", "60",
                     "--max-queue-len", "0"],
        env=env, max_restarts=3, backoff_base=0.2, backoff_max=2.0,
        ready_timeout_s=180.0,
    )
    router = None
    httpd = None
    try:
        fleet.start()
        names = set()
        # warm every replica DIRECTLY (prefill ladder + decode) so the
        # measured window and the compile pin are deterministic
        for r_url in fleet.urls:
            for n in (1, 2, 4, 8, 16):
                status, body, _ = http_post_json_with_retries(
                    r_url + "/generate",
                    {"prompt_ids": [1] * n, "max_new_tokens": 2,
                     "temperature": 0.0, "seed": 0},
                    timeout=120, max_retries=2,
                )
                assert status == 200, (r_url, n, body)

        cfg = RouterConfig(
            probe_interval_s=0.05, probe_backoff_s=0.05,
            probe_backoff_max_s=0.5, eject_after=2, readmit_after=2,
            max_attempts=4, retry_base_s=0.02, retry_cap_s=0.2,
            default_deadline_s=120.0, wait_for_replica_s=5.0,
        )
        router = Router(fleet.urls, cfg).start()
        for rep in router.replicas:
            names.add(rep.name)
        httpd = serve_router(router, port=0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}/generate"
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        results = []
        results_lock = threading.Lock()
        stop = threading.Event()

        def client(wid):
            k = 0
            while not stop.is_set():
                k += 1
                payload = {
                    "prompt_ids": [1 + (wid + k) % 7] * (1 + (k % 12)),
                    "max_new_tokens": 4, "temperature": 0.0,
                    "seed": wid * 1000 + k, "timeout": 60,
                    "session_id": f"w{wid}",
                }
                req = urllib.request.Request(
                    url, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=90) as r:
                        rec = (r.status, json.load(r))
                except urllib.error.HTTPError as e:
                    rec = (e.code, json.loads(e.read() or b"{}"))
                except OSError as e:
                    rec = (-1, {"error": repr(e)})
                with results_lock:
                    results.append(rec)

        workers = [
            threading.Thread(target=client, args=(w,)) for w in range(4)
        ]
        for w in workers:
            w.start()
        try:
            # phase 1: rolling restart under load (drain -> kill ->
            # relaunch each replica, one at a time), gated on the
            # router RE-ADMITTING each replica before the next drains
            by_url = {rep.url: rep for rep in router.replicas}
            time.sleep(1.0)
            fleet.rolling_restart(
                ready_check=lambda r: by_url[r.url].eligible()
            )
            with results_lock:
                n_after_rolling = len(results)
            assert n_after_rolling > 0, "no load flowed during restart"
            # phase 2: hard crash one replica under load; the fleet
            # supervisor relaunches it, the router routes around it
            fleet.kill(0)
            deadline = time.time() + 120
            while time.time() < deadline and not fleet.replicas[0].alive():
                time.sleep(0.05)
            assert fleet.replicas[0].alive(), "supervisor never relaunched"
            assert fleet.wait_ready(0, timeout_s=180)
            time.sleep(1.0)  # serve a little while fully healed
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=120)
                assert not w.is_alive(), "client hung"

        # ZERO failed client requests, no client-side retries involved
        bad = [(s, b) for s, b in results if s != 200]
        assert not bad, f"{len(bad)} failed requests, first: {bad[:3]}"
        assert len(results) >= 20
        # every reply attributable to a known healthy replica
        for s, b in results:
            assert b.get("replica") in names, b
        assert fleet.replicas[0].restarts >= 1  # the SIGKILL was real
        # compile pin: re-warm each (restarted, cold) replica with the
        # full pinned shape set directly, then assert routed traffic
        # added NOTHING on top — decode sits at exactly 1 cache entry
        for r_url in fleet.urls:
            for n in (1, 2, 4, 8, 16):
                status, _b, _ = http_post_json_with_retries(
                    r_url + "/generate",
                    {"prompt_ids": [1] * n, "max_new_tokens": 2,
                     "temperature": 0.0, "seed": 0},
                    timeout=120, max_retries=2,
                )
                assert status == 200, (r_url, n, _b)
            with urllib.request.urlopen(r_url + "/health",
                                        timeout=30) as r:
                health = json.load(r)
            assert health["compiles"]["decode"] == 1, (r_url, health)
        # the router observed the dance: ejections and/or retries fired
        reg = router.registry.render()
        assert "router_requests_total" in reg
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()
