"""Export parity: weights trained HERE, loaded by the reference's OWN
torch models (utils/torch_export.py — the inverse of torch_import).

For each family: initialize our params, export to the reference
state_dict layout, ``load_state_dict(strict=True)`` into the reference
class imported from /root/reference (never copied), and assert the two
implementations produce the same logits — the bidirectional half of the
interop story (import is covered by test_torch_import.py). Also pins the
pytree round-trip (export -> import == identity) and the on-disk
``save_pretrained`` blob loading through the reference's own
``from_pretrained``.

Skipped automatically when /root/reference or torch is unavailable.
"""

import os
import sys

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

REFERENCE = "/root/reference"
if not os.path.isdir(REFERENCE):  # pragma: no cover
    pytest.skip("reference repo not mounted", allow_module_level=True)
sys.path.insert(0, REFERENCE)

from differential_transformer_replication_tpu.config import ModelConfig  # noqa: E402
from differential_transformer_replication_tpu.models import (  # noqa: E402
    init_model,
    model_forward,
)
from differential_transformer_replication_tpu.utils.torch_export import (  # noqa: E402
    export_reference_state_dict,
    save_reference_checkpoint,
)
from differential_transformer_replication_tpu.utils.torch_import import (  # noqa: E402
    import_reference_state_dict,
    load_reference_checkpoint,
)

DIMS = dict(vocab_size=64, n_embd=32, n_head=2, n_layer=3, block_size=16, dropout=0.0)


def _cfg(kind):
    kw = dict(DIMS, model=kind, compute_dtype="float32")
    if kind == "ndiff":
        kw["n_terms"] = 3
    return ModelConfig(**kw)


def _reference_model(kind):
    torch.manual_seed(0)
    if kind == "control":
        from control import StandardTransformer

        return StandardTransformer(**DIMS)
    if kind == "diff":
        from diff_transformer import DiffTransformer

        return DiffTransformer(**DIMS)
    from Ndiff_transformer import AlternatingDiffTransformer

    return AlternatingDiffTransformer(**DIMS, n_terms=3)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_reference_model_runs_our_weights(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(3), cfg)
    sd = export_reference_state_dict(params, cfg)

    ref = _reference_model(kind)
    # strict: every reference param AND buffer must be present and
    # correctly shaped — missing/unexpected keys fail here
    ref.load_state_dict(sd, strict=True)
    ref.eval()

    rng = np.random.default_rng(11)
    x = rng.integers(0, DIMS["vocab_size"], (2, DIMS["block_size"]))
    with torch.no_grad():
        ref_logits, _ = ref(torch.tensor(x, dtype=torch.long))
    ours, _ = model_forward(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(ours), ref_logits.numpy(), atol=2e-5,
        err_msg=f"{kind}: reference forward on exported weights diverged",
    )


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_export_import_roundtrip(kind):
    cfg = _cfg(kind)
    params = init_model(jax.random.PRNGKey(5), cfg)
    back, inferred = import_reference_state_dict(
        export_reference_state_dict(params, cfg)
    )
    assert inferred.model == kind
    ours = jax.tree_util.tree_leaves(params)
    theirs = jax.tree_util.tree_leaves(back)
    assert len(ours) == len(theirs)
    for a, b in zip(ours, theirs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_save_pretrained_blob_loads_via_reference(tmp_path):
    """The exported blob goes through the reference's OWN from_pretrained
    (Ndiff_transformer.py:243-249) — full on-disk interop for ndiff."""
    from Ndiff_transformer import AlternatingDiffTransformer

    cfg = _cfg("ndiff")
    params = init_model(jax.random.PRNGKey(7), cfg)
    path = str(tmp_path / "ndiff_export.pt")
    save_reference_checkpoint(path, params, cfg, fmt="pretrained")

    ref = AlternatingDiffTransformer.from_pretrained(path).eval()
    rng = np.random.default_rng(13)
    x = rng.integers(0, cfg.vocab_size, (2, cfg.block_size))
    with torch.no_grad():
        ref_logits, _ = ref(torch.tensor(x, dtype=torch.long))
    ours, _ = model_forward(params, x, cfg)
    np.testing.assert_allclose(np.asarray(ours), ref_logits.numpy(), atol=2e-5)


def test_train_blob_loads_via_importer(tmp_path):
    """The best_model.pt-shaped export reads back through our own
    load_reference_checkpoint — the two formats and both directions
    agree."""
    cfg = _cfg("diff")
    params = init_model(jax.random.PRNGKey(9), cfg)
    path = str(tmp_path / "best_model.pt")
    save_reference_checkpoint(
        path, params, cfg, fmt="train", extra={"iter_num": 123}
    )
    back, inferred = load_reference_checkpoint(path)
    assert inferred.model == "diff"
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_non_fp32_leaf_fails_loud():
    """A non-fp32 params leaf (e.g. a future bf16-saved checkpoint) must
    be rejected, not silently rewritten to fp32 (utils/torch_export.py:_t):
    export is a parity surface and a dtype rewrite would hand the reference
    different numbers than the checkpoint holds."""
    import jax.numpy as jnp

    cfg = _cfg("control")
    params = init_model(jax.random.PRNGKey(7), cfg)
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), params)
    with pytest.raises(TypeError, match="expected float32 params"):
        export_reference_state_dict(params, cfg)
