"""Zero-loss in-flight failover (PR 20): live decode-state migration
via checksummed KV-page streaming, plus resume-by-replay crash
recovery.

The load-bearing contracts:

- **Wire format** (serving/migrate.py): a versioned, length-prefixed,
  per-page-CRC32 image of one slot's decode state round-trips exactly;
  a flipped byte ANYWHERE in a shipped page is convicted at decode
  (``MigratePayloadError``) before anything reaches the device, and
  torn framing / bad magic / version skew all fail typed.
- **Replay determinism**: the t-th token's sampling key is
  ``fold_in(PRNGKey(seed), t)`` — a pure function of t — so
  resubmitting prompt+emitted-prefix with ``key_offset`` continues
  greedy AND sampled streams bit-identically, through penalties,
  stop sequences, constraint FSMs, and speculative decoding.
- **Migration parity**: export -> release -> import on a peer engine
  rides the PR-17 zero-recompile swap-in; the migrated continuation's
  full token list equals the uninterrupted run bit-for-bit, radix
  dedup ships fewer pages without changing a token, and the decode
  compile count stays pinned at 1 through export/import churn.
- **Fallback ladder** (serving/router.py): migrate -> replay -> plain
  retry; every rung is typed and counted
  (``router_migrations_total{outcome=}``), a corrupt transfer falls
  back without harming the source slot, and affinity re-pins follow a
  migrated session immediately.

Quick tier: wire-format / ReplayJournal / GL301 pure tests, engine
replay + migration parity, and canned-HTTP router-ladder tests. Slow
tier: the two chaos acceptance gates over a real 2-replica fleet
(SIGKILL mid-decode -> zero failures; drain-by-migration under load).
"""

import json
import pathlib
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from functools import lru_cache
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.analysis.sanitizers import (
    RecompileSentinel,
)
from differential_transformer_replication_tpu.config import (
    ModelConfig,
    RouterConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    SamplingParams,
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.serving.migrate import (
    MIGRATE_MAGIC,
    MIGRATE_VERSION,
    MigrateExportError,
    MigratePayloadError,
    ReplayJournal,
    decode_slot_state,
    encode_slot_state,
    from_wire,
    params_from_dict,
    params_to_dict,
    to_wire,
)
from differential_transformer_replication_tpu.serving.router import (
    Router,
    serve_router,
)
from differential_transformer_replication_tpu.utils import faults

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind, **kw):
    base = dict(
        model=kind, vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


@lru_cache(maxsize=None)
def _setup(kind):
    cfg = _cfg(kind)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab=61, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


def _paged(**kw):
    base = dict(num_slots=2, prefill_chunk=4, prefill_budget=6,
                kv_page_size=8, kv_pool_pages=12)
    base.update(kw)
    return ServingConfig(**base)


def _contig(**kw):
    base = dict(num_slots=2, prefill_chunk=4, prefill_budget=6)
    base.update(kw)
    return ServingConfig(**base)


def _char_vocab(v=61):
    return [chr(i) if 32 <= i < 127 else "" for i in range(v)]


def _page_image(n=32, layers=2, seed=0):
    """A fake page image shaped like ``_extract_page`` output:
    per-layer dicts of arrays (mixed dtypes, like int8 KV + fp32
    scale planes)."""
    rng = np.random.default_rng(seed)
    return [
        {"k": rng.integers(-128, 127, (2, n), dtype=np.int8),
         "v": rng.integers(-128, 127, (2, n), dtype=np.int8),
         "scale": rng.standard_normal((2, 4)).astype(np.float32)}
        for _ in range(layers)
    ]


def _meta(**kw):
    base = {
        "prompt": [1, 2, 3], "params": params_to_dict(SamplingParams()),
        "generated": [4, 5], "n_live": 2, "dedup_pages": 0,
        "page_size": 8, "model": "control", "block_size": 32,
        "filled": 5, "cached_len": 0, "spec_proposed": 0,
        "spec_accepted": 0, "fsm_state": 0, "token_logprobs": None,
        "top_logprobs": None, "deadline_left_s": 0.0,
    }
    base.update(kw)
    return base


# ---------------------------------------------------------------------
# wire format: versioned, length-prefixed, per-page-checksummed
# ---------------------------------------------------------------------


class TestWireFormat:
    def test_roundtrip_with_dedup_holes(self):
        pages = [_page_image(seed=1), None, _page_image(seed=2)]
        blob = encode_slot_state(_meta(n_live=3, dedup_pages=1), pages)
        meta, got = decode_slot_state(blob)
        assert meta["prompt"] == [1, 2, 3]
        assert meta["generated"] == [4, 5]
        assert got[1] is None
        for payload, want in ((got[0], pages[0]), (got[2], pages[2])):
            for lg, lw in zip(payload, want):
                assert sorted(lg) == sorted(lw)
                for key in lw:
                    assert lg[key].dtype == lw[key].dtype
                    np.testing.assert_array_equal(lg[key], lw[key])

    def test_transport_base64_roundtrip(self):
        blob = encode_slot_state(_meta(), [_page_image()])
        assert from_wire(to_wire(blob)) == blob
        with pytest.raises(MigratePayloadError, match="undecodable"):
            from_wire("!!! not base64 !!!")

    def test_flipped_page_byte_is_convicted(self):
        """A single flipped bit anywhere in a page section must raise
        — garbage KV is never attended."""
        blob = encode_slot_state(_meta(), [_page_image()])
        torn = bytearray(blob)
        torn[-1] ^= 0x01  # deep inside the last page's array bytes
        with pytest.raises(MigratePayloadError, match="convicted"):
            decode_slot_state(bytes(torn))

    def test_bad_magic_and_version_skew_fail_typed(self):
        blob = encode_slot_state(_meta(), [_page_image()])
        assert blob[:4] == MIGRATE_MAGIC
        with pytest.raises(MigratePayloadError, match="magic"):
            decode_slot_state(b"NOPE" + blob[4:])
        skew = bytearray(blob)
        skew[5] = MIGRATE_VERSION + 1  # big-endian u16 at offset 4
        with pytest.raises(MigratePayloadError, match="version"):
            decode_slot_state(bytes(skew))

    def test_torn_framing_fails_typed(self):
        blob = encode_slot_state(_meta(), [_page_image()])
        with pytest.raises(MigratePayloadError, match="torn"):
            decode_slot_state(blob[:3])
        with pytest.raises(MigratePayloadError, match="torn"):
            decode_slot_state(blob[:len(blob) // 2])
        with pytest.raises(MigratePayloadError, match="trailing"):
            decode_slot_state(blob + b"x")

    def test_params_survive_json_transit(self):
        """SamplingParams round-trip through the JSON meta (tuples
        become lists on the wire; the dataclass normalizes back)."""
        p = SamplingParams(
            max_new_tokens=7, temperature=0.9, seed=42, top_k=5,
            stop=((1, 2), (3,)), repetition_penalty=1.3,
            presence_penalty=0.4, frequency_penalty=0.2,
            priority="batch", key_offset=3,
        )
        d = json.loads(json.dumps(params_to_dict(p)))
        assert params_from_dict(d) == p


# ---------------------------------------------------------------------
# ReplayJournal: bounded, grow-only, lock-owned
# ---------------------------------------------------------------------


class TestReplayJournal:
    def test_grow_only_and_stale_probe_cannot_shrink(self):
        j = ReplayJournal()
        j.begin("a")
        j.update("a", [1, 2, 3])
        j.update("a", [1, 2])  # stale probe body: ignored
        assert j.tokens("a") == [1, 2, 3]
        j.update("a", [1, 2, 3, 4])
        assert j.tokens("a") == [1, 2, 3, 4]
        assert j.tokens("never-registered") is None
        j.update("never-registered", [9])  # unknown id: no-op
        assert j.tokens("never-registered") is None

    def test_per_entry_cap_and_byte_accounting(self):
        j = ReplayJournal(max_tokens=4)
        j.begin("a")
        j.update("a", list(range(100)))
        assert j.tokens("a") == [0, 1, 2, 3]
        assert j.stats()["bytes"] == 4 * ReplayJournal._TOKEN_BYTES
        j.begin("b")
        j.update("b", [7])
        assert j.stats()["bytes"] == 5 * ReplayJournal._TOKEN_BYTES
        j.finish("a")
        assert j.stats()["bytes"] == 1 * ReplayJournal._TOKEN_BYTES
        assert j.stats()["entries"] == 1

    def test_finished_lru_bounds_and_counts_evictions(self):
        j = ReplayJournal(max_finished=2)
        for name in ("a", "b", "c"):
            j.begin(name)
            j.finish(name)
        assert not j.finished("a")  # evicted, oldest first
        assert j.finished("b") and j.finished("c")
        assert j.stats()["evicted_total"] == 1
        assert j.stats()["finished"] == 2

    def test_begin_is_idempotent(self):
        j = ReplayJournal()
        j.begin("a")
        j.update("a", [1, 2])
        j.begin("a")  # must not reset the entry
        assert j.tokens("a") == [1, 2]


# ---------------------------------------------------------------------
# GL301 mutation test on the REAL journal class (satellite e)
# ---------------------------------------------------------------------


class TestGL301CoversReplayJournal:
    """ReplayJournal is a lock-owning class shared between the probe
    loop, handle_generate, and /metrics readers; GL301 is the machine
    check that its byte/entry writes stay under ``self._lock``.
    Planting exactly that bug — the byte counter hoisted OUT of the
    lock in ``update`` — in the real module source MUST fire; the
    unmutated module must stay clean."""

    SPEC = (
        REPO / "differential_transformer_replication_tpu" / "serving"
        / "migrate.py"
    )
    ANCHOR = (
        "        with self._lock:\n"
        "            cur = self._live.get(journal_id)\n"
        "            if cur is None or len(tokens) <= len(cur):\n"
        "                return"
    )

    def _copy(self, tmp_path, src):
        # keep the serving/ path component: GL301 is a serving-dir rule
        path = tmp_path / "serving" / "migrate.py"
        path.parent.mkdir(parents=True)
        path.write_text(src)
        return path

    def _lint(self, path, rules):
        sys.path.insert(0, str(REPO))
        from differential_transformer_replication_tpu.analysis.lint import (
            lint_paths,
        )

        return lint_paths([str(path)], rules=rules)

    def test_unmutated_journal_is_lock_clean(self, tmp_path):
        path = self._copy(tmp_path, self.SPEC.read_text())
        result = self._lint(path, ["GL301", "GL601", "GL602"])
        assert [f.rule for f in result.active] == []

    def test_planted_off_lock_byte_write_fires(self, tmp_path):
        src = self.SPEC.read_text()
        assert self.ANCHOR in src, (
            "mutation anchor vanished — ReplayJournal.update's lock "
            "block moved; update the anchor so this mutation test "
            "keeps guarding it"
        )
        mutated = src.replace(
            self.ANCHOR,
            "        self._bytes += 1  # planted: off-lock write\n"
            + self.ANCHOR,
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == ["GL301"]
        (finding,) = result.active
        assert "_bytes" in finding.message

    def test_planted_write_under_lock_stays_clean(self, tmp_path):
        src = self.SPEC.read_text()
        mutated = src.replace(
            self.ANCHOR,
            self.ANCHOR.replace(
                "                return",
                "                return\n"
                "            self._bytes += 0  # under the lock",
            ),
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == []


# ---------------------------------------------------------------------
# resume-by-replay: key_offset continues the stream bit-exactly
# ---------------------------------------------------------------------


# reduced matrix, same shape as tests/test_pages.py: every family in
# both layouts and both KV dtypes without the full cross product
REPLAY_CELLS = [
    ("control", "paged", "bf16"),
    ("control", "contig", "int8"),
    ("diff", "contig", "bf16"),
    ("diff", "paged", "int8"),
    ("ndiff", "paged", "bf16"),
    ("ndiff", "contig", "int8"),
]


@pytest.mark.parametrize("kind,layout,kvd", REPLAY_CELLS)
def test_replay_continuation_bit_parity(kind, layout, kvd):
    """Replay = resubmit prompt+emitted-prefix with ``key_offset``
    carrying the key-chain position. Greedy AND sampled continuations
    must be bit-identical to the uninterrupted run at every split
    point — this is the whole correctness argument of the crash rung
    (router resume-by-replay) and it must hold in every engine
    configuration a replica can run."""
    cfg, params = _setup(kind)
    sv = (_paged if layout == "paged" else _contig)(kv_cache_dtype=kvd)
    eng = ServingEngine(params, cfg, sv)
    prompt = _prompts([9], seed=20)[0]
    n = 8

    ref = eng.generate([prompt], max_new_tokens=n, temperature=0.0)[0]
    assert len(ref.tokens) == n
    for k in (1, 4, 7):
        out = eng.generate(
            [prompt + ref.tokens[:k]], max_new_tokens=n - k,
            temperature=0.0, key_offset=k,
        )[0]
        assert out.tokens == ref.tokens[k:], (kind, layout, kvd, k)

    # sampled: the fold_in(key, t) chain is what key_offset preserves
    ref_s = eng.generate(
        [prompt], max_new_tokens=n, temperature=0.9, seed=123,
    )[0]
    out_s = eng.generate(
        [prompt + ref_s.tokens[:3]], max_new_tokens=n - 3,
        temperature=0.9, seed=123, key_offset=3,
    )[0]
    assert out_s.tokens == ref_s.tokens[3:], (kind, layout, kvd)


class TestReplaySpecialStates:
    """Replay must reconstruct every piece of per-slot decode state
    from the prompt tail: penalty histograms, stop-sequence partial
    matches, constraint-FSM cursors, and the spec drafter."""

    def test_penalties_seed_from_prompt_tail(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _paged(kv_cache_dtype="int8"))
        prompt = _prompts([9], seed=21)[0]
        kw = dict(max_new_tokens=8, temperature=0.9, seed=5,
                  repetition_penalty=1.3, presence_penalty=0.4,
                  frequency_penalty=0.2)
        ref = eng.generate([prompt], **kw)[0]
        assert len(ref.tokens) == 8
        out = eng.generate(
            [prompt + ref.tokens[:4]], key_offset=4,
            **{**kw, "max_new_tokens": 4},
        )[0]
        assert out.tokens == ref.tokens[4:]

    def test_stop_sequence_spanning_the_replay_boundary(self):
        """A stop pair whose first token was emitted BEFORE the crash
        must still fire after replay — the matcher's partial state is
        rebuilt from the prompt tail (key_offset tokens)."""
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _contig())
        prompt = _prompts([7], seed=22)[0]
        ref = eng.generate([prompt], max_new_tokens=8,
                           temperature=0.0)[0]
        stop = (tuple(ref.tokens[2:4]),)
        full = eng.generate([prompt], max_new_tokens=8,
                            temperature=0.0, stop=stop)[0]
        assert full.finish_reason == "stop_sequence"
        k = len(full.tokens) - 1  # split INSIDE the stop pair
        out = eng.generate(
            [prompt + full.tokens[:k]], max_new_tokens=8 - k,
            temperature=0.0, stop=stop, key_offset=k,
        )[0]
        assert out.tokens == full.tokens[k:]
        assert out.finish_reason == "stop_sequence"

    def test_constraint_fsm_cursor_rebuilt_from_prompt_tail(self):
        # printable ASCII must fit the vocab so "[ab]" is spellable
        cfg = _cfg("control", vocab_size=128)
        params = init_model(jax.random.PRNGKey(0), cfg)
        eng = ServingEngine(params, cfg, _paged(),
                            vocab=_char_vocab(128))
        prompt = _prompts([6], vocab=128, seed=23)[0]
        kw = dict(max_new_tokens=10, temperature=0.9, seed=9,
                  regex="[ab]{4,8}")
        ref = eng.generate([prompt], **kw)[0]
        assert ref.finish_reason == "constraint_complete"
        k = 2
        out = eng.generate(
            [prompt + ref.tokens[:k]], key_offset=k,
            **{**kw, "max_new_tokens": 10 - k},
        )[0]
        assert out.tokens == ref.tokens[k:]
        assert out.finish_reason == "constraint_complete"

    def test_speculative_decode_replay_parity(self):
        cfg, params = _setup("control")
        eng = ServingEngine(
            params, cfg,
            _paged(spec_mode="ngram", spec_draft_len=4),
        )
        prompt = _prompts([9], seed=24)[0]
        ref = eng.generate([prompt], max_new_tokens=8,
                           temperature=0.0)[0]
        out = eng.generate(
            [prompt + ref.tokens[:3]], max_new_tokens=5,
            temperature=0.0, key_offset=3,
        )[0]
        assert out.tokens == ref.tokens[3:]


# ---------------------------------------------------------------------
# live migration: export -> release -> import parity on a peer engine
# ---------------------------------------------------------------------


def _decode_until(eng, rid, n):
    """Step the engine until the request's slot has emitted >= n
    tokens (the mid-decode moment a drain would catch it at)."""
    for _ in range(400):
        slot = eng._slot_for(rid)
        if slot is not None and len(slot.generated) >= n:
            return slot
        eng.step()
    raise AssertionError(f"request {rid} never reached {n} tokens")


def _migrate(src, dst, rid, dedup_pages=0):
    blob = src.export_slot_state(rid, dedup_pages=dedup_pages)
    assert src.release_migrated(rid) is True
    return dst.import_state(blob)


MIGRATE_CELLS = [("control", "bf16"), ("diff", "int8"),
                 ("ndiff", "int8")]


@pytest.mark.parametrize("kind,kvd", MIGRATE_CELLS)
def test_migrated_continuation_bit_parity(kind, kvd):
    """The money shot: interrupt a SAMPLED decode mid-flight, ship the
    slot's checksummed page image to a peer engine, and the completed
    token list equals the uninterrupted run bit-for-bit — generated
    prefix restored, key chain continued, KV pages injected exact."""
    cfg, params = _setup(kind)
    src = ServingEngine(params, cfg, _paged(kv_cache_dtype=kvd))
    dst = ServingEngine(params, cfg, _paged(kv_cache_dtype=kvd))
    prompt = _prompts([12], seed=30)[0]
    kw = dict(max_new_tokens=10, temperature=0.9, seed=77)

    ref = src.generate([prompt], **kw)[0]
    assert len(ref.tokens) == 10

    rid = src.submit(prompt, **kw)
    _decode_until(src, rid, 4)
    new_rid = _migrate(src, dst, rid, dedup_pages=0)
    outs = dst.run()
    (out,) = [o for o in outs if o.request_id == new_rid]
    assert out.tokens == ref.tokens, (kind, kvd)
    assert src.stats["migrate_exports"] == 1
    assert dst.stats["migrate_imports"] == 1
    # the source retired the slot as migrated, not finished/failed
    assert not src.has_work()


def test_radix_dedup_ships_fewer_pages_same_tokens():
    """Pages the destination's radix tree already holds travel as
    holes; the importer resolves them device-locally. Fewer bytes on
    the wire, identical tokens."""
    cfg, params = _setup("control")
    src = ServingEngine(params, cfg, _paged())
    dst = ServingEngine(params, cfg, _paged())
    prompt = _prompts([12], seed=31)[0]  # one full 8-token page
    kw = dict(max_new_tokens=10, temperature=0.9, seed=78)

    ref = src.generate([prompt], **kw)[0]
    # warm the destination's radix tree with the same prompt prefix
    dst.generate([prompt], max_new_tokens=2, temperature=0.0)
    cached = dst._pages.probe_prefix(prompt)
    assert cached >= 1

    rid = src.submit(prompt, **kw)
    _decode_until(src, rid, 4)
    plain = src.export_slot_state(rid)
    deduped = src.export_slot_state(rid, dedup_pages=cached)
    assert len(deduped) < len(plain)
    assert src.release_migrated(rid) is True
    new_rid = dst.import_state(deduped)
    outs = dst.run()
    (out,) = [o for o in outs if o.request_id == new_rid]
    assert out.tokens == ref.tokens
    assert src.stats["migrate_pages_deduped"] >= 1


class TestMigrateTypedFailures:
    def test_contiguous_layout_export_fails_typed(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _contig())
        rid = eng.submit(_prompts([6], seed=32)[0], max_new_tokens=4)
        with pytest.raises(MigrateExportError, match="paged"):
            eng.export_slot_state(rid)

    def test_unknown_or_queued_request_fails_typed(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _paged())
        with pytest.raises(MigrateExportError) as ei:
            eng.export_slot_state(12345)
        assert ei.value.code == "migrate_not_active"

    def test_geometry_mismatch_fails_typed_on_import(self):
        cfg, params = _setup("control")
        src = ServingEngine(params, cfg, _paged())
        dst = ServingEngine(params, cfg,
                            _paged(kv_page_size=4, kv_pool_pages=24))
        rid = src.submit(_prompts([12], seed=33)[0], max_new_tokens=8,
                         temperature=0.0)
        _decode_until(src, rid, 2)
        blob = src.export_slot_state(rid)
        with pytest.raises(MigrateExportError) as ei:
            dst.import_state(blob)
        assert ei.value.code == "migrate_geometry"

    def test_dedup_miss_fails_typed_and_source_is_unharmed(self):
        """Export claims a dedup the destination no longer caches: the
        import convicts it typed, and the SOURCE — whose slot was
        never disturbed — finishes the request bit-exact (a failed
        transfer costs nothing)."""
        cfg, params = _setup("control")
        src = ServingEngine(params, cfg, _paged())
        dst = ServingEngine(params, cfg, _paged())
        prompt = _prompts([12], seed=34)[0]
        kw = dict(max_new_tokens=10, temperature=0.9, seed=79)
        ref = src.generate([prompt], **kw)[0]

        rid = src.submit(prompt, **kw)
        _decode_until(src, rid, 4)
        blob = src.export_slot_state(rid, dedup_pages=1)
        # dst radix is cold: the claimed chain cannot resolve
        with pytest.raises(MigrateExportError) as ei:
            dst.import_state(blob)
        assert ei.value.code == "migrate_dedup_miss"
        assert not dst.has_work()
        outs = src.run()
        (out,) = [o for o in outs if o.request_id == rid]
        assert out.tokens == ref.tokens


def test_migrate_churn_keeps_decode_compile_pinned():
    """Export/import churn must ride the zero-recompile swap-in: after
    one warm cycle, a second full migration (both directions' engines
    already warm) triggers ZERO new compilations and the destination's
    decode cache sits at exactly 1 entry."""
    cfg, params = _setup("control")
    src = ServingEngine(params, cfg, _paged())
    dst = ServingEngine(params, cfg, _paged())
    prompt = _prompts([12], seed=35)[0]
    kw = dict(max_new_tokens=10, temperature=0.9, seed=80)

    def cycle(seed):
        rid = src.submit(prompt, **{**kw, "seed": seed})
        _decode_until(src, rid, 4)
        new_rid = _migrate(src, dst, rid)
        dst.run()
        return new_rid

    cycle(80)  # warm: prefill ladder + decode + swap-in all jit
    dst.generate([prompt], max_new_tokens=2, temperature=0.0)
    with RecompileSentinel(budget=0, name="migrate-churn"):
        cycle(81)
    assert dst.compile_stats()["decode"] == 1
    assert src.compile_stats()["decode"] == 1


# ---------------------------------------------------------------------
# fault drills (satellite a): migrate_corrupt / migrate_hang
# ---------------------------------------------------------------------


class TestMigrateFaultDrills:
    def test_corrupt_transfer_convicted_and_source_finishes(self):
        """migrate_corrupt flips one byte AFTER the CRCs are stamped:
        the import side must convict the transfer (typed), admit
        nothing, and the undisturbed source still finishes the request
        bit-exact — the zero-loss guarantee under corruption."""
        cfg, params = _setup("control")
        src = ServingEngine(params, cfg, _paged())
        dst = ServingEngine(params, cfg, _paged())
        prompt = _prompts([12], seed=40)[0]
        kw = dict(max_new_tokens=10, temperature=0.9, seed=90)
        ref = src.generate([prompt], **kw)[0]

        rid = src.submit(prompt, **kw)
        _decode_until(src, rid, 4)
        faults.arm("migrate_corrupt")
        blob = src.export_slot_state(rid)
        with pytest.raises(MigratePayloadError, match="convicted"):
            dst.import_state(blob)
        assert not dst.has_work()
        assert dst.stats["migrate_imports"] == 0
        # fault was one-shot: a clean re-export succeeds end to end
        blob = src.export_slot_state(rid)
        assert src.release_migrated(rid) is True
        new_rid = dst.import_state(blob)
        outs = dst.run()
        (out,) = [o for o in outs if o.request_id == new_rid]
        assert out.tokens == ref.tokens

    def test_migrate_hang_stalls_export_via_env_knob(self, monkeypatch):
        monkeypatch.setenv(faults.MIGRATE_HANG_ENV_VAR, "0.12")
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _paged())
        rid = eng.submit(_prompts([12], seed=41)[0], max_new_tokens=8,
                         temperature=0.0)
        _decode_until(eng, rid, 2)
        faults.arm("migrate_hang")
        t0 = time.perf_counter()
        eng.export_slot_state(rid)
        assert time.perf_counter() - t0 >= 0.1
        t0 = time.perf_counter()  # one-shot: disarmed now
        eng.export_slot_state(rid)
        assert time.perf_counter() - t0 < 0.1


class TestMigrateOutOffEngineThread:
    """Regression: probe/transfer used to run INSIDE one engine-thread
    command, so a slow or unreachable destination froze every
    co-resident decode for up to the transfer budget. Only the device
    touches (snapshot, release) may run on the engine thread — the
    network legs stay on the caller's."""

    class _Eng:
        def __init__(self):
            self.serving = ServingConfig(num_slots=4)
            self.stats = {"rejected": 0}
            self.steps = 0
            self.finish = threading.Event()
            self._q = []
            self._rid = 0

        def queue_len(self):
            return 0

        def has_work(self):
            return bool(self._q)

        def submit(self, prompt, params=None, **kw):
            rid = self._rid
            self._rid += 1
            self._q.append(rid)
            return rid

        def cancel(self, rid):
            return False

        def step(self):
            if self.finish.is_set():
                self._q.clear()
            self.steps += 1
            time.sleep(0.002)
            return []

        def _slot_for(self, rid):
            class _S:
                prompt = [1, 2, 3]
            return _S()

        def export_slot_state(self, rid, dedup_pages=0):
            return b"wire-image"

        def release_migrated(self, rid):
            self._q = [r for r in self._q if r != rid]
            return True

    def test_transfer_off_engine_thread_decodes_continue(
        self, monkeypatch
    ):
        import differential_transformer_replication_tpu.serving.server \
            as server_mod

        eng = self._Eng()
        runner = server_mod.EngineRunner(eng)
        calls = []

        def fake_post(url, payload, **kw):
            calls.append(url.rsplit("/", 1)[-1])
            assert threading.current_thread() is not runner._thread, \
                "network leg ran on the engine thread"
            if url.endswith("/migrate/probe"):
                return 200, {"cached_pages": 0}, None
            # the transfer stalls until the engine has stepped three
            # MORE times — were the transfer still an engine command,
            # no step could run and this would time out
            start = eng.steps
            deadline = time.time() + 5.0
            while eng.steps < start + 3:
                assert time.time() < deadline, \
                    "engine thread stalled during the transfer"
                time.sleep(0.002)
            return 200, {"request_id": 0,
                         "migrate_id": payload["migrate_id"]}, None

        monkeypatch.setattr(
            server_mod, "http_post_json_with_retries", fake_post
        )
        try:
            moving = runner.submit([1, 2, 3], max_new_tokens=8)
            resident = runner.submit([4, 5], max_new_tokens=8)
            deadline = time.time() + 5.0
            while moving.rid is None or resident.rid is None:
                assert time.time() < deadline
                time.sleep(0.002)
            res = runner.migrate_out(
                moving.rid, "http://dest", "mig1", budget_s=5.0
            )
            assert res["outcome"] == "migrated"
            assert calls == ["probe", "import"]
            assert moving.done.wait(1.0)
            assert isinstance(moving.error, server_mod.MigratedError)
            assert moving.error.dest == "http://dest"
            # the co-resident request was never settled or disturbed
            assert not resident.settled
        finally:
            eng.finish.set()
            runner.close(timeout=10)


# ---------------------------------------------------------------------
# router fallback ladder over canned HTTP replicas (no jax, no engine)
# ---------------------------------------------------------------------


def _rcfg(**kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_backoff_s", 0.05)
    kw.setdefault("probe_backoff_max_s", 0.4)
    kw.setdefault("retry_base_s", 0.001)
    kw.setdefault("retry_cap_s", 0.01)
    kw.setdefault("wait_for_replica_s", 0.0)
    return RouterConfig(**kw)


def _mark_up(*replicas, now=0.0):
    for r in replicas:
        r.note_probe_success(True, "healthy", {}, now=now)


def _spawn(handler_cls):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _json_handler(on_post, on_get=None):
    """A fake replica: POST bodies go through ``on_post(path, payload)
    -> (status, body)``; GETs through ``on_get(path)``."""

    class H(BaseHTTPRequestHandler):
        def _reply(self, status, body):
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(n) or b"{}")
            self._reply(*on_post(self.path, payload))

        def do_GET(self):
            if on_get is None:
                self._reply(404, {})
            else:
                self._reply(*on_get(self.path))

        def log_message(self, *a):
            pass

    return H


class TestRouterReplayRung:
    def test_replay_resubmits_prefix_with_key_offset(self):
        """First attempt dies AFTER the journal harvested 3 tokens
        (503 engine_crash); the retry must go out as prompt+prefix
        with key_offset=3, a FRESH journal id, and a shrunken
        max_new_tokens — and the client sees one seamless stitched
        reply flagged ``replayed``."""
        state = {"hits": 0, "second": None}
        router_box = {}

        def on_post(path, payload):
            state["hits"] += 1
            if state["hits"] == 1:
                # the dying attempt: the probe loop had harvested a
                # 3-token prefix before the crash
                router_box["r"].journal.update(
                    payload["journal_id"], [5, 6, 7]
                )
                state["first_jid"] = payload["journal_id"]
                return 503, {"code": "engine_crash"}
            state["second"] = payload
            return 200, {"request_id": 2, "tokens": [8, 9],
                         "finish_reason": "length", "ttft_ms": 1.0}

        h = _json_handler(on_post)
        s1, u1 = _spawn(h)
        s2, u2 = _spawn(h)
        router = Router([u1, u2], _rcfg(max_attempts=4),
                        rng=random.Random(0))
        router_box["r"] = router
        _mark_up(*router.replicas)
        try:
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 5,
                "temperature": 0.0,
            })
            assert status == 200
            assert body["tokens"] == [5, 6, 7, 8, 9]
            assert body["prompt_ids"] == [1, 2, 3]
            assert body["replayed"] is True
            second = state["second"]
            assert second["prompt_ids"] == [1, 2, 3, 5, 6, 7]
            assert second["key_offset"] == 3
            assert second["max_new_tokens"] == 2
            assert second["journal_id"] != state["first_jid"]
            assert router._migration_counter.labels(
                outcome="replayed"
            ).value == 1
        finally:
            router.close()
            s1.shutdown()
            s2.shutdown()

    def test_journal_complete_short_circuits_without_resubmit(self):
        """The source died after FINISHING (journal holds all
        max_new_tokens tokens): the router synthesizes the reply from
        the journal instead of decoding extra tokens on a peer."""
        state = {"hits": 0}
        router_box = {}

        def on_post(path, payload):
            state["hits"] += 1
            if state["hits"] == 1:
                router_box["r"].journal.update(
                    payload["journal_id"], [5, 6, 7, 11, 12]
                )
                return 503, {"code": "engine_crash"}
            return 200, {"request_id": 2, "tokens": [99],
                         "finish_reason": "length", "ttft_ms": 1.0}

        s1, u1 = _spawn(_json_handler(on_post))
        s2, u2 = _spawn(_json_handler(on_post))
        router = Router([u1, u2], _rcfg(max_attempts=4),
                        rng=random.Random(0))
        router_box["r"] = router
        _mark_up(*router.replicas)
        try:
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 5,
                "temperature": 0.0,
            })
            assert status == 200
            assert body["tokens"] == [5, 6, 7, 11, 12]
            assert body["finish_reason"] == "length"
            assert body["replayed"] is True
            assert state["hits"] == 1  # no peer resubmission
        finally:
            router.close()
            s1.shutdown()
            s2.shutdown()

    def test_unexpected_exception_retires_journal_entry(self):
        """An exception that escapes the attempt loop (surfacing as
        do_POST's catch-all 500) bypasses _done — the try/finally must
        still retire the live journal entry, or every such failure
        leaks bytes into _live forever (only finished entries evict)."""
        router = Router(["http://127.0.0.1:1"], _rcfg(),
                        rng=random.Random(0))
        _mark_up(*router.replicas)

        def boom(*a, **kw):
            raise RuntimeError("attempt blew up")

        router._attempt = boom
        try:
            with pytest.raises(RuntimeError, match="blew up"):
                router.handle_generate({
                    "prompt_ids": [1, 2], "max_new_tokens": 2,
                })
            stats = router.journal.stats()
            assert stats["entries"] == 0
            assert stats["bytes"] == 0
        finally:
            router.close()

    def test_finish_reason_inference(self):
        f = Router._replay_finish_reason
        assert f([1, 2], {}, 0) == "length"
        assert f([1, 7], {"eos_token_id": 7}, 3) == "eos"
        assert f([1, 2, 3], {"stop": [[2, 3]]}, 3) == "stop_sequence"
        assert f([1, 2, 3], {"stop": [[9]]}, 3) is None
        assert f([], {}, 3) is None


class TestRouterMigrateRung:
    def _pair(self, on_post_a, on_post_b, **cfg_kw):
        sa, ua = _spawn(_json_handler(on_post_a))
        sb, ub = _spawn(_json_handler(on_post_b))
        router = Router([ua, ub], _rcfg(**cfg_kw),
                        rng=random.Random(0))
        _mark_up(*router.replicas)
        return router, (sa, ua), (sb, ub)

    def test_migrated_reply_followed_to_destination(self):
        """200 {"code": "migrated"} flips the blocked /generate into a
        follow: POST dest /migrate/await returns the COMPLETE reply,
        attribution flips to the destination, the outcome counter
        ticks ``migrated``, and the sticky session re-pins NOW."""
        box = {}

        def on_a(path, payload):
            assert path == "/generate"
            return 200, {"code": "migrated", "dest": box["ub"],
                         "migrate_id": "m1"}

        def on_b(path, payload):
            box["await"] = (path, payload)
            return 200, {"request_id": 7, "prompt_ids": [1, 2, 3],
                         "tokens": [4, 5], "finish_reason": "length",
                         "ttft_ms": 2.0}

        router, (sa, ua), (sb, ub) = self._pair(on_a, on_b)
        box["ub"] = ub
        try:
            # pre-pin the session to A so the first attempt lands there
            assert router.repin("s1", ua) is True
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 5,
                "session_id": "s1",
            })
            assert status == 200
            assert body["migrated"] is True
            assert body["tokens"] == [4, 5]
            path, awaited = box["await"]
            assert path == "/migrate/await"
            assert awaited["migrate_id"] == "m1"
            b_rep = next(r for r in router.replicas if r.url == ub)
            assert body["replica"] == b_rep.name
            assert router._migration_counter.labels(
                outcome="migrated"
            ).value == 1
            # affinity followed the moved state immediately
            with router._aff_lock:
                assert router._affinity["s1"] is b_rep
        finally:
            router.close()
            sa.shutdown()
            sb.shutdown()

    def test_chained_migration_followed_across_hops(self):
        """The destination itself drains while decoding the imported
        continuation (one-at-a-time rolling restarts do this
        naturally): /migrate/await answers ANOTHER forwarding pointer.
        The router must follow the chain to the final replica — never
        hand the pointer body to the client as a "successful"
        generation with no tokens."""
        box = {"awaits": []}

        def on_a(path, payload):
            assert path == "/generate"
            return 200, {"code": "migrated", "dest": box["ub"],
                         "migrate_id": "m1"}

        def on_b(path, payload):
            assert path == "/migrate/await"
            box["awaits"].append(("b", payload["migrate_id"]))
            return 200, {"code": "migrated", "dest": box["uc"],
                         "migrate_id": "m2"}

        def on_c(path, payload):
            assert path == "/migrate/await"
            box["awaits"].append(("c", payload["migrate_id"]))
            return 200, {"request_id": 7, "prompt_ids": [1, 2, 3],
                         "tokens": [4, 5], "finish_reason": "length",
                         "ttft_ms": 2.0}

        sa, ua = _spawn(_json_handler(on_a))
        sb, ub = _spawn(_json_handler(on_b))
        sc, uc = _spawn(_json_handler(on_c))
        box["ub"], box["uc"] = ub, uc
        router = Router([ua, ub, uc], _rcfg(), rng=random.Random(0))
        _mark_up(*router.replicas)
        try:
            assert router.repin("s1", ua) is True
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 5,
                "session_id": "s1",
            })
            assert status == 200
            assert body["migrated"] is True
            assert body["tokens"] == [4, 5]
            assert box["awaits"] == [("b", "m1"), ("c", "m2")]
            c_rep = next(r for r in router.replicas if r.url == uc)
            assert body["replica"] == c_rep.name
            # affinity followed the moved state through EVERY hop
            with router._aff_lock:
                assert router._affinity["s1"] is c_rep
            assert router._migration_counter.labels(
                outcome="migrated"
            ).value == 1
        finally:
            router.close()
            for s in (sa, sb, sc):
                s.shutdown()

    def test_migration_hop_limit_falls_back_to_replay(self):
        """A pathological forwarding chain (the destination keeps
        answering another pointer) is bounded by migrate_max_hops;
        past the bound the router drops to the replay rung instead of
        looping forever — the client still gets real tokens."""
        box = {"awaits": 0, "b_gen": None}
        router_box = {}

        def on_a(path, payload):
            router_box["r"].journal.update(payload["journal_id"], [5])
            return 200, {"code": "migrated", "dest": box["ub"],
                         "migrate_id": "m1"}

        def on_b(path, payload):
            if path == "/migrate/await":
                box["awaits"] += 1
                return 200, {"code": "migrated", "dest": box["ub"],
                             "migrate_id": f"m{box['awaits'] + 1}"}
            box["b_gen"] = payload
            return 200, {"request_id": 9, "tokens": [6],
                         "finish_reason": "length", "ttft_ms": 1.0}

        router, (sa, ua), (sb, ub) = self._pair(
            on_a, on_b, max_attempts=4, migrate_max_hops=2
        )
        box["ub"] = ub
        router_box["r"] = router
        try:
            assert router.repin("s1", ua) is True
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 2,
                "session_id": "s1",
            })
            assert status == 200
            assert body["tokens"] == [5, 6]
            assert body["replayed"] is True
            assert box["awaits"] == 2  # the hop bound held
            assert box["b_gen"]["key_offset"] == 1
            labels = router._migration_counter.labels
            assert labels(outcome="migrate_failed").value == 1
            assert labels(outcome="replayed").value == 1
        finally:
            router.close()
            sa.shutdown()
            sb.shutdown()

    def test_await_failure_falls_back_to_replay(self):
        """Destination lost the continuation between import and
        finish: migrate_await_failed is retriable by construction, and
        the replay rung reconstructs from the journal — the ladder
        never strands a request on a broken migration."""
        box = {"b_gen": None}
        router_box = {}

        def on_a(path, payload):
            router_box["r"].journal.update(payload["journal_id"], [5])
            return 200, {"code": "migrated", "dest": box["ub"],
                         "migrate_id": "m1"}

        def on_b(path, payload):
            if path == "/migrate/await":
                return 503, {"code": "migrate_import_failed"}
            box["b_gen"] = payload
            return 200, {"request_id": 9, "tokens": [6],
                         "finish_reason": "length", "ttft_ms": 1.0}

        router, (sa, ua), (sb, ub) = self._pair(on_a, on_b,
                                                max_attempts=4)
        box["ub"] = ub
        router_box["r"] = router
        try:
            assert router.repin("s1", ua) is True
            status, body, _ = router.handle_generate({
                "prompt_ids": [1, 2, 3], "max_new_tokens": 2,
                "session_id": "s1",
            })
            assert status == 200
            assert body["tokens"] == [5, 6]
            assert body["replayed"] is True
            assert box["b_gen"]["key_offset"] == 1
            labels = router._migration_counter.labels
            assert labels(outcome="migrate_failed").value == 1
            assert labels(outcome="replayed").value == 1
        finally:
            router.close()
            sa.shutdown()
            sb.shutdown()


class TestRouterDrain:
    def test_migrate_out_enumerates_and_skips_tokenless(self):
        """Drain: GET source /inflight, POST one /migrate/export per
        ACTIVE request to the least-loaded peer; queued/prefilling
        entries (no tokens) are left to the replay rung."""
        box = {"exports": []}

        def on_a_get(path):
            assert path == "/inflight"
            return 200, {"inflight": [
                {"request_id": 3, "prompt_len": 4, "tokens": [1, 2],
                 "journal_id": "j1"},
                {"request_id": 9, "prompt_len": 2, "tokens": [],
                 "journal_id": "j2"},
            ]}

        def on_a_post(path, payload):
            assert path == "/migrate/export"
            box["exports"].append(payload)
            return 200, {"outcome": "migrated"}

        sa, ua = _spawn(_json_handler(on_a_post, on_a_get))
        sb, ub = _spawn(_json_handler(lambda p, b: (200, {})))
        router = Router([ua, ub], _rcfg(), rng=random.Random(0))
        _mark_up(*router.replicas)
        try:
            res = router.migrate_out(ua)
            assert res["migrated"] == 1
            assert res["failed"] == 0
            assert res["drain_seconds"] >= 0.0
            (exp,) = box["exports"]
            assert exp["request_id"] == 3
            assert exp["dest"] == ub
            assert exp["budget_s"] == router.cfg.migrate_budget_s
            assert exp["migrate_id"]
        finally:
            router.close()
            sa.shutdown()
            sb.shutdown()

    def test_migrate_budget_zero_disables_migration(self):
        router = Router(
            ["http://127.0.0.1:1", "http://127.0.0.1:2"],
            _rcfg(migrate_budget_s=0.0), rng=random.Random(0),
        )
        try:
            res = router.migrate_out("http://127.0.0.1:1")
            assert res["outcome"] == "migration_disabled"
            assert res["migrated"] == 0
        finally:
            router.close()

    def test_probe_harvests_inflight_into_journal(self):
        def on_get(path):
            if path == "/ready":
                return 200, {"ready": True, "status": "healthy"}
            if path == "/metrics":
                return 200, {}
            assert path == "/inflight"
            return 200, {"inflight": [
                {"request_id": 1, "journal_id": "jx",
                 "tokens": [1, 2, 3]},
            ]}

        s, u = _spawn(_json_handler(lambda p, b: (404, {}), on_get))
        router = Router([u], _rcfg(), rng=random.Random(0))
        try:
            router.journal.begin("jx")
            router.probe(router.replicas[0])
            assert router.journal.tokens("jx") == [1, 2, 3]
            assert router._journal_bytes_gauge.value == 3 * 4
        finally:
            router.close()
            s.shutdown()

    def test_repin_moves_affinity_and_counts(self):
        router = Router(
            ["http://127.0.0.1:19101", "http://127.0.0.1:19102"],
            _rcfg(), rng=random.Random(0),
        )
        try:
            a, b = router.replicas
            moves0 = router._move_counter.value
            assert router.repin("s", a.url) is True
            assert router.repin("s", b.url) is True
            with router._aff_lock:
                assert router._affinity["s"] is b
            assert router.repin("s", b.url) is True  # no-op re-pin
            assert router._move_counter.value == moves0 + 2
            assert router.repin("s", "http://nowhere:1") is False
        finally:
            router.close()


# ---------------------------------------------------------------------
# end to end over live HTTP: drain-by-migration, zero loss (quick tier)
# ---------------------------------------------------------------------


def _http_post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_e2e_http_drain_migrates_inflight_request_bit_exact():
    """Two real in-process replicas behind a real router: a sampled
    request is caught mid-decode by ``migrate_out`` on its replica,
    live-migrates to the peer, and the client's single blocking POST
    returns 200 with the COMPLETE token list — bit-identical to the
    same request run undisturbed. Every hop is the production path:
    /inflight -> /migrate/export -> /migrate/probe -> /migrate/import
    -> /migrate/await."""
    cfg, params = _setup("control")
    clients = [
        ServingClient(ServingEngine(params, cfg, _paged()))
        for _ in range(2)
    ]
    servers = [serve(c, port=0) for c in clients]
    for s in servers:
        threading.Thread(target=s.serve_forever, daemon=True).start()
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
    router = Router(urls, _rcfg(max_attempts=4, migrate_budget_s=10.0,
                                default_deadline_s=120.0,
                                wait_for_replica_s=5.0),
                    rng=random.Random(0)).start()
    httpd = serve_router(router, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    rurl = f"http://127.0.0.1:{httpd.server_address[1]}/generate"
    prompt = _prompts([6], seed=50)[0]
    payload = {"prompt_ids": prompt, "max_new_tokens": 20,
               "temperature": 0.9, "seed": 7, "session_id": "mig"}
    try:
        # warm both replicas (compile outside the measured window)
        for u in urls:
            st, _ = _http_post(u + "/generate",
                               {"prompt_ids": prompt,
                                "max_new_tokens": 2,
                                "temperature": 0.0})
            assert st == 200
        result = {}

        def post():
            result["r"] = _http_post(rurl, payload)

        drained = None
        for _ in range(3):  # decode is fast on CPU: allow re-tries
            drained = None
            t = threading.Thread(target=post)
            t.start()
            # catch the request mid-decode via the engines directly
            src = None
            deadline = time.time() + 30
            while time.time() < deadline and src is None:
                for u, c in zip(urls, clients):
                    if any(e.get("tokens")
                           for e in c.runner.inflight_snapshot()):
                        src = u
                        break
                time.sleep(0.002)
            if src is not None:
                drained = router.migrate_out(src)
            t.join(timeout=120)
            assert not t.is_alive()
            if drained and drained.get("migrated"):
                break
        assert drained and drained["migrated"] >= 1, drained
        status, body = result["r"]
        assert status == 200
        assert body.get("migrated") is True
        assert len(body["tokens"]) == 20
        # bit-parity: the same request undisturbed on a replica
        st, ref = _http_post(urls[0] + "/generate",
                             {k: v for k, v in payload.items()
                              if k != "session_id"})
        assert st == 200
        assert body["tokens"] == ref["tokens"]
        # the sticky session followed the moved state
        dest_url = next(u for u in urls if u != src)
        with router._aff_lock:
            assert router._affinity["mig"].url == dest_url
        # counters: one migrated outcome, drain histogram observed
        assert router._migration_counter.labels(
            outcome="migrated"
        ).value >= 1
        time.sleep(0.2)  # let a probe harvest the replica counters
        reg = router.fleet_metrics()
        assert "router_migrations_total" in reg
        assert "serving_migrate_pages_shipped_total" in reg
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.close()
        for s in servers:
            s.shutdown()
            s.server_close()
        for c in clients:
            c.close()


# ---------------------------------------------------------------------
# chaos acceptance gates (slow tier): real 2-replica fleet
# ---------------------------------------------------------------------


def _load_fleet():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "fleet", str(REPO / "tools" / "fleet.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fleet_env():
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_chaos_gate_a_sigkill_mid_decode_zero_loss_replay():
    """Acceptance gate A: sustained greedy load through the router
    over a 2-replica fleet survives a hard SIGKILL mid-decode with
    ZERO failed requests; every reply that rode the replay rung is
    bit-identical to the same request run undisturbed, and journaled
    requests resumed from their emitted prefix (no full re-decode from
    scratch is observable: the replayed flag proves the rung)."""
    from differential_transformer_replication_tpu.serving.retry import (
        http_post_json_with_retries,
    )

    fleet_mod = _load_fleet()
    fleet = fleet_mod.Fleet(
        2,
        server_args=["--num-slots", "2", "--prefill-chunk", "16",
                     "--prefill-budget", "32", "--drain-timeout", "60",
                     "--max-queue-len", "0"],
        env=_fleet_env(), max_restarts=3, backoff_base=0.2,
        backoff_max=2.0, ready_timeout_s=180.0,
    )
    router = None
    httpd = None
    try:
        fleet.start()
        for r_url in fleet.urls:
            for n in (1, 2, 4, 8, 16):
                status, body, _ = http_post_json_with_retries(
                    r_url + "/generate",
                    {"prompt_ids": [1] * n, "max_new_tokens": 2,
                     "temperature": 0.0, "seed": 0},
                    timeout=120, max_retries=2,
                )
                assert status == 200, (r_url, n, body)
        cfg = RouterConfig(
            probe_interval_s=0.02, probe_backoff_s=0.05,
            probe_backoff_max_s=0.5, eject_after=2, readmit_after=2,
            max_attempts=4, retry_base_s=0.02, retry_cap_s=0.2,
            default_deadline_s=120.0, wait_for_replica_s=5.0,
        )
        router = Router(fleet.urls, cfg).start()
        httpd = serve_router(router, port=0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}/generate"
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

        results = []
        results_lock = threading.Lock()
        stop = threading.Event()

        def client(wid):
            k = 0
            while not stop.is_set():
                k += 1
                # long generations: each request spends many probe
                # intervals decoding, so the journal harvest has real
                # emitted prefixes when the SIGKILL lands
                payload = {
                    "prompt_ids": [1 + (wid + k) % 7] * (1 + (k % 12)),
                    "max_new_tokens": 100, "temperature": 0.0,
                    "seed": 0, "timeout": 60,
                }
                status, body = _http_post(url, payload, timeout=90)
                with results_lock:
                    results.append((payload, status, body))

        workers = [threading.Thread(target=client, args=(w,))
                   for w in range(4)]
        for w in workers:
            w.start()
        try:
            # kill the replica that provably has a JOURNALED in-flight
            # request: wait until the probe loop harvested a prefix,
            # then SIGKILL whichever replica is mid-decode
            victim = None
            deadline = time.time() + 60
            while time.time() < deadline and victim is None:
                if router.journal.stats()["bytes"] == 0:
                    time.sleep(0.005)
                    continue
                for i, u in enumerate(fleet.urls):
                    try:
                        with urllib.request.urlopen(
                            u + "/inflight", timeout=5
                        ) as r:
                            ents = json.load(r).get("inflight", [])
                    except OSError:
                        continue
                    if any(e.get("tokens") for e in ents):
                        victim = i
                        break
            assert victim is not None, "no journaled in-flight decode"
            fleet.kill(victim)  # SIGKILL mid-decode
            deadline = time.time() + 120
            while (time.time() < deadline
                   and not fleet.replicas[victim].alive()):
                time.sleep(0.05)
            assert fleet.replicas[victim].alive()
            assert fleet.wait_ready(victim, timeout_s=180)
            time.sleep(1.0)
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=120)
                assert not w.is_alive()

        bad = [(s, b) for _p, s, b in results if s != 200]
        assert not bad, f"{len(bad)} failed requests, first: {bad[:3]}"
        assert len(results) >= 20
        replayed = [(p, b) for p, s, b in results if b.get("replayed")]
        assert replayed, "SIGKILL never caught a journaled request"
        # greedy continuations are bit-identical to undisturbed runs
        survivor = fleet.urls[1 - victim]
        for payload, body in replayed[:5]:
            ref_p = {k: v for k, v in payload.items() if k != "timeout"}
            status, ref, _ = http_post_json_with_retries(
                survivor + "/generate", ref_p, timeout=120,
                max_retries=2,
            )
            assert status == 200
            assert body["tokens"] == ref["tokens"], payload
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()


@pytest.mark.slow
def test_chaos_gate_b_drain_by_migration_bounded_and_bit_exact():
    """Acceptance gate B: draining a replica whose in-flight requests
    have FAR more decode left than the drain budget completes within
    the bound by MIGRATING them (drain time ~ page-transfer time, not
    max_new_tokens' worth of decoding); migrated continuations are
    bit-identical on the peer and each replica's decode compile count
    stays pinned at 1 through the export/import churn."""
    from differential_transformer_replication_tpu.serving.retry import (
        http_post_json_with_retries,
    )

    fleet_mod = _load_fleet()
    fleet = fleet_mod.Fleet(
        2,
        server_args=["--num-slots", "2", "--prefill-chunk", "16",
                     "--prefill-budget", "32", "--drain-timeout", "60",
                     "--max-queue-len", "0", "--kv-page-size", "8",
                     "--kv-pool-pages", "64"],
        env=_fleet_env(), max_restarts=3, backoff_base=0.2,
        backoff_max=2.0, ready_timeout_s=180.0,
    )
    router = None
    httpd = None
    try:
        fleet.start()
        for r_url in fleet.urls:
            for n in (1, 2, 4, 8, 16):
                status, body, _ = http_post_json_with_retries(
                    r_url + "/generate",
                    {"prompt_ids": [1] * n, "max_new_tokens": 2,
                     "temperature": 0.0, "seed": 0},
                    timeout=120, max_retries=2,
                )
                assert status == 200, (r_url, n, body)
        cfg = RouterConfig(
            probe_interval_s=0.05, probe_backoff_s=0.05,
            probe_backoff_max_s=0.5, eject_after=3, readmit_after=2,
            max_attempts=4, retry_base_s=0.02, retry_cap_s=0.2,
            default_deadline_s=300.0, wait_for_replica_s=5.0,
            migrate_budget_s=20.0,
        )
        router = Router(fleet.urls, cfg).start()
        httpd = serve_router(router, port=0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}/generate"
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

        # long generations under CONTINUOUS load: every request has
        # far more decode left than a drain takes, and there is always
        # something mid-decode for the drain to catch
        results = []
        results_lock = threading.Lock()
        stop = threading.Event()

        def client(wid):
            k = 0
            while not stop.is_set():
                k += 1
                payload = {
                    "prompt_ids": [2 + (wid + k) % 5] * 8,
                    "max_new_tokens": 100, "temperature": 0.0,
                    "seed": 0, "timeout": 240,
                }
                status, body = _http_post(url, payload, timeout=300)
                with results_lock:
                    results.append((payload, status, body))

        workers = [threading.Thread(target=client, args=(w,))
                   for w in range(3)]
        for w in workers:
            w.start()
        drained = None
        try:
            # drain whichever replica is provably mid-decode; decode
            # on the tiny demo model is fast, so retry until a drain
            # catches a request with real pages to ship
            deadline = time.time() + 120
            while time.time() < deadline:
                src = None
                for u in fleet.urls:
                    try:
                        with urllib.request.urlopen(
                            u + "/inflight", timeout=5
                        ) as r:
                            ents = json.load(r).get("inflight", [])
                    except OSError:
                        continue
                    if any(len(e.get("tokens") or []) >= 2
                           for e in ents):
                        src = u
                        break
                if src is None:
                    time.sleep(0.01)
                    continue
                drained = router.migrate_out(src)
                if drained["migrated"] >= 1:
                    break
            assert drained is not None, "no in-flight decode observed"
            assert drained["migrated"] >= 1, drained
            assert drained["drain_seconds"] < 20.0, drained
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=300)
                assert not w.is_alive()

        bad = [(s, b) for _p, s, b in results if s != 200]
        assert not bad, f"failed requests: {bad[:3]}"
        migrated = [(p, b) for p, s, b in results
                    if b.get("migrated")]
        assert migrated, "drain migrated nothing visible to clients"
        for payload, body in migrated[:5]:
            assert len(body["tokens"]) == 100
            ref_p = {k: v for k, v in payload.items() if k != "timeout"}
            status, ref, _ = http_post_json_with_retries(
                src + "/generate", ref_p, timeout=240, max_retries=2,
            )
            assert status == 200
            assert body["tokens"] == ref["tokens"], payload
        # compile pin: routed + migrated traffic added no decode shapes
        for r_url in fleet.urls:
            with urllib.request.urlopen(r_url + "/health",
                                        timeout=30) as r:
                health = json.load(r)
            assert health["compiles"]["decode"] == 1, (r_url, health)
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.close()
        fleet.stop()
