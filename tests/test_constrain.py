"""Structured decoding (serving/constrain.py + the engine pipeline).

The subsystem's contract, pinned at every layer: the FSM compiler
(regex -> char DFA -> token FSM over a concrete vocab, Willard & Louf
2023) admits exactly the constraint's language; the refcounted compile
cache shares one FSM across identical requests; and inside the ONE
jitted pool step, constraints/penalties/stop/logprobs ride runtime
arrays — unconstrained rows stay bit-identical, mixed traffic never
recompiles, and constrained+spec greedy output is bit-identical to
constrained non-spec greedy for all three model families.
"""

import json
import re as pyre
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from functools import lru_cache
from pathlib import Path

import jax
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import init_model
from differential_transformer_replication_tpu.serving import (
    ConstraintCache,
    ConstraintCompileError,
    ConstraintDeadEndError,
    SamplingParams,
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.serving.constrain import (
    build_token_fsm,
    compile_constraint,
    compile_regex,
    schema_to_regex,
    spec_key,
)
from differential_transformer_replication_tpu.utils import faults

REPO = Path(__file__).resolve().parents[1]

V = 128  # printable ASCII must fit: '{' is 0x7b


def _char_vocab(v=V):
    return [chr(i) if 32 <= i < 127 else "" for i in range(v)]


def _ids(text):
    return [ord(c) for c in text]


def _text(tokens, vocab=None):
    vocab = vocab or _char_vocab()
    return "".join(vocab[t] for t in tokens)


def _cfg(kind):
    return ModelConfig(
        model=kind, vocab_size=V, n_embd=32, n_head=2, n_layer=2,
        block_size=64, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind):
    cfg = _cfg(kind)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _serving(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_budget", 16)
    return ServingConfig(**kw)


def _prompts(lens, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, size=n).tolist() for n in lens]


# ---------------------------------------------------------------------
# regex -> char DFA
# ---------------------------------------------------------------------


class TestRegexCompiler:
    @pytest.mark.parametrize("pattern,samples", [
        ("[ab]{4,8}", ["abab", "aaaaaaaa", "ab", "ababababa", "abcx"]),
        ("a(b|c)*d", ["ad", "abcbcd", "abd", "a", "abc", "dd"]),
        ("yes|no|maybe", ["yes", "no", "maybe", "ye", "nope", ""]),
        ("-?[0-9]+", ["-7", "42", "007", "-", "", "4.2"]),
        ("x?y+z*", ["y", "xyz", "xyyzz", "x", "z", "xy"]),
    ])
    def test_matches_python_re(self, pattern, samples):
        dfa = compile_regex(pattern)
        for s in samples:
            assert dfa.matches(s) == bool(pyre.fullmatch(pattern, s)), (
                pattern, s
            )

    def test_literal_escapes(self):
        dfa = compile_regex(r"\{a\}")
        assert dfa.matches("{a}")
        assert not dfa.matches("a")


class TestSchemaToRegex:
    def _dfa(self, schema):
        return compile_regex(schema_to_regex(schema))

    def test_boolean_object(self):
        dfa = self._dfa({
            "type": "object",
            "properties": {"ok": {"type": "boolean"}},
            "required": ["ok"],
        })
        assert dfa.matches('{"ok":true}')
        assert dfa.matches('{"ok":false}')
        assert not dfa.matches('{"ok":1}')
        assert not dfa.matches("{}")
        assert not dfa.matches('{"ok": true}')  # canonical: no spaces

    def test_enum_const_and_scalars(self):
        assert self._dfa({"enum": ["x", "y"]}).matches('"x"')
        assert not self._dfa({"enum": ["x", "y"]}).matches('"z"')
        assert self._dfa({"const": 42}).matches("42")
        assert self._dfa({"type": "integer"}).matches("-7")
        assert not self._dfa({"type": "integer"}).matches("4.2")
        assert self._dfa({"type": "null"}).matches("null")

    def test_string_bounds(self):
        dfa = self._dfa({"type": "string", "maxLength": 3})
        assert dfa.matches('"abc"')
        assert not dfa.matches('"abcd"')

    def test_nested_object_and_array(self):
        dfa = self._dfa({
            "type": "object",
            "properties": {
                "tags": {"type": "array",
                         "items": {"type": "boolean"}},
            },
        })
        assert dfa.matches('{"tags":[]}')
        assert dfa.matches('{"tags":[true,false]}')
        assert not dfa.matches('{"tags":[true,]}')

    def test_unsupported_fails_typed(self):
        with pytest.raises(ConstraintCompileError):
            schema_to_regex({"type": "array"})  # items required
        with pytest.raises(ConstraintCompileError):
            schema_to_regex({"anyOf": []})
        with pytest.raises(ConstraintCompileError):
            schema_to_regex("not-a-dict")


# ---------------------------------------------------------------------
# char DFA -> token FSM over a vocab
# ---------------------------------------------------------------------


class TestTokenFsm:
    # multi-char BPE-style vocab: id 0 is the "" never-allowed marker
    VOCAB = ["", "a", "b", "ab", "ba", "c"]

    def _fsm(self, pattern, eos=None):
        return build_token_fsm(compile_regex(pattern), self.VOCAB, eos)

    def test_start_mask_walks_multichar_tokens(self):
        fsm = self._fsm("ab+")
        row = fsm.allowed_row(fsm.start)
        # "a" and "ab" both spell a prefix of the language; "b"/"ba"/
        # "c" do not; "" never advances anything
        assert row.tolist() == [False, True, False, True, False, False]

    def test_walk_matches_prefix_len(self):
        fsm = self._fsm("ab+")
        assert fsm.matches([3])          # "ab"
        assert fsm.matches([1, 2, 2])    # "a","b","b"
        assert not fsm.matches([1])      # "a" alone: not accepting
        assert not fsm.matches([4])      # "ba"
        assert fsm.prefix_len([1, 2, 4]) == 2  # "ba" after "ab" dies
        assert fsm.walk([1, 2]) >= 0
        assert fsm.walk([2]) == -1

    def test_eos_column_on_accepting_states_only(self):
        eos = 0  # reuse the "" id as EOS: it must appear via the EOS
        fsm = self._fsm("ab", eos=eos)  # column, never via text walk
        assert not fsm.allowed_row(fsm.start)[eos]
        end = fsm.walk([3])  # "ab" -> accepting
        assert fsm.is_accepting(end)
        assert fsm.allowed_row(end)[eos]
        assert fsm.advance(end, eos) == -1  # EOS has no successor

    def test_empty_language_fails_typed(self):
        with pytest.raises(ConstraintCompileError):
            self._fsm("z+")  # unspellable with this vocab

    def test_nbytes_accounts_tables(self):
        fsm = self._fsm("ab+")
        assert fsm.nbytes >= fsm.masks.nbytes + fsm.trans.nbytes


class TestConstraintCache:
    KEYS = [("regex", "[ab]{2}", None), ("regex", "a+", None),
            ("regex", "b+", None)]
    VOCAB = ["", "a", "b"]

    def test_refcount_hit_miss_stats(self):
        c = ConstraintCache(max_entries=8)
        f1 = c.acquire(self.KEYS[0], self.VOCAB)
        f2 = c.acquire(self.KEYS[0], self.VOCAB)
        assert f1 is f2
        st = c.stats()
        assert st["entries"] == 1 and st["referenced"] == 1
        assert st["hits_total"] == 1 and st["misses_total"] == 1
        c.release(self.KEYS[0])
        c.release(self.KEYS[0])
        assert c.stats()["referenced"] == 0
        assert c.stats()["entries"] == 1  # stays cached at refcount 0
        assert c.stats()["bytes"] > 0

    def test_lru_eviction_spares_referenced(self):
        c = ConstraintCache(max_entries=2)
        c.acquire(self.KEYS[0], self.VOCAB)  # held
        c.acquire(self.KEYS[1], self.VOCAB)
        c.release(self.KEYS[1])
        c.acquire(self.KEYS[2], self.VOCAB)
        c.release(self.KEYS[2])
        st = c.stats()
        # KEYS[1] (oldest refcount-0) was evicted; the referenced
        # KEYS[0] survived
        assert st["entries"] == 2 and st["evictions_total"] == 1
        c.acquire(self.KEYS[1], self.VOCAB)
        assert c.stats()["misses_total"] == 4  # 3 cold + re-compile

    def test_bad_capacity(self):
        with pytest.raises(ValueError):
            ConstraintCache(max_entries=0)


# ---------------------------------------------------------------------
# the shared logit pipeline (models/decode.py)
# ---------------------------------------------------------------------


class TestLogitPipeline:
    def test_numpy_oracle_and_bitwise_passthrough(self):
        from differential_transformer_replication_tpu.models.decode import (
            apply_logit_pipeline,
        )

        rng = np.random.default_rng(0)
        B, Vs = 3, 7
        logits = rng.normal(size=(B, Vs)).astype(np.float32)
        counts = rng.integers(0, 3, size=(B, Vs)).astype(np.int32)
        allowed = rng.random((B, Vs)) > 0.3
        allowed[0] = True  # row 0: default row
        counts[0] = 0
        rep = np.array([1.0, 1.5, 2.0], np.float32)
        pres = np.array([0.0, 0.4, 0.0], np.float32)
        freq = np.array([0.0, 0.0, 0.2], np.float32)
        got = np.asarray(apply_logit_pipeline(
            logits, allowed, counts, rep, pres, freq
        ))
        # numpy oracle
        seen = counts > 0
        pen = np.where(
            seen,
            np.where(logits > 0, logits / rep[:, None],
                     logits * rep[:, None]),
            logits,
        )
        pen = pen - pres[:, None] * seen - freq[:, None] * counts
        ref = np.where(allowed, pen, -np.inf)
        ref[0] = logits[0]  # inactive row passes through raw
        assert np.array_equal(got[1:], ref[1:].astype(np.float32))
        # the default row is BITWISE the input — the engine's pinned
        # unconstrained bit-repro depends on this
        assert got[0].tobytes() == logits[0].tobytes()


# ---------------------------------------------------------------------
# engine integration: the one jitted pool step
# ---------------------------------------------------------------------


REGEX = "[ab]{4,8}"


def _constrained_params(seed=0, n=16, **kw):
    kw.setdefault("regex", REGEX)
    return SamplingParams(max_new_tokens=n, temperature=0.0,
                          seed=seed, **kw)


class TestEngineConstrained:
    def test_greedy_valid_and_bit_reproducible_across_batches(self):
        """The same constrained request produces IDENTICAL tokens
        alone and inside a mixed batch; its unconstrained neighbors
        are bit-identical to an engine that never saw a constraint
        (the all-ones mask row passes logits through bitwise)."""
        cfg, params = _setup("control")
        vocab = _char_vocab()
        cprompt = _prompts([6], seed=3)[0]
        uprompts = _prompts([5, 9, 3], seed=4)

        alone = ServingEngine(params, cfg, _serving(), vocab=vocab)
        (c_alone,) = alone.generate([cprompt],
                                    params=[_constrained_params()])
        plain = ServingEngine(params, cfg, _serving())
        u_alone = plain.generate(
            uprompts,
            params=[SamplingParams(max_new_tokens=8, temperature=0.0,
                                   seed=7 + i)
                    for i in range(3)],
        )

        mixed = ServingEngine(params, cfg, _serving(), vocab=vocab)
        outs = mixed.generate(
            [cprompt] + uprompts,
            params=[_constrained_params()] + [
                SamplingParams(max_new_tokens=8, temperature=0.0,
                               seed=7 + i)
                for i in range(3)
            ],
        )
        assert outs[0].tokens == c_alone.tokens
        fsm = compile_constraint(
            spec_key(_constrained_params(), None), vocab
        )
        assert fsm.matches(c_alone.tokens)
        assert outs[0].finish_reason == "constraint_complete"
        for got, ref in zip(outs[1:], u_alone):
            assert got.tokens == ref.tokens

    def test_zero_recompiles_for_mixed_churn(self):
        """After one warm mixed pass, a different constraint, a
        different batch mix, and penalty/logprob variation compile
        NOTHING: per-request state rides runtime arrays."""
        from differential_transformer_replication_tpu.analysis.sanitizers import (  # noqa: E501
            RecompileSentinel,
        )

        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving(), vocab=_char_vocab())
        warm = _prompts([4, 7, 5, 9], seed=5)
        eng.generate(
            warm,
            params=[_constrained_params()] + [
                SamplingParams(max_new_tokens=6, temperature=0.0,
                               seed=i)
                for i in range(3)
            ],
        )
        with RecompileSentinel(budget=0, name="constrain-churn"):
            outs = eng.generate(
                _prompts([6, 3, 8, 5], seed=6),
                params=[
                    _constrained_params(regex="(ab|ba){2,5}c?"),
                    _constrained_params(
                        regex=None,
                        json_schema=json.dumps({
                            "type": "object",
                            "properties": {
                                "ok": {"type": "boolean"},
                            },
                        }),
                    ),
                    SamplingParams(max_new_tokens=6, temperature=0.0,
                                   seed=11, repetition_penalty=1.3,
                                   logprobs=2),
                    SamplingParams(max_new_tokens=6, temperature=0.0,
                                   seed=12),
                ],
            )
        assert outs[0].finish_reason == "constraint_complete"
        assert _text(outs[1].tokens) in ('{"ok":true}', '{"ok":false}')

    def test_penalties_presence_blocks_repeats(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving())
        (out,) = eng.generate(
            [_prompts([5], seed=9)[0]],
            params=[SamplingParams(max_new_tokens=10, temperature=0.0,
                                   seed=0, presence_penalty=1e4)],
        )
        # a huge presence penalty makes greedy spend each token once
        assert len(set(out.tokens)) == len(out.tokens)

    def test_stop_sequence_finishes_typed(self):
        cfg, params = _setup("control")
        prompt = _prompts([6], seed=10)[0]
        eng = ServingEngine(params, cfg, _serving())
        (ref,) = eng.generate(
            [prompt],
            params=[SamplingParams(max_new_tokens=8, temperature=0.0,
                                   seed=0)],
        )
        assert len(ref.tokens) == 8
        stop = (tuple(ref.tokens[2:4]),)
        (out,) = eng.generate(
            [prompt],
            params=[SamplingParams(max_new_tokens=8, temperature=0.0,
                                   seed=0, stop=stop)],
        )
        assert out.finish_reason == "stop_sequence"
        assert out.tokens == ref.tokens[:4]
        # the labeled finished counter saw it
        text = eng.registry.render()
        assert 'reason="stop_sequence"' in text

    def test_logprob_echo_greedy(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving())
        (out,) = eng.generate(
            [_prompts([5], seed=11)[0]],
            params=[SamplingParams(max_new_tokens=6, temperature=0.0,
                                   seed=0, logprobs=3)],
        )
        assert len(out.token_logprobs) == len(out.tokens)
        assert len(out.top_logprobs) == len(out.tokens)
        for tok, lp, top in zip(out.tokens, out.token_logprobs,
                                out.top_logprobs):
            assert lp <= 0.0
            assert 1 <= len(top) <= 3
            ids = [t for t, _ in top]
            lps = [v for _, v in top]
            # greedy chose the argmax: it leads the top-k echo
            assert ids[0] == tok
            assert abs(lps[0] - lp) < 1e-5
            assert lps == sorted(lps, reverse=True)

    def test_unconstrained_requests_carry_no_echo(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving())
        (out,) = eng.generate(
            [_prompts([5], seed=11)[0]],
            params=[SamplingParams(max_new_tokens=4, temperature=0.0,
                                   seed=0)],
        )
        assert out.token_logprobs is None
        assert out.top_logprobs is None

    def test_constrain_stats_and_gauges(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving(), vocab=_char_vocab())
        eng.generate(
            _prompts([4, 6], seed=12),
            params=[_constrained_params(seed=i) for i in range(2)],
        )
        st = eng.constrain_stats()
        assert st["entries"] == 1  # one compile, shared
        assert st["misses_total"] == 1 and st["hits_total"] == 1
        assert st["active"] == 0  # both released at retire
        text = eng.registry.render()
        for name in (
            "serving_constrained_requests_active",
            "serving_constraint_cache_entries",
            "serving_constraint_cache_bytes",
            "serving_constraint_cache_hits_total",
            "serving_constraint_cache_misses_total",
        ):
            assert name in text

    def test_constraint_without_vocab_fails_typed(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving())  # no vocab table
        with pytest.raises((ConstraintCompileError, ValueError)):
            eng.generate([_prompts([4])[0]],
                         params=[_constrained_params()])


# the tentpole's distribution pin: constrained+spec greedy output is
# bit-identical to constrained non-spec greedy for all three families
@pytest.mark.parametrize("kind", [
    "control",
    pytest.param("diff", marks=pytest.mark.slow),
    pytest.param("ndiff", marks=pytest.mark.slow),
])
def test_constrained_spec_greedy_bit_parity(kind):
    cfg, params = _setup(kind)
    vocab = _char_vocab()
    prompts = _prompts([6, 4, 9], seed=13)
    ps = [_constrained_params(seed=i) for i in range(3)]

    plain = ServingEngine(params, cfg, _serving(), vocab=vocab)
    refs = plain.generate(prompts, params=ps)
    spec = ServingEngine(
        params, cfg,
        _serving(spec_mode="ngram", spec_draft_len=4),
        vocab=vocab,
    )
    outs = spec.generate(prompts, params=ps)
    for got, ref in zip(outs, refs):
        assert got.tokens == ref.tokens
        assert got.finish_reason == ref.finish_reason
    fsm = compile_constraint(spec_key(ps[0], None), vocab)
    for o in refs:
        assert fsm.matches(o.tokens)


# ---------------------------------------------------------------------
# fault points
# ---------------------------------------------------------------------


class TestFaults:
    def setup_method(self):
        faults.reset()

    def teardown_method(self):
        faults.reset()

    def test_dead_end_retires_typed_with_partial_output(self):
        """constrain_dead_end@N poisons a constrained slot's FSM
        cursor: the request must retire as a typed retriable failure
        with its partial output — never hang, never emit through a
        zeroed mask — and the slot must be reusable immediately."""
        cfg, params = _setup("control")
        faults.arm("constrain_dead_end@0-50")
        client = ServingClient(
            ServingEngine(params, cfg, _serving(), vocab=_char_vocab())
        )
        try:
            with pytest.raises(ConstraintDeadEndError) as ei:
                client.generate(
                    _prompts([6], seed=14)[0],
                    params=_constrained_params(),
                    timeout=120,
                )
            out = ei.value.output
            assert out.finish_reason == "constraint_dead_end"
            assert isinstance(out.tokens, list)
            # the slot and its pages came back: the engine still serves
            ok = client.generate(
                _prompts([4], seed=15)[0],
                params=SamplingParams(max_new_tokens=4,
                                      temperature=0.0, seed=0),
                timeout=120,
            )
            assert ok.finish_reason == "length"
            st = client.runner.engine.constrain_stats()
            assert st["active"] == 0
        finally:
            client.close()

    def test_compile_fail_rejects_at_submit_engine_untouched(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _serving(), vocab=_char_vocab())
        faults.arm("constrain_compile_fail@1")
        with pytest.raises(ConstraintCompileError):
            eng.generate([_prompts([4])[0]],
                         params=[_constrained_params()])
        # the injected failure consumed the point; the SAME spec now
        # compiles and decodes — nothing engine-side was corrupted
        (out,) = eng.generate([_prompts([4])[0]],
                              params=[_constrained_params()])
        assert out.finish_reason == "constraint_complete"


# ---------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------


@pytest.mark.slow
def test_http_constrained_end_to_end():
    """POST /generate with a regex constraint; malformed schema ->
    400 constraint_compile_failed; injected dead end -> 400
    constraint_dead_end with partial_tokens; /metrics exports the
    constraint gauges."""
    faults.reset()
    cfg, params = _setup("control")
    client = ServingClient(
        ServingEngine(params, cfg, _serving(), vocab=_char_vocab())
    )
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def _post(payload):
        return urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )

    try:
        prompt = _prompts([6], seed=16)[0]
        with urllib.request.urlopen(
            _post({"prompt_ids": prompt, "max_new_tokens": 16,
                   "temperature": 0.0, "regex": REGEX,
                   "logprobs": 2}),
            timeout=120,
        ) as r:
            body = json.load(r)
        assert body["finish_reason"] == "constraint_complete"
        assert pyre.fullmatch(REGEX, _text(body["tokens"]))
        assert len(body["token_logprobs"]) == len(body["tokens"])
        assert all(len(row) <= 2 for row in body["top_logprobs"])

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                _post({"prompt_ids": prompt, "max_new_tokens": 4,
                       "json_schema": {"type": "array"}}),
                timeout=30,
            )
        assert ei.value.code == 400
        err = json.load(ei.value)
        assert err["code"] == "constraint_compile_failed"

        faults.arm("constrain_dead_end@0-50")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                _post({"prompt_ids": prompt, "max_new_tokens": 16,
                       "temperature": 0.0, "regex": REGEX}),
                timeout=120,
            )
        faults.reset()
        assert ei.value.code == 400
        err = json.load(ei.value)
        assert err["code"] == "constraint_dead_end"
        assert isinstance(err["partial_tokens"], list)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ) as r:
            text = r.read().decode()
        assert "serving_constraint_cache_entries" in text
        assert "serving_constrained_requests_active" in text

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert "constraints" in health
    finally:
        faults.reset()
        httpd.shutdown()
        httpd.server_close()
        client.close()


# ---------------------------------------------------------------------
# SamplingParams validation (satellite: the negative-top_k hole)
# ---------------------------------------------------------------------


class TestSamplingParamsValidation:
    def test_negative_top_k_rejected(self):
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(max_new_tokens=4, top_k=-3)

    def test_at_most_one_constraint(self):
        with pytest.raises(ValueError, match="at most one"):
            SamplingParams(max_new_tokens=4, regex="a+",
                           choices=("a",))

    def test_penalty_and_stop_validation(self):
        with pytest.raises(ValueError, match="repetition_penalty"):
            SamplingParams(max_new_tokens=4, repetition_penalty=0.0)
        p = SamplingParams(max_new_tokens=4, stop=[[1, 2], [3]])
        assert p.stop == ((1, 2), (3,))
        with pytest.raises(ValueError):
            SamplingParams(max_new_tokens=4, logprobs=-1)


# ---------------------------------------------------------------------
# GL301 mutation test: the cache's lock discipline is machine-checked
# ---------------------------------------------------------------------


class TestGL301CoversConstraintCache:
    """ConstraintCache is a lock-owning class shared between the
    engine thread and /health / /metrics readers; GL301 is the machine
    check that its counter/refcount writes stay under ``self._lock``.
    Planting exactly that bug — a counter write hoisted OUT of the
    lock in ``release`` — in the real module source MUST fire; the
    unmutated module must stay clean."""

    SPEC = (
        REPO / "differential_transformer_replication_tpu" / "serving"
        / "constrain.py"
    )
    ANCHOR = (
        "        with self._lock:\n"
        "            ent = self._entries.get(key)\n"
        "            if ent is not None and ent.refs > 0:\n"
        "                ent.refs -= 1"
    )

    def _copy(self, tmp_path, src):
        # keep the serving/ path component: GL301 is a serving-dir rule
        path = tmp_path / "serving" / "constrain.py"
        path.parent.mkdir(parents=True)
        path.write_text(src)
        return path

    def _lint(self, path, rules):
        sys.path.insert(0, str(REPO))
        from differential_transformer_replication_tpu.analysis.lint import (
            lint_paths,
        )

        return lint_paths([str(path)], rules=rules)

    def test_unmutated_cache_is_lock_clean(self, tmp_path):
        path = self._copy(tmp_path, self.SPEC.read_text())
        result = self._lint(path, ["GL301", "GL601", "GL602"])
        assert [f.rule for f in result.active] == []

    def test_planted_off_lock_counter_write_fires(self, tmp_path):
        src = self.SPEC.read_text()
        assert self.ANCHOR in src, (
            "mutation anchor vanished — ConstraintCache.release's lock "
            "block moved; update the anchor so this mutation test "
            "keeps guarding it"
        )
        mutated = src.replace(
            self.ANCHOR,
            "        self._misses += 1  # planted: off-lock write\n"
            + self.ANCHOR,
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == ["GL301"]
        (finding,) = result.active
        assert "_misses" in finding.message

    def test_planted_write_under_lock_stays_clean(self, tmp_path):
        src = self.SPEC.read_text()
        mutated = src.replace(
            self.ANCHOR,
            self.ANCHOR.replace(
                "                ent.refs -= 1",
                "                self._misses += 0  # under the lock\n"
                "                ent.refs -= 1",
            ),
        )
        path = self._copy(tmp_path, mutated)
        result = self._lint(path, ["GL301"])
        assert [f.rule for f in result.active] == []


# ---------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestTools:
    def test_serve_bench_constrained_smoke(self):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "serve_bench.py"),
             "--smoke", "--constrained", "regex"],
            capture_output=True, text=True, timeout=900,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stderr[-2000:]
        line = json.loads(r.stdout.strip().splitlines()[-1])
        assert line["schema_validity_rate"] == 1.0
        assert line["compiles_in_window"] == 0
        assert line["constraint_cache"]["hits_total"] >= 1

    def test_constrain_report_smoke_check(self):
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "constrain_report.py"),
             "--smoke", "--check", "--spec", "choices"],
            capture_output=True, text=True, timeout=900,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stderr[-2000:]
        line = json.loads(r.stdout.strip().splitlines()[-1])
        assert line["constrained_validity_diff"] == 1.0
        assert line["constrained_validity_control"] == 1.0
        assert "lambda_mean" in line
