"""Paged KV-cache subsystem (serving/pages.py + the paged engine path).

The load-bearing contracts:

- **Greedy parity**: the paged engine (fixed-size pages, per-slot page
  tables, radix prefix sharing, COW forks, ring rollover across page
  boundaries) produces exactly the tokens the contiguous engine — and
  sequential ``generate_cached`` — produce, for all three families,
  both decode-attention impls, and int8 KV storage.
- **Zero recompiles**: pages are allocated, freed, shared and forked
  between steps as runtime int32 arrays; the decode compile count
  stays pinned at 1 no matter how page tables churn.
- **Pool discipline**: admission keys on free pages (worst case
  reserved up front, so mid-decode allocation can never fail), shared
  nodes are refcounted, unreferenced prefixes LRU-evict, exhaustion is
  the typed retriable :class:`PagePoolExhaustedError`, and
  ``reset_after_crash`` rebuilds pool + radix tree from scratch (the
  poisoned-prefix eviction path).
"""

import json
import os
import subprocess
import sys
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    PagePool,
    PagePoolExhaustedError,
    ServingClient,
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.engine import (
    EngineCrashError,
)
from differential_transformer_replication_tpu.serving.pages import (
    page_bytes,
)
from differential_transformer_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind, **kw):
    base = dict(
        model=kind, vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


@lru_cache(maxsize=None)
def _setup(kind):
    cfg = _cfg(kind)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


def _paged(**kw):
    base = dict(num_slots=2, prefill_chunk=4, prefill_budget=6,
                kv_page_size=8)
    base.update(kw)
    return ServingConfig(**base)


# ---------------------------------------------------------------------------
# PagePool unit tests (pure host state, no device work)
# ---------------------------------------------------------------------------


class TestPagePool:
    def _pool(self, **kw):
        base = dict(page_size=4, pages_per_slot=4, num_slots=2,
                    total_pages=9, prefix_cache=True)
        base.update(kw)
        return PagePool(**base)

    def test_reservation_and_release(self):
        pool = self._pool()
        adm = pool.plan_admission(0, list(range(6)), 3)
        # min(6+3, 16) = 9 tokens -> 3 pages, nothing cached yet
        assert adm is not None and adm.cached_len == 0 and not adm.hit
        st = pool.stats()
        assert st["free"] == 8 - 3
        row = pool.table_row(0)
        assert (row[:3] > 0).all() and (row[3:] == PagePool.TRASH).all()
        # trash page never allocated
        assert PagePool.TRASH not in row[:3]
        pool.release(0, list(range(6)), cacheable=False)
        assert pool.stats()["free"] == 8

    def test_admission_waits_when_pages_short(self):
        pool = self._pool(total_pages=9)  # capacity 8
        assert pool.plan_admission(0, list(range(16)), 16) is not None
        # slot 0 reserved all 4 ring pages... 4 left; a second
        # max-length request needs 4 -> fits; a third must wait
        assert pool.plan_admission(1, list(range(16)), 16) is not None
        assert pool.plan_admission(0, list(range(16)), 16) is None

    def test_constructor_rejects_pool_below_one_request(self):
        with pytest.raises(ValueError):
            self._pool(total_pages=5)  # pages_per_slot + 2 = 6

    def test_force_exhaust_raises_once_typed(self):
        pool = self._pool()
        pool.force_exhaust()
        with pytest.raises(PagePoolExhaustedError) as ei:
            pool.plan_admission(0, [1, 2, 3], 2)
        assert getattr(ei.value, "retriable", None) is True
        assert pool.plan_admission(0, [1, 2, 3], 2) is not None

    def test_full_page_share_refcount_and_partial_fork(self):
        pool = self._pool()
        prompt = list(range(10))  # 2 full pages + 2-token tail
        adm = pool.plan_admission(0, prompt, 2)
        assert adm.cached_len == 0
        pool.release(0, prompt, cacheable=True)
        st = pool.stats()
        assert st["cached"] == 3  # 2 full nodes + the partial tail
        # identical prompt: shares both full pages, forks the tail
        # (cap at len-1 = 9 -> 2 full pages + 1 forked token)
        adm2 = pool.plan_admission(0, prompt, 2)
        assert adm2.hit and adm2.cached_len == 9
        assert len(adm2.copies) == 1
        assert pool.stats()["cow_forks_total"] == 1
        # shared nodes pinned: eviction cannot free them while held
        row = pool.table_row(0)
        cached_pages = set(pool.cached_pages())
        assert int(row[0]) in cached_pages
        assert int(row[1]) in cached_pages
        pool.release(0, prompt, cacheable=True)

    def test_divergent_prompt_forks_at_partial_boundary(self):
        pool = self._pool()
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        pool.plan_admission(0, a, 2)
        pool.release(0, a, cacheable=True)
        b = [1, 2, 3, 4, 5, 6, 9, 9]  # diverges mid page 2
        adm = pool.plan_admission(1, b, 2)
        assert adm.cached_len == 6  # page 1 shared + 2 forked tokens
        assert len(adm.copies) == 1
        pool.release(1, b, cacheable=True)

    def test_lru_eviction_frees_unreferenced_leaves(self):
        pool = self._pool(total_pages=9)
        # cache two distinct prompts (3 pages each incl. tails)
        for i, base in enumerate((10, 20)):
            p = [base + j for j in range(9)]
            pool.plan_admission(i, p, 1)
            pool.release(i, p, cacheable=True)
        st = pool.stats()
        assert st["cached"] == 6 and st["free"] == 2
        # a max-length admission must evict cached leaves to fit
        assert pool.plan_admission(0, list(range(40, 56)), 4) is not None
        st = pool.stats()
        assert st["evictions_total"] >= 2
        assert st["cached"] < 6

    def test_match_capped_below_full_prompt(self):
        # a fully-cached prompt still recomputes its last token (its
        # logits seed the first sample)
        pool = self._pool()
        p = list(range(8))  # exactly 2 pages
        pool.plan_admission(0, p, 2)
        pool.release(0, p, cacheable=True)
        adm = pool.plan_admission(1, p, 2)
        assert adm.cached_len == 7  # page 1 + 3 forked tokens

    def test_rolling_request_skips_sharing(self):
        pool = self._pool()
        p = list(range(8))
        pool.plan_admission(0, p, 2)
        pool.release(0, p, cacheable=True)
        # prompt + max_new > ring: reserves every page privately and
        # consults no cache (its pages get overwritten by rollover)
        adm = pool.plan_admission(1, p, 20)
        assert adm.cached_len == 0 and not adm.hit
        assert (pool.table_row(1) > 0).all()

    def test_reset_rebuilds_free_list_and_drops_cache(self):
        pool = self._pool()
        p = list(range(9))
        pool.plan_admission(0, p, 2)
        pool.release(0, p, cacheable=True)
        assert pool.stats()["cached"] > 0
        pool.reset()
        st = pool.stats()
        assert st["cached"] == 0 and st["free"] == 8
        # monotonic counters survive (prometheus semantics)
        assert st["misses_total"] == 1

    def test_page_bytes_int8_aware(self):
        cfg = _cfg("control")
        b_f32 = page_bytes(cfg, 8)
        b_int8 = page_bytes(cfg.replace(kv_cache_dtype="int8"), 8)
        assert b_int8 < b_f32  # int8 + scales still beat fp32/bf16


# ---------------------------------------------------------------------------
# Paged engine: greedy parity with the contiguous engine / generate_cached
# ---------------------------------------------------------------------------


def test_paged_greedy_bit_identical_to_generate_cached():
    """Acceptance pin (quick tier): mixed-length prompts through a
    2-slot paged pool — requests queue, slots and pages are reused —
    produce exactly the tokens sequential generate_cached produces."""
    cfg, params = _setup("control")
    prompts = _prompts([3, 9, 14, 6, 11], cfg.vocab_size)
    eng = ServingEngine(params, cfg, _paged())
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _ref_greedy(params, cfg, p, 8)
        assert o.finish_reason == "length"
    assert eng.stats["completed"] == 5
    assert eng.compile_stats()["decode"] == 1


@pytest.mark.slow
@pytest.mark.parametrize("kind,impl,kvd", [
    ("control", "pallas", ""),
    ("diff", "xla", ""),
    ("diff", "pallas", "int8"),
    ("ndiff", "pallas", ""),
    ("ndiff", "xla", "int8"),
    ("control", "pallas", "int8"),
])
def test_paged_matches_contiguous_all_families(kind, impl, kvd):
    """Paged-vs-contiguous greedy bit-parity across families, both
    decode-attention impls, and int8 KV (same serving overrides on both
    engines, so quantization error is identical on each side)."""
    cfg, params = _setup(kind)
    sv = _paged(decode_attention_impl=impl, kv_cache_dtype=kvd)
    prompts = _prompts([3, 9, 14, 6], cfg.vocab_size, seed=4)
    paged = ServingEngine(params, cfg, sv).generate(
        prompts, max_new_tokens=8, temperature=0.0
    )
    contiguous = ServingEngine(
        params, cfg, sv.replace(kv_page_size=0)
    ).generate(prompts, max_new_tokens=8, temperature=0.0)
    for a, b in zip(paged, contiguous):
        assert a.tokens == b.tokens


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_paged_ring_rollover_past_page_boundaries(impl):
    """RoPE families roll the ring past block_size: the write position
    wraps through every page of the table (rollover requests reserve
    all pages privately, so no shared page is ever overwritten) and
    greedy output still matches generate_cached."""
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg,
        _paged(max_seq_len=64, prefill_chunk=8, prefill_budget=16,
               decode_attention_impl=impl),
    )
    long_p, short_p = _prompts([28, 5], cfg.vocab_size, seed=2)
    outs = eng.generate([long_p, short_p], max_new_tokens=20,
                        temperature=0.0)
    assert outs[0].tokens == _ref_greedy(params, cfg, long_p, 20)
    assert outs[1].tokens == _ref_greedy(params, cfg, short_p, 20)


# ---------------------------------------------------------------------------
# Radix prefix sharing through the engine
# ---------------------------------------------------------------------------


def test_prefix_hit_skips_prefill_and_matches_greedy():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg,
                        _paged(prefill_chunk=8, prefill_budget=16))
    rng = np.random.default_rng(7)
    shared = rng.integers(0, cfg.vocab_size, size=16).tolist()
    p1 = shared + rng.integers(0, cfg.vocab_size, size=4).tolist()
    p2 = shared + rng.integers(0, cfg.vocab_size, size=5).tolist()
    out1 = eng.generate([p1], max_new_tokens=6, temperature=0.0)[0]
    prefill_after_first = eng.stats["prefill_tokens"]
    st1 = eng.page_stats()
    out2 = eng.generate([p2], max_new_tokens=6, temperature=0.0)[0]
    st2 = eng.page_stats()
    assert out1.tokens == _ref_greedy(params, cfg, p1, 6)
    assert out2.tokens == _ref_greedy(params, cfg, p2, 6)
    assert st2["hits_total"] == st1["hits_total"] + 1
    # the hit skipped the shared pages: only the un-cached suffix ran
    assert (eng.stats["prefill_tokens"] - prefill_after_first
            <= len(p2) - 16 + 8)


def test_cow_fork_mid_page_matches_greedy():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg,
                        _paged(prefill_chunk=8, prefill_budget=16))
    rng = np.random.default_rng(8)
    shared = rng.integers(0, cfg.vocab_size, size=12).tolist()
    p1 = shared + rng.integers(0, cfg.vocab_size, size=3).tolist()
    p2 = shared + rng.integers(0, cfg.vocab_size, size=6).tolist()
    eng.generate([p1], max_new_tokens=4, temperature=0.0)
    out = eng.generate([p2], max_new_tokens=4, temperature=0.0)[0]
    assert out.tokens == _ref_greedy(params, cfg, p2, 4)
    st = eng.page_stats()
    assert st["cow_forks_total"] >= 1 and st["hits_total"] >= 1


def test_prefix_cache_off_never_hits():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged(prefix_cache=False))
    p = _prompts([10], cfg.vocab_size)[0]
    eng.generate([p], max_new_tokens=4, temperature=0.0)
    eng.generate([p], max_new_tokens=4, temperature=0.0)
    st = eng.page_stats()
    assert st["hits_total"] == 0 and st["cached"] == 0


def test_decode_compile_pinned_under_page_churn():
    """The zero-recompile pin: page tables churn (admissions, shares,
    forks, retirements, evictions) while the decode closure stays at
    ONE compile-cache entry and the fork copy at <= 1."""
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg,
                        _paged(prefill_chunk=8, prefill_budget=16))
    rng = np.random.default_rng(9)
    shared = rng.integers(0, cfg.vocab_size, size=12).tolist()
    batches = [
        _prompts([3, 9], cfg.vocab_size, seed=10),
        [shared + [1], shared + [2, 3]],  # hit + fork traffic
        _prompts([14, 6, 11], cfg.vocab_size, seed=11),
    ]
    for prompts in batches:
        eng.generate(prompts, max_new_tokens=5, temperature=0.0)
    stats = eng.compile_stats()
    assert stats["decode"] == 1
    assert stats["page_copy"] <= 1
    assert eng.page_stats()["free"] == eng.page_stats()["total"] - \
        eng.page_stats()["cached"]


# ---------------------------------------------------------------------------
# Faults + crash recovery
# ---------------------------------------------------------------------------


def test_page_exhaust_fault_sheds_typed():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged())
    faults.arm(f"page_exhaust@{eng.stats['iterations']}")
    p = _prompts([6], cfg.vocab_size)[0]
    eng.submit(p, max_new_tokens=4, temperature=0.0)
    outs = eng.run()
    assert len(outs) == 1
    assert outs[0].finish_reason == "page_exhausted"
    assert outs[0].tokens == []
    assert eng.stats["page_shed"] == 1
    # the pool recovered: the next request admits and completes
    out = eng.generate([p], max_new_tokens=4, temperature=0.0)[0]
    assert out.tokens == _ref_greedy(params, cfg, p, 4)


def test_runner_delivers_page_exhausted_as_typed_error():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged())
    client = ServingClient(eng)
    try:
        faults.arm(f"page_exhaust@{eng.stats['iterations']}")
        p = _prompts([6], cfg.vocab_size)[0]
        with pytest.raises(PagePoolExhaustedError):
            client.generate(p, max_new_tokens=4, temperature=0.0,
                            timeout=30)
    finally:
        client.close()


def test_prefix_corrupt_fault_trips_guard_and_pool_rebuilds():
    """Poisoned cached prefix: the finite-logits guard raises the typed
    EngineCrashError (never garbage tokens); reset_after_crash rebuilds
    pool + radix tree, evicting the poison, and the same request then
    completes correctly on a fresh prefill."""
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg,
                        _paged(prefill_chunk=8, prefill_budget=16))
    rng = np.random.default_rng(12)
    shared = rng.integers(0, cfg.vocab_size, size=16).tolist()
    p1 = shared + [1, 2]
    eng.generate([p1], max_new_tokens=3, temperature=0.0)
    assert eng.page_stats()["cached"] > 0
    p2 = shared + [3, 4, 5]
    eng.submit(p2, max_new_tokens=3, temperature=0.0)
    faults.arm(f"prefix_corrupt@{eng.stats['iterations']}")
    with pytest.raises(EngineCrashError):
        while eng.has_work():
            eng.step()
    lost = eng.reset_after_crash()
    assert lost  # the in-flight hit was failed, typed
    st = eng.page_stats()
    assert st["cached"] == 0 and st["free"] == st["total"]
    out = eng.generate([p2], max_new_tokens=3, temperature=0.0)[0]
    assert out.tokens == _ref_greedy(params, cfg, p2, 3)


def test_reset_after_crash_preserves_queue_and_pool_capacity():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged())
    prompts = _prompts([5, 7, 6], cfg.vocab_size, seed=13)
    for p in prompts:
        eng.submit(p, max_new_tokens=4, temperature=0.0)
    faults.arm(f"serve_raise@{eng.stats['iterations'] + 1}")
    with pytest.raises(Exception):
        while eng.has_work():
            eng.step()
    eng.reset_after_crash()
    st = eng.page_stats()
    assert st["free"] == st["total"]
    outs = eng.run()
    assert {o.finish_reason for o in outs} == {"length"}
    for o in outs:
        assert o.tokens == _ref_greedy(params, cfg, o.prompt, 4)
    assert eng.compile_stats()["decode"] == 1  # restart adds no compiles


# ---------------------------------------------------------------------------
# Capacity: admission keys on free pages, not slots
# ---------------------------------------------------------------------------


def test_undersized_pool_paces_admission_and_completes_everything():
    """Pool sized at HALF the slots' worst case: more slots than pages
    can hold max-length requests, so admission paces on free pages —
    everything still completes, and concurrency is bounded by pages."""
    cfg, params = _setup("control")
    # pp = 4 per slot; 4 slots x 4 = 16 worst case; pool of 8
    eng = ServingEngine(
        params, cfg,
        _paged(num_slots=4, kv_pool_pages=8, prefix_cache=False),
    )
    prompts = _prompts([12, 14, 13, 12, 14, 13], cfg.vocab_size, seed=5)
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o.tokens == _ref_greedy(params, cfg, p, 8)
    # max-length requests need ceil(22/8)=3 pages -> at most 2 fit the
    # 8-page pool concurrently even with 4 slots free
    assert eng.scheduler.max_concurrent <= 2


def test_short_requests_pack_more_slots_at_equal_pages():
    """The capacity win: at the SAME pool size, short requests (1 page
    each) admit to every slot concurrently — capacity scales with
    actual context, not worst case."""
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg,
        _paged(num_slots=4, kv_pool_pages=8, prefill_chunk=8,
               prefill_budget=32, prefix_cache=False),
    )
    prompts = _prompts([4, 4, 4, 4], cfg.vocab_size, seed=6)
    outs = eng.generate(prompts, max_new_tokens=3, temperature=0.0)
    assert len(outs) == 4
    assert eng.scheduler.max_concurrent == 4


def test_gauges_and_health_surface_page_stats():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged())
    p = _prompts([10], cfg.vocab_size)[0]
    eng.generate([p], max_new_tokens=3, temperature=0.0)
    text = eng.registry.render()
    for name in (
        "serving_kv_pages_total", "serving_kv_pages_free",
        "serving_kv_pages_cached", "serving_kv_pages_cow_forks_total",
        "serving_prefix_cache_hits_total",
        "serving_prefix_cache_misses_total",
        "serving_prefix_cache_evictions_total",
        "serving_kv_page_bytes",
    ):
        assert name in text, name
    st = eng.page_stats()
    assert st["total"] == 8 and st["page_size"] == 8


def test_never_fitting_request_rejected_at_submit():
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _paged())
    # force capacity below a max-length request by hand: the config
    # floor normally prevents this, so drive the pool directly
    eng._pages.capacity = 2
    with pytest.raises(PagePoolExhaustedError) as ei:
        eng.submit(_prompts([20], cfg.vocab_size)[0], max_new_tokens=8)
    assert ei.value.retriable is False
    assert eng.stats["rejected"] == 1


# ---------------------------------------------------------------------------
# serve_bench --shared-prefix (the acceptance workload)
# ---------------------------------------------------------------------------


def test_serve_bench_shared_prefix_smoke():
    """Acceptance pin: the --shared-prefix N:M smoke bench reports TTFT
    split by cache-hit/miss, a full hit rate, and ZERO compiles inside
    the measured window (page churn + COW forks never retrace)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "serve_bench.py"),
         "--smoke", "--shared-prefix", "4:16"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "serving_output_tokens_per_sec"
    assert line["shared_prefix"] == {"sessions": 4, "prefix_len": 16}
    assert line["prefix_cache_hit_rate"] == 1.0
    assert line["compiles_in_window"] == 0
    assert line["ttft_ms_hit"]["p50"] is not None
    assert line["ttft_ms_miss"]["p50"] is not None
    assert line["kv_pages"]["hits_total"] >= 4
