"""Tier-1 gate: the package lints clean under graftlint.

This is the CI teeth of the analysis/ subsystem — from this PR on, a
stray host sync in a jit region, an unguarded shared attribute in
serving/, or a missing donate_argnums on a step entry point fails the
quick tier (CPU-only, no jax import in the linter, sub-second), instead
of surfacing as a mysterious perf regression three PRs later.

Runs the CLI as a subprocess — exactly the documented invocation
(``python tools/graftlint.py differential_transformer_replication_tpu/``)
— so the gate also covers the wrapper and the --json plumbing."""

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "differential_transformer_replication_tpu"
GRAFTLINT = REPO / "tools" / "graftlint.py"


def _lint_json():
    r = subprocess.run(
        [sys.executable, str(GRAFTLINT), "--json", str(PKG)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    return r, (json.loads(r.stdout) if r.stdout else None)


def test_package_lints_clean():
    t0 = time.monotonic()
    r, doc = _lint_json()
    elapsed = time.monotonic() - t0
    # the full-tree gate must stay cheap enough for pre-commit: the
    # PR-11 interprocedural passes run in ~2s here; 15s is the ceiling
    # before the gate stops being run reflexively
    assert elapsed < 15.0, f"full-tree lint took {elapsed:.1f}s"
    assert doc is not None, f"no JSON output (stderr: {r.stderr})"
    active = [f for f in doc["findings"] if not f["suppressed"]]
    assert r.returncode == 0 and not active, (
        "graftlint found unsuppressed hazards (fix them or annotate the "
        "deliberate ones — see ANALYSIS.md):\n"
        + "\n".join(
            f"  {f['path']}:{f['line']}: {f['rule']} {f['message']}"
            for f in active
        )
        + f"\nparse errors: {doc['parse_errors']}"
    )
    assert doc["parse_errors"] == []


def test_engine_actually_analyzed_the_tree():
    """Guards the gate against vacuous passes: a regression that stops
    jit-region discovery (or file walking) would make every rule
    silently inapplicable while still exiting 0."""
    _, doc = _lint_json()
    assert doc["files_scanned"] >= 60, doc["files_scanned"]
    # train/step.py + engine closures + models stack + the PR-11
    # interprocedural expansion (Pallas kernels, shard_map bodies
    # through the compat wrapper, defvjp pairs) exceed this by a lot;
    # the floor pins that the expansion never silently regresses
    assert doc["jit_regions"] >= 200, doc["jit_regions"]
    # GL1xx-GL6xx: 10 original + 9 sharding/pallas/concurrency rules
    assert len(doc["rules"]) >= 13
    # the tree's deliberate exceptions stay visible as suppressed
    # findings — if this drops to zero the suppression plumbing broke
    # (or someone deleted the annotations wholesale; either needs eyes)
    assert doc["summary"]["suppressed"] >= 1


def test_fleet_tool_lints_clean():
    """GL6xx's second motivating surface (ISSUE: serving/ AND
    tools/fleet.py): the fleet supervisor's lock discipline is gated
    alongside the package."""
    r = subprocess.run(
        [sys.executable, str(GRAFTLINT), "--json",
         str(REPO / "tools" / "fleet.py")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    doc = json.loads(r.stdout)
    active = [f for f in doc["findings"] if not f["suppressed"]]
    assert r.returncode == 0 and not active, active


def test_new_rule_families_fire_on_fixtures():
    """Anti-vacuity for the PR-11 families: the committed fixture files
    under tests/test_analysis/fixtures/ carry one planted hazard per
    rule — a pass that stops firing there is dead, and the clean-tree
    gate above would be meaningless."""
    r = subprocess.run(
        [sys.executable, str(GRAFTLINT), "--json",
         str(REPO / "tests" / "test_analysis" / "fixtures")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    doc = json.loads(r.stdout)
    assert r.returncode == 1, "planted fixtures must fail the gate"
    active_rules = {
        f["rule"] for f in doc["findings"] if not f["suppressed"]
    }
    for rule in ("GL401", "GL402", "GL403", "GL501", "GL502", "GL503",
                 "GL504", "GL601", "GL602"):
        assert rule in active_rules, f"{rule} did not fire on its fixture"
    # every family also demonstrates auditable suppression plumbing
    assert any(f["suppressed"] for f in doc["findings"])
    # and the warn-level rule stays warn-level
    sev = {f["rule"]: f["severity"] for f in doc["findings"]}
    assert sev["GL503"] == "warning"


def test_lint_is_fast_enough_for_tier1():
    """The gate must stay cheap: stdlib-only, no jax import. A
    graftlint that starts importing jax would cost seconds per run and
    eventually a TPU lock — keep it static."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; "
         "import differential_transformer_replication_tpu.analysis.cli; "
         "sys.exit(1 if 'jax' in sys.modules else 0)"],
        cwd=str(REPO),
    )
    assert r.returncode == 0, "analysis CLI must not import jax"
