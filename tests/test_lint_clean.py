"""Tier-1 gate: the package lints clean under graftlint.

This is the CI teeth of the analysis/ subsystem — from this PR on, a
stray host sync in a jit region, an unguarded shared attribute in
serving/, or a missing donate_argnums on a step entry point fails the
quick tier (CPU-only, no jax import in the linter, sub-second), instead
of surfacing as a mysterious perf regression three PRs later.

Runs the CLI as a subprocess — exactly the documented invocation
(``python tools/graftlint.py differential_transformer_replication_tpu/``)
— so the gate also covers the wrapper and the --json plumbing."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "differential_transformer_replication_tpu"
GRAFTLINT = REPO / "tools" / "graftlint.py"


def _lint_json():
    r = subprocess.run(
        [sys.executable, str(GRAFTLINT), "--json", str(PKG)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    return r, (json.loads(r.stdout) if r.stdout else None)


def test_package_lints_clean():
    r, doc = _lint_json()
    assert doc is not None, f"no JSON output (stderr: {r.stderr})"
    active = [f for f in doc["findings"] if not f["suppressed"]]
    assert r.returncode == 0 and not active, (
        "graftlint found unsuppressed hazards (fix them or annotate the "
        "deliberate ones — see ANALYSIS.md):\n"
        + "\n".join(
            f"  {f['path']}:{f['line']}: {f['rule']} {f['message']}"
            for f in active
        )
        + f"\nparse errors: {doc['parse_errors']}"
    )
    assert doc["parse_errors"] == []


def test_engine_actually_analyzed_the_tree():
    """Guards the gate against vacuous passes: a regression that stops
    jit-region discovery (or file walking) would make every rule
    silently inapplicable while still exiting 0."""
    _, doc = _lint_json()
    assert doc["files_scanned"] >= 60, doc["files_scanned"]
    # train/step.py + engine closures + models stack alone exceed this
    assert doc["jit_regions"] >= 50, doc["jit_regions"]
    assert len(doc["rules"]) >= 8
    # the tree's deliberate exceptions stay visible as suppressed
    # findings — if this drops to zero the suppression plumbing broke
    # (or someone deleted the annotations wholesale; either needs eyes)
    assert doc["summary"]["suppressed"] >= 1


def test_lint_is_fast_enough_for_tier1():
    """The gate must stay cheap: stdlib-only, no jax import. A
    graftlint that starts importing jax would cost seconds per run and
    eventually a TPU lock — keep it static."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; "
         "import differential_transformer_replication_tpu.analysis.cli; "
         "sys.exit(1 if 'jax' in sys.modules else 0)"],
        cwd=str(REPO),
    )
    assert r.returncode == 0, "analysis CLI must not import jax"
