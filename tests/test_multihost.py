"""Multi-host runtime helpers, exercised in the single-process regime the
CI environment provides (process semantics beyond one host are covered by
jax.distributed itself; our logic is the wrapping arithmetic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import MeshConfig
from differential_transformer_replication_tpu.parallel import create_mesh
from differential_transformer_replication_tpu.parallel.multihost import (
    global_batch,
    initialize,
    is_primary,
    local_batch_slice,
    process_count,
)


def test_initialize_singleprocess_noop():
    initialize()  # must not raise or try to reach a coordinator
    assert process_count() == 1
    assert is_primary()


def test_local_batch_slice():
    start, size = local_batch_slice(32)
    assert (start, size) == (0, 32)  # single process owns everything


def test_global_batch_assembles_sharded_arrays():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=1, sequence=2))
    local = {
        "x": np.arange(2 * 4 * 16, dtype=np.int32).reshape(2, 4, 16),
        "y": np.ones((2, 4, 16), np.int32),
    }
    g = global_batch(local, mesh)
    assert g["x"].shape == (2, 4, 16)
    # round-trips the data and carries the training batch sharding
    np.testing.assert_array_equal(np.asarray(g["x"]), local["x"])
    assert g["x"].sharding.spec == jax.sharding.PartitionSpec(
        None, ("data", "fsdp"), "sequence"
    )
    # usable directly in a sharded computation
    s = jax.jit(lambda b: jnp.sum(b["x"]))(g)
    assert int(s) == int(local["x"].sum())
