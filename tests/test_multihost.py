"""Multi-host runtime helpers, exercised in the single-process regime the
CI environment provides (process semantics beyond one host are covered by
jax.distributed itself; our logic is the wrapping arithmetic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import MeshConfig
from differential_transformer_replication_tpu.parallel import create_mesh
from differential_transformer_replication_tpu.parallel.multihost import (
    global_batch,
    initialize,
    is_primary,
    local_batch_slice,
    process_count,
)


def test_initialize_singleprocess_noop():
    initialize()  # must not raise or try to reach a coordinator
    assert process_count() == 1
    assert is_primary()


def test_local_batch_slice():
    start, size = local_batch_slice(32)
    assert (start, size) == (0, 32)  # single process owns everything


def test_faked_per_host_slices_reassemble_to_single_host_draw():
    """The trainer's multi-host data path (train/trainer.py:_materialize):
    every host computes the SAME seeded offsets, slices its own batch
    columns, and gathers host-side. Fake 4 hosts, reassemble their
    host_batches, and assert equality with the single-host device draw —
    the epoch permutation makes this exactly checkable (VERDICT r1
    item 3)."""
    from differential_transformer_replication_tpu.data import TokenWindows
    from differential_transformer_replication_tpu.data.native import (
        EpochPermutation,
    )

    tokens = np.arange(512, dtype=np.int32) % 97
    ds = TokenWindows(tokens, block_size=16)
    A, B, n_hosts = 2, 8, 4
    perm = EpochPermutation(len(ds), seed=7)
    offs = perm.take(A * B).reshape(A, B)

    single = ds.batches(offs)

    per = B // n_hosts
    parts = [
        ds.host_batches(offs[:, h * per : (h + 1) * per]) for h in range(n_hosts)
    ]
    for key in ("x", "y"):
        reassembled = np.concatenate([p[key] for p in parts], axis=1)
        np.testing.assert_array_equal(reassembled, np.asarray(single[key]))


def test_host_batches_matches_device_batches():
    from differential_transformer_replication_tpu.data import TokenWindows

    tokens = (np.arange(300, dtype=np.int32) * 31) % 113
    ds = TokenWindows(tokens, block_size=8)
    offs = np.array([[0, 5, 17], [33, 2, 100]])
    dev = ds.batches(offs)
    host = ds.host_batches(offs)
    for key in ("x", "y"):
        np.testing.assert_array_equal(host[key], np.asarray(dev[key]))


def test_global_batch_assembles_sharded_arrays():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=1, sequence=2))
    local = {
        "x": np.arange(2 * 4 * 16, dtype=np.int32).reshape(2, 4, 16),
        "y": np.ones((2, 4, 16), np.int32),
    }
    g = global_batch(local, mesh)
    assert g["x"].shape == (2, 4, 16)
    # round-trips the data and carries the training batch sharding
    np.testing.assert_array_equal(np.asarray(g["x"]), local["x"])
    assert g["x"].sharding.spec == jax.sharding.PartitionSpec(
        None, ("data", "fsdp"), "sequence"
    )
    # usable directly in a sharded computation
    s = jax.jit(lambda b: jnp.sum(b["x"]))(g)
    assert int(s) == int(local["x"].sum())
