"""Fault-tolerance tests: the anomaly guard (skip / rollback / abort),
the fault-injection harness, checkpoint-corruption errors, and the crash
supervisor.

Tiering: the single-step and single-run tests here are quick (tier-1);
the kill-and-resume chaos tests spawn real ``train.py`` subprocesses and
are marked ``slow``. The compile-count pin
(test_guard_adds_no_recompiles) is the acceptance check that the
``lax.cond`` guard costs zero steady-state recompiles.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.train import (
    CheckpointError,
    TrainingDivergedError,
    create_train_state,
    load_checkpoint,
    make_train_step,
    save_checkpoint,
    train,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
SUPERVISOR = os.path.join(TOOLS, "train_supervisor.py")
TRAIN_PY = os.path.join(os.path.dirname(__file__), "..", "train.py")

TINY_MODEL = dict(vocab_size=256, n_embd=32, n_head=2, n_layer=2,
                  block_size=16, dropout=0.0, compute_dtype="float32")


@pytest.fixture(autouse=True)
def _clean_faults():
    """The fault plan is process-global; never leak between tests."""
    faults.reset()
    yield
    faults.reset()


def tiny_cfg(tmp_path, **kw):
    defaults = dict(
        vocab_size=256,
        dataset="synthetic",
        num_train_samples=200,
        micro_batch_size=4,
        grad_acc_steps=1,
        max_iters=20,
        eval_interval=10,
        eval_iters=2,
        log_interval=5,
        learning_rate=3e-3,
        min_lr=3e-4,
        warmup_iters=5,
        control_head_multiplier=1,
        tokenizer_dir=str(tmp_path / "tokenizer"),
        checkpoint_path=str(tmp_path / "ckpt"),
        last_checkpoint_path=str(tmp_path / "last_ckpt"),
        metrics_path=str(tmp_path / "metrics.jsonl"),
        seed=7,
        # tight guard knobs so tiny runs exercise every path
        anomaly_check_interval=1,
        anomaly_snapshot_interval=5,
        anomaly_rollback_after=3,
        anomaly_max_rollbacks=2,
    )
    model_kw = kw.pop("model_kw", {})
    return TrainConfig(
        model=ModelConfig(model=kw.pop("model", "diff"),
                          **{**TINY_MODEL, **model_kw}),
        **{**defaults, **kw},
    )


def step_cfg(**kw):
    return TrainConfig(
        model=ModelConfig(model="control", **{**TINY_MODEL, "vocab_size": 31}),
        vocab_size=31, learning_rate=1e-2, warmup_iters=2, max_iters=100,
        control_head_multiplier=1, **kw,
    )


def _params_finite(state) -> bool:
    return all(
        bool(jnp.isfinite(leaf).all())
        for leaf in jax.tree_util.tree_leaves(state["params"])
    )


def _batch(cfg, key=1, poison=None):
    x = jax.random.randint(jax.random.PRNGKey(key), (1, 4, 16), 0,
                           cfg.vocab_size)
    b = {"x": x, "y": jnp.roll(x, -1, -1)}
    if poison is not None:
        b["poison"] = np.full((1,), poison, np.float32)
    return b


class TestFaultSpec:
    def test_parse_kinds_and_ranges(self):
        faults.arm("raise@3,nan@5-7,sigterm@9,ckpt_write@2")
        assert faults.armed()
        assert faults.nan_armed()
        assert faults.poison_at(5) and faults.poison_at(7)
        assert not faults.poison_at(8)
        # raise is one-shot: fires once, then the same step is clean
        with pytest.raises(faults.FaultInjected):
            faults.fire(3)
        faults.fire(3)  # disarmed

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.arm("meteor@4")

    def test_resilience_kinds_parse(self):
        """The distributed-resilience points (train/watchdog.py +
        parallel/heartbeat.py chaos seams) ride the same spec grammar."""
        faults.arm("train_hang@16,collective_skew@3-4,heartbeat_silence@1")
        assert faults.armed()
        assert faults.heartbeat_silenced(1)
        assert not faults.heartbeat_silenced(0)
        # heartbeat_silence is deliberately NOT one-shot
        assert faults.heartbeat_silenced(1)

    def test_inert_when_unarmed(self):
        assert not faults.armed()
        faults.fire(0)
        faults.check("ckpt_write")  # no-op

    def test_ckpt_write_counts_calls(self):
        faults.arm("ckpt_write@2")
        faults.check("ckpt_write")  # 1st call survives
        with pytest.raises(faults.FaultInjected):
            faults.check("ckpt_write")  # 2nd fires
        faults.check("ckpt_write")  # disarmed


class TestAnomalyGuard:
    def test_nan_batch_skipped_and_params_protected(self):
        """The tentpole contract: one NaN batch is SKIPPED — params and
        optimizer state untouched, the skip counter increments, the
        streak resets on the next good batch, and the step counter still
        advances (lr schedule / sampler fast-forward stay exact)."""
        cfg = step_cfg()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        for i in range(5):
            poison = np.nan if i == 2 else 1.0
            state, m = step(state, _batch(cfg, poison=poison))
            if i == 2:
                assert int(m["bad"]) == 1
                assert not np.isfinite(float(m["loss"]))
            else:
                assert int(m["bad"]) == 0
                assert np.isfinite(float(m["loss"]))
        assert _params_finite(state)
        assert int(m["skipped"]) == 1
        assert int(m["bad_streak"]) == 0
        assert int(state["step"]) == 5  # skipped steps still count

    def test_unguarded_step_is_poisoned(self):
        """The contrast run: without the guard the same NaN batch
        corrupts the params permanently."""
        cfg = step_cfg(anomaly_guard=False)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        assert "guard" not in state
        step = make_train_step(cfg)
        state, _ = step(state, _batch(cfg, poison=np.nan))
        assert not _params_finite(state)

    def test_grad_spike_skipped_after_warmup(self):
        """A finite but exploding gradient (norm >> spike_factor x EMA)
        is skipped once the EMA has warmed up."""
        cfg = step_cfg(anomaly_warmup_steps=3, anomaly_spike_factor=4.0)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        for _ in range(4):  # warm the EMA past warmup_steps good steps
            state, m = step(state, _batch(cfg, poison=1.0))
        before = jax.device_get(state["params"])
        # x1e4 loss scale: grad norm stays FINITE (no overflow — this
        # must exercise the spike leg, not the non-finite leg) but far
        # beyond spike_factor x EMA
        state, m = step(state, _batch(cfg, poison=1e4))
        assert int(m["bad"]) == 1 and np.isfinite(float(m["grad_norm"]))
        after = jax.device_get(state["params"])
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)):
            np.testing.assert_array_equal(a, b)
        # and a normal batch afterwards trains again
        state, m = step(state, _batch(cfg, poison=1.0))
        assert int(m["bad"]) == 0 and int(m["bad_streak"]) == 0

    def test_guard_adds_no_recompiles(self):
        """Acceptance pin: the guarded step compiles exactly once across
        good AND bad batches — same count as the unguarded baseline.
        lax.cond keeps both branches in one program."""
        counts = {}
        for guard in (True, False):
            cfg = step_cfg(anomaly_guard=guard)
            state = create_train_state(jax.random.PRNGKey(0), cfg)
            step = make_train_step(cfg)
            for i in range(6):
                poison = np.nan if (guard and i == 3) else 1.0
                state, _ = step(state, _batch(cfg, key=i, poison=poison))
            counts[guard] = step._cache_size()
        assert counts[True] == counts[False] == 1

    def test_guard_state_not_checkpointed(self, tmp_path):
        """Checkpoints are guard-agnostic: a guarded state saves to the
        same on-disk format and loads into guarded AND unguarded
        targets (and vice versa)."""
        cfg = step_cfg()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        state, m = step(state, _batch(cfg, poison=np.nan))  # skipped=1
        save_checkpoint(str(tmp_path / "c"), state, 1.0, cfg)
        # guarded target: fresh guard re-seeded, not the saved counters
        restored, _ = load_checkpoint(
            str(tmp_path / "c"), cfg,
            create_train_state(jax.random.PRNGKey(1), cfg),
        )
        assert int(restored["guard"]["skipped"]) == 0
        assert int(restored["step"]) == 1
        # unguarded target loads the same file
        cfg_off = cfg.replace(anomaly_guard=False)
        restored2, _ = load_checkpoint(
            str(tmp_path / "c"), cfg_off,
            create_train_state(jax.random.PRNGKey(1), cfg_off),
        )
        assert "guard" not in restored2


class TestTrainerRecovery:
    def test_nan_steps_skipped_end_to_end(self, tmp_path, capsys):
        """A two-batch NaN burst mid-run: the run completes, the skip
        count lands in the metrics, the loss keeps decreasing, and the
        checkpoints contain only finite values."""
        cfg = tiny_cfg(tmp_path, faults="nan@6-7")
        state = train(cfg)
        assert int(state["step"]) == 20
        assert _params_finite(state)
        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        step_lines = [l for l in lines if "skipped_steps" in l]
        assert step_lines[-1]["skipped_steps"] == 2
        assert step_lines[-1]["rollbacks"] == 0
        assert np.isfinite(step_lines[-1]["loss"])
        # checkpoints never contain non-finite values
        target = create_train_state(jax.random.PRNGKey(0), cfg)
        restored, _ = load_checkpoint(cfg.checkpoint_path, cfg, target)
        assert _params_finite(restored)

    def test_rollback_recovers_from_corrupt_params(self, tmp_path, capsys):
        """State corruption (NaN'd param leaf) that skipping cannot cure:
        after rollback_after consecutive bad steps the trainer restores
        the in-HBM snapshot and the run completes with finite params."""
        cfg = tiny_cfg(tmp_path, faults="corrupt_params@12")
        state = train(cfg)
        out = capsys.readouterr().out
        assert "rolling back to iter 10" in out
        assert int(state["step"]) == 20
        assert _params_finite(state)
        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        assert [l for l in lines if l.get("rollbacks") == 1]

    def test_abort_after_rollback_budget_preserves_checkpoint(
        self, tmp_path, capsys
    ):
        """Persistent badness: rollbacks replay into the same poison, the
        budget exhausts, the run raises TrainingDivergedError, and the
        finite-check rescue save leaves the previous good rescue
        checkpoint byte-identical."""
        clean = tiny_cfg(tmp_path, max_iters=6, eval_interval=5)
        train(clean)  # writes a good last-checkpoint to protect
        good = open(
            os.path.join(clean.last_checkpoint_path, "state.msgpack"), "rb"
        ).read()

        faults.reset()
        cfg = tiny_cfg(
            tmp_path, faults="nan@0-999", anomaly_max_rollbacks=1,
            metrics_path=str(tmp_path / "m2.jsonl"),
        )
        with pytest.raises(TrainingDivergedError, match="did not recover"):
            train(cfg)
        out = capsys.readouterr().out
        assert "skipping last-checkpoint rescue save" in out
        now = open(
            os.path.join(cfg.last_checkpoint_path, "state.msgpack"), "rb"
        ).read()
        assert now == good

    def test_injected_sigterm_takes_graceful_stop_path(self, tmp_path, capsys):
        """sigterm@K rides the real signal handler: the run stops early,
        writes the rescue checkpoint, and a resume completes it."""
        cfg = tiny_cfg(tmp_path, faults="sigterm@7")
        state = train(cfg)
        stopped = int(state["step"])
        assert stopped < 20
        assert "SIGTERM received" in capsys.readouterr().out
        assert os.path.isfile(
            os.path.join(cfg.last_checkpoint_path, "state.msgpack")
        )
        faults.reset()
        cfg2 = tiny_cfg(tmp_path, resume_from=cfg.last_checkpoint_path)
        assert int(train(cfg2)["step"]) == 20

    def test_injected_crash_skips_nothing_good(self, tmp_path):
        """raise@K: the crash escapes train() (the supervisor's restart
        trigger) AFTER the rescue save ran, so the crash point is
        resumable."""
        cfg = tiny_cfg(tmp_path, faults="raise@9")
        with pytest.raises(faults.FaultInjected, match="iteration 9"):
            train(cfg)
        target = create_train_state(jax.random.PRNGKey(0), cfg)
        restored, _ = load_checkpoint(cfg.last_checkpoint_path, cfg, target)
        assert int(restored["step"]) == 9


class TestCheckpointCorruption:
    def _good_ckpt(self, tmp_path):
        cfg = step_cfg()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state, 1.0, cfg)
        return cfg, state, path

    def test_truncated_state_raises_one_clear_error(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        sp = os.path.join(path, "state.msgpack")
        data = open(sp, "rb").read()
        open(sp, "wb").write(data[: len(data) // 3])
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        with pytest.raises(CheckpointError, match="state.msgpack"):
            load_checkpoint(path, cfg, target)

    def test_garbage_meta_raises_one_clear_error(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        open(os.path.join(path, "meta.json"), "w").write("{not json")
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        with pytest.raises(CheckpointError, match="meta.json"):
            load_checkpoint(path, cfg, target)

    def test_load_params_for_inference_corrupt_meta(self, tmp_path):
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference,
        )

        cfg, state, path = self._good_ckpt(tmp_path)
        open(os.path.join(path, "meta.json"), "w").write('{"config": {}}')
        with pytest.raises(CheckpointError, match="meta.json"):
            load_params_for_inference(path)
        open(os.path.join(path, "meta.json"), "w").write("\x00\x01garbage")
        with pytest.raises(CheckpointError, match="meta.json"):
            load_params_for_inference(path)

    def test_missing_meta_raises_clear_error(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        os.unlink(os.path.join(path, "meta.json"))
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        with pytest.raises(CheckpointError, match="meta.json"):
            load_checkpoint(path, cfg, target)

    def test_failed_write_leaves_previous_checkpoint_intact(self, tmp_path):
        """_atomic_write's whole point, failure-injected at the worst
        moment (temp written, rename pending): the previous checkpoint
        survives byte-for-byte and no temp litter remains."""
        cfg, state, path = self._good_ckpt(tmp_path)
        before = {
            f: open(os.path.join(path, f), "rb").read()
            for f in ("state.msgpack", "meta.json")
        }
        faults.arm("ckpt_write")
        with pytest.raises(faults.FaultInjected):
            save_checkpoint(path, state, 2.0, cfg)
        for f, data in before.items():
            assert open(os.path.join(path, f), "rb").read() == data
        assert not [f for f in os.listdir(path) if f.endswith(".tmp")]
        # and the next (un-injected) save succeeds
        save_checkpoint(path, state, 2.0, cfg)
        meta = json.load(open(os.path.join(path, "meta.json")))
        assert meta["best_val_loss"] == 2.0


def _load_supervisor_module():
    spec = importlib.util.spec_from_file_location("train_supervisor", SUPERVISOR)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSupervisorUnit:
    def test_classify_exit(self):
        sup = _load_supervisor_module()
        assert sup.classify_exit(0) == "clean"
        assert sup.classify_exit(-signal.SIGTERM) == "sigterm"
        assert sup.classify_exit(143) == "sigterm"
        assert sup.classify_exit(-signal.SIGKILL) == "sigkill"
        assert sup.classify_exit(1) == "crash"
        assert sup.classify_exit(-11) == "crash"  # segfault

    def test_with_resume_replaces_existing_flag(self):
        sup = _load_supervisor_module()
        cmd = ["python", "train.py", "--resume-from", "old", "--seed", "1"]
        out = sup.with_resume(cmd, "new")
        assert out == ["python", "train.py", "--seed", "1",
                       "--resume-from", "new"]
        out2 = sup.with_resume(["t", "--resume-from=old"], "new")
        assert out2 == ["t", "--resume-from", "new"]

    def test_strip_flag_both_forms(self):
        """--faults must not survive into relaunches (it would re-fire
        the same kill every restart); both argv forms are stripped."""
        sup = _load_supervisor_module()
        assert sup._strip_flag(
            ["t", "--faults", "sigkill@9", "--seed", "1"], "--faults"
        ) == ["t", "--seed", "1"]
        assert sup._strip_flag(["t", "--faults=raise@2"], "--faults") == ["t"]

    def test_backoff_is_exponential_and_capped(self):
        sup = _load_supervisor_module()
        assert sup.backoff_s(0, 2.0, 120.0) == 2.0
        assert sup.backoff_s(3, 2.0, 120.0) == 16.0
        assert sup.backoff_s(10, 2.0, 120.0) == 120.0


def _run_supervisor(tmp_path, child_args, *sup_args, timeout=60):
    log = tmp_path / "restarts.json"
    proc = subprocess.run(
        [sys.executable, SUPERVISOR, "--backoff-base", "0.01",
         "--restart-log", str(log), *sup_args, "--", *child_args],
        capture_output=True, text=True, timeout=timeout,
    )
    records = (
        [json.loads(l) for l in open(log)] if log.exists() else []
    )
    return proc, records


class TestSupervisorProcess:
    """Supervisor behavior against cheap non-jax children (quick)."""

    def test_restarts_until_clean_exit(self, tmp_path):
        """Child crashes twice, then succeeds: three launches, rc 0,
        outcomes logged in order."""
        script = tmp_path / "flaky.py"
        script.write_text(
            "import os, sys\n"
            f"p = {str(tmp_path / 'count')!r}\n"
            "n = int(open(p).read()) if os.path.exists(p) else 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            "sys.exit(0 if n >= 2 else 3)\n"
        )
        proc, records = _run_supervisor(
            tmp_path, [sys.executable, str(script)], "--max-restarts", "5"
        )
        assert proc.returncode == 0, proc.stderr
        assert [r["outcome"] for r in records] == ["crash", "crash", "clean"]
        assert [r["attempt"] for r in records] == [0, 1, 2]

    def test_restart_budget_exhausts(self, tmp_path):
        proc, records = _run_supervisor(
            tmp_path, [sys.executable, "-c", "import sys; sys.exit(3)"],
            "--max-restarts", "1",
        )
        assert proc.returncode == 3
        assert "budget exhausted" in proc.stderr
        assert [r["outcome"] for r in records] == ["crash", "crash"]

    def test_resume_flag_injected_when_checkpoint_exists(self, tmp_path):
        """On restart the child is relaunched with --resume-from pointing
        at the rescue checkpoint (only once it exists on disk)."""
        ckpt = tmp_path / "last.ckpt"
        ckpt.mkdir()
        (ckpt / "state.msgpack").write_bytes(b"x")
        # the supervisor only injects checkpoints that pass integrity
        # verification (train/ckpt_writer.py manifests)
        import hashlib

        (ckpt / "manifest.json").write_text(json.dumps({
            "format": 1, "step": 0, "files": {"state.msgpack": {
                "sha256": hashlib.sha256(b"x").hexdigest(), "bytes": 1,
            }},
        }))
        script = tmp_path / "argv_logger.py"
        script.write_text(
            "import os, sys\n"
            f"log = {str(tmp_path / 'argvs')!r}\n"
            "open(log, 'a').write(' '.join(sys.argv[1:]) + '\\n')\n"
            f"p = {str(tmp_path / 'count')!r}\n"
            "n = int(open(p).read()) if os.path.exists(p) else 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            "sys.exit(0 if n >= 1 else 3)\n"
        )
        proc, records = _run_supervisor(
            tmp_path,
            [sys.executable, str(script), "--seed", "1",
             "--faults", "sigkill@9"],
            "--max-restarts", "2", "--resume-ckpt", str(ckpt),
        )
        assert proc.returncode == 0, proc.stderr
        argvs = open(tmp_path / "argvs").read().splitlines()
        assert "--resume-from" not in argvs[0]  # first launch verbatim
        assert "--faults sigkill@9" in argvs[0]
        assert f"--resume-from {ckpt}" in argvs[1]
        # CLI fault specs are first-launch-only, like the env channel
        assert "--faults" not in argvs[1]
        assert records[1]["resumed_from"] == str(ckpt)

    def test_sigterm_to_supervisor_forwards_and_stops(self, tmp_path):
        """Preemption semantics: SIGTERM to the supervisor reaches the
        child and ends the loop with no restart."""
        log = tmp_path / "restarts.json"
        proc = subprocess.Popen(
            [sys.executable, SUPERVISOR, "--backoff-base", "0.01",
             "--restart-log", str(log), "--max-restarts", "3", "--",
             sys.executable, "-c", "import time; time.sleep(60)"],
            stderr=subprocess.PIPE, text=True,
        )
        time.sleep(1.0)  # let the child start
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 128 + signal.SIGTERM
        records = [json.loads(l) for l in open(log)]
        assert len(records) == 1
        assert records[0]["outcome"] == "sigterm"


def _train_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop(faults.ENV_VAR, None)
    return env


def _train_cmd(tmp_path, *extra):
    return [
        sys.executable, TRAIN_PY, "--model", "diff",
        "--dataset", "synthetic", "--num-train-samples", "200",
        "--vocab-size", "256", "--n-embd", "32", "--n-head", "2",
        "--n-layer", "2", "--block-size", "16",
        "--compute-dtype", "float32", "--micro-batch-size", "4",
        "--max-iters", "24", "--eval-interval", "8", "--eval-iters", "2",
        "--learning-rate", "3e-3", "--warmup-iters", "5", "--seed", "7",
        *extra,
    ]


def _run_chaos(tmp_path, name, *extra, supervised=False, fault=None,
               resume_ckpt=None):
    """One train.py run (optionally under the supervisor) in its own
    checkpoint/metrics namespace but the SHARED tokenizer cache. Faults
    ride the DTX_FAULTS env var — the supervisor strips it from the
    child env on restarts, so an injected kill fires exactly once even
    when the resumed run replays the same iteration."""
    d = tmp_path / name
    d.mkdir()
    env = _train_env()
    cmd = _train_cmd(
        tmp_path,
        "--tokenizer-dir", str(tmp_path / "tokenizer"),
        "--checkpoint-path", str(d / "best.ckpt"),
        "--last-checkpoint-path", str(d / "last.ckpt"),
        "--metrics-path", str(d / "metrics.jsonl"),
        *extra,
    )
    if fault:
        env[faults.ENV_VAR] = fault
    if supervised:
        cmd = [
            sys.executable, SUPERVISOR, "--backoff-base", "0.05",
            "--max-restarts", "3", "--restart-log", str(d / "restarts.json"),
            "--resume-ckpt", str(resume_ckpt or (d / "last.ckpt")), "--",
        ] + cmd
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, env=env
    )
    return d, proc


def _final_eval(metrics_path):
    evals = [
        json.loads(l) for l in open(metrics_path) if "val_loss" in l
    ]
    return evals[-1]


@pytest.mark.slow
def test_sigkill_resume_under_supervisor_matches_uninterrupted(tmp_path):
    """THE chaos acceptance test: a run SIGKILLed mid-flight (no rescue
    save possible) and relaunched by the supervisor from the last
    on-disk checkpoint reaches the same final step with the SAME final
    val loss as an uninterrupted run — the epoch-sampler fast-forward
    and the sequential val batches make the comparison exact — and the
    final rescue checkpoints are byte-identical."""
    a, proc_a = _run_chaos(tmp_path, "uninterrupted")
    assert proc_a.returncode == 0, proc_a.stderr[-2000:]

    b, proc_b = _run_chaos(
        tmp_path, "killed", supervised=True, fault="sigkill@18",
        resume_ckpt=tmp_path / "killed" / "best.ckpt",
    )
    assert proc_b.returncode == 0, proc_b.stderr[-2000:]
    records = [json.loads(l) for l in open(b / "restarts.json")]
    assert [r["outcome"] for r in records] == ["sigkill", "clean"]
    assert records[1]["resumed_from"] == str(b / "best.ckpt")

    ea, eb = _final_eval(a / "metrics.jsonl"), _final_eval(b / "metrics.jsonl")
    assert ea["iter"] == eb["iter"] == 24
    assert ea["val_loss"] == pytest.approx(eb["val_loss"], abs=1e-9)
    # the resumed run's final state is bit-identical to the clean run's
    sa = open(a / "last.ckpt" / "state.msgpack", "rb").read()
    sb = open(b / "last.ckpt" / "state.msgpack", "rb").read()
    assert sa == sb


@pytest.mark.slow
def test_crash_resume_rides_rescue_checkpoint(tmp_path):
    """A catchable crash (raise@K) writes the rescue checkpoint on the
    way down; the supervisor resumes from it and the finished run
    matches the uninterrupted one bit-for-bit."""
    a, proc_a = _run_chaos(tmp_path, "clean_run")
    assert proc_a.returncode == 0, proc_a.stderr[-2000:]

    b, proc_b = _run_chaos(
        tmp_path, "crashed", supervised=True, fault="raise@13",
    )
    assert proc_b.returncode == 0, proc_b.stderr[-2000:]
    records = [json.loads(l) for l in open(b / "restarts.json")]
    assert [r["outcome"] for r in records] == ["crash", "clean"]
    # resumed from the rescue checkpoint at exactly the crash iteration
    assert records[1]["resumed_from"] == str(b / "last.ckpt")
    assert "Resumed from" in proc_b.stdout
    sa = open(a / "last.ckpt" / "state.msgpack", "rb").read()
    sb = open(b / "last.ckpt" / "state.msgpack", "rb").read()
    assert sa == sb
