"""REAL 2-process distributed training test (VERDICT r2 item 2c).

Spawns two genuine OS processes, each a jax process with 4 virtual CPU
devices, joined via ``jax.distributed.initialize`` + gloo collectives
into one 8-device 2-process runtime. Both run the full trainer
(data×fsdp mesh, batched eval, best/rescue checkpoint saves, resume),
with state shards genuinely NON-addressable across the process boundary
— the regime the faked-slice tests in test_multihost.py cannot reach.

Parity oracle: the identical config trained in THIS process (8 local
devices, single jax process, same mesh axes). Same seeds → identical
data draws and init, so the per-iter losses, eval losses, and the
post-resume continuation must agree to float32 collective-reduction
noise. That simultaneously validates the per-process slice + global
assembly of train AND eval batches, and the collective host-gather in
save_checkpoint/resume.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)

_PORT = 21000 + os.getpid() % 9000


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _losses(records, key="loss"):
    return {r["iter"]: r[key] for r in records if key in r}


def _run_workers(workdir):
    env = dict(os.environ)
    # 4 virtual CPU devices per process; REPLACE the parent's 8-device
    # flag. JAX_PLATFORMS is pinned by sitecustomize, the worker
    # overrides it through jax.config before backend init.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "mh2_worker.py")
    # each worker's output goes to a FILE, not a pipe: with pipes, a
    # worker that fills its 64KB buffer while the parent is draining the
    # other one blocks, and its gloo peer then blocks inside a
    # collective — a three-way deadlock that only resolves at timeout
    logs = [os.path.join(workdir, f"worker_{rank}.log") for rank in (0, 1)]
    handles = [open(p, "w") for p in logs]
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(rank), str(_PORT), workdir],
                env=env,
                stdout=handles[rank],
                stderr=subprocess.STDOUT,
                text=True,
            )
            for rank in (0, 1)
        ]
        for p in procs:
            try:
                p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
    finally:
        for h in handles:
            h.close()
    for rank, p in enumerate(procs):
        with open(logs[rank]) as f:
            out = f.read()
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert os.path.exists(os.path.join(workdir, f"done_{rank}"))


def _single_process_reference(workdir):
    """The same two-phase run (train 4 iters, resume to 6) on this
    process's 8 local devices — identical mesh axes and seeds."""
    from differential_transformer_replication_tpu.train.trainer import train

    cwd = os.getcwd()
    rundir = os.path.join(workdir, "single")
    os.makedirs(rundir, exist_ok=True)
    os.chdir(rundir)
    try:
        cfg = TrainConfig(
            model=ModelConfig(
                model="diff",
                vocab_size=300,
                n_embd=64,
                n_head=2,
                n_layer=2,
                block_size=32,
                dropout=0.0,
                compute_dtype="float32",
                attention_impl="xla",
            ),
            mesh=MeshConfig(data=4, fsdp=2),
            micro_batch_size=8,
            grad_acc_steps=1,
            max_iters=4,
            eval_interval=2,
            eval_iters=2,
            log_interval=1,
            dataset="synthetic",
            num_train_samples=200,
            vocab_size=300,
            seed=3,
            metrics_path=os.path.join(workdir, "metrics_single.jsonl"),
            checkpoint_path=os.path.join(workdir, "best_single.ckpt"),
            last_checkpoint_path=os.path.join(workdir, "last_single.ckpt"),
        )
        train(cfg)
        cfg2 = cfg.replace(
            max_iters=6,
            resume_from=os.path.join(workdir, "last_single.ckpt"),
            metrics_path=os.path.join(workdir, "metrics_single_resume.jsonl"),
        )
        train(cfg2)
    finally:
        os.chdir(cwd)


@pytest.mark.skipif(sys.platform != "linux", reason="gloo CPU collectives")
def test_two_process_training_matches_single_process(tmp_path):
    workdir = str(tmp_path)
    _run_workers(workdir)
    _single_process_reference(workdir)

    for phase, mh_name, single_name in (
        ("fresh", "metrics_2proc.jsonl", "metrics_single.jsonl"),
        ("resume", "metrics_2proc_resume.jsonl", "metrics_single_resume.jsonl"),
    ):
        mh = _read_jsonl(os.path.join(workdir, mh_name))
        single = _read_jsonl(os.path.join(workdir, single_name))
        for key in ("loss", "train_loss", "val_loss"):
            lm, ls = _losses(mh, key), _losses(single, key)
            assert set(lm) == set(ls), (phase, key, lm, ls)
            assert lm, (phase, key)  # at least one record
            for it in lm:
                np.testing.assert_allclose(
                    lm[it], ls[it], rtol=1e-5, atol=1e-6,
                    err_msg=f"{phase} {key} iter {it}",
                )

    # the resume really continued (iters 5..6 present after a 4-iter run)
    resume = _losses(_read_jsonl(os.path.join(workdir, "metrics_2proc_resume.jsonl")))
    assert set(resume) == {5, 6}, resume

    # the 2-process best checkpoint is readable and was written at an
    # eval boundary (the resume run may legitimately re-save at iter 6)
    with open(os.path.join(workdir, "best.ckpt", "meta.json")) as f:
        meta = json.load(f)
    assert meta["iter_num"] in (2, 4, 6)


@pytest.mark.skipif(sys.platform != "linux", reason="gloo CPU collectives")
@pytest.mark.parametrize("leg", ["tensor", "pipeline"])
def test_two_proc_axis_crossing_legs(leg):
    """The round-4 dryrun legs where the 2-process boundary cuts the
    ``tensor`` (Megatron activation all-gather over DCN) or ``pipeline``
    (GPipe ppermute handoff) mesh axis — structurally different
    cross-process collectives from the data leg (VERDICT r3 item 5).
    ``_dryrun_2proc`` spawns both ranks as real OS processes and raises
    unless both exit 0 with a finite loss."""
    import __graft_entry__ as g

    g._dryrun_2proc(2, leg)
