"""Training-slice tests: schedule parity, optimizer behavior, and a tiny
end-to-end run per model family asserting the loss decreases."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.train import (
    cosine_warmup_schedule,
    create_train_state,
    make_eval_step,
    make_multi_train_step,
    make_train_step,
)

TINY_MODEL = dict(vocab_size=31, n_embd=32, n_head=2, n_layer=2, block_size=16,
                  dropout=0.0, compute_dtype="float32")


def tiny_train_cfg(model_kind, **kw):
    defaults = dict(
        vocab_size=31,
        learning_rate=1e-2,
        min_lr=1e-3,
        warmup_iters=10,
        max_iters=200,
        control_head_multiplier=1,
    )
    return TrainConfig(
        model=ModelConfig(model=model_kind, **TINY_MODEL), **{**defaults, **kw}
    )


class TestSchedule:
    def test_exact_reference_formula(self):
        """CosineWarmupScheduler.get_lr (train.py:116-123): linear warmup
        then cosine from base to min_lr."""
        base, warm, mx, mn = 3.2e-4, 1000, 40_000, 6e-5
        sched = cosine_warmup_schedule(base, warm, mx, mn)
        # first optimizer step runs at lr 0 (torch scheduler quirk)
        assert float(sched(0)) == 0.0
        assert float(sched(500)) == pytest.approx(base * 500 / warm, rel=1e-6)
        assert float(sched(warm)) == pytest.approx(base, rel=1e-6)  # progress 0
        # midpoint of decay: factor 0.5
        mid = warm + (mx - warm) // 2
        want = mn + (base - mn) * 0.5 * (1 + math.cos(math.pi * 0.5))
        assert float(sched(mid)) == pytest.approx(want, rel=1e-4)
        assert float(sched(mx)) == pytest.approx(mn, rel=1e-4)  # factor 0

    def test_monotone_decay_after_warmup(self):
        sched = cosine_warmup_schedule(1e-3, 10, 100, 1e-5)
        vals = [float(sched(s)) for s in range(10, 101, 10)]
        assert all(a >= b for a, b in zip(vals, vals[1:]))


class TestTrainStep:
    def test_loss_decreases_all_models(self):
        """Tiny memorization run per family: loss must drop well below the
        random-init plateau (the reference's only correctness check is this
        same signal, train.py:288)."""
        for kind in ("control", "diff", "ndiff"):
            cfg = tiny_train_cfg(kind)
            state = create_train_state(jax.random.PRNGKey(0), cfg)
            step = make_train_step(cfg)
            # fixed batch -> memorize
            key = jax.random.PRNGKey(1)
            x = jax.random.randint(key, (1, 8, 16), 0, 31)
            y = jnp.roll(x, -1, axis=-1)
            batch = {"x": x, "y": y}
            first = None
            for _ in range(60):
                state, metrics = step(state, batch)
                if first is None:
                    first = float(metrics["loss"])
            last = float(metrics["loss"])
            assert last < first - 1.0, f"{kind}: {first} -> {last}"
            assert int(state["step"]) == 60

    def test_grad_accumulation_matches_big_batch(self):
        """A=2 microbatches of 4 must produce the same update as A=1
        microbatch of 8 (gradient averaging, train.py:265)."""
        cfg = tiny_train_cfg("control")
        state1 = create_train_state(jax.random.PRNGKey(0), cfg)
        # deep copy: the train step donates its input state, so the two
        # runs must not share buffers
        state2 = jax.tree_util.tree_map(jnp.copy, state1)
        step = make_train_step(cfg)
        x = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 31)
        y = jnp.roll(x, -1, axis=-1)
        big = {"x": x[None], "y": y[None]}  # (1, 8, 16)
        split = {"x": x.reshape(2, 4, 16), "y": y.reshape(2, 4, 16)}
        s1, m1 = step(state1, big)
        s2, m2 = step(state2, split)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        leaves1 = jax.tree_util.tree_leaves(s1["params"])
        leaves2 = jax.tree_util.tree_leaves(s2["params"])
        for a, b in zip(leaves1, leaves2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)

    def test_multi_step_scan_matches_sequential_steps(self):
        """make_multi_train_step (K optimizer steps per launch) must be
        numerically identical to K sequential make_train_step calls on
        the same batch/rng sequence — it only changes the LAUNCH
        structure, never the math."""
        K = 4
        cfg = tiny_train_cfg("diff")
        key = jax.random.PRNGKey(0)
        xs = jax.random.randint(
            jax.random.PRNGKey(1),
            (K, 1, 4, cfg.model.block_size), 0, cfg.vocab_size,
        )
        ys = jnp.roll(xs, -1, axis=-1)

        s1 = create_train_state(key, cfg)
        step = make_train_step(cfg)
        losses = []
        for k in range(K):
            s1, m = step(s1, {"x": xs[k], "y": ys[k]}, None)
            losses.append(float(m["loss"]))

        s2 = create_train_state(key, cfg)
        multi = make_multi_train_step(cfg, K)
        s2, mm = multi(s2, {"x": xs, "y": ys}, None)
        np.testing.assert_allclose(
            np.asarray(mm["loss"]), np.asarray(losses), rtol=1e-6, atol=1e-7
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            s1["params"], s2["params"],
        )
        # the contract is fail-loud on a K mismatch
        with pytest.raises(AssertionError):
            make_multi_train_step(cfg, K + 1)(
                create_train_state(key, cfg), {"x": xs, "y": ys}, None
            )

    def test_first_step_lr_zero_keeps_params(self):
        """Step 0 runs at lr=0 (torch scheduler quirk): params must be
        unchanged apart from nothing — AdamW with lr 0 is a no-op update."""
        cfg = tiny_train_cfg("control")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        before = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), state["params"])
        step = make_train_step(cfg)
        x = jax.random.randint(jax.random.PRNGKey(3), (1, 4, 16), 0, 31)
        state, metrics = step(state, {"x": x, "y": jnp.roll(x, -1, -1)})
        assert float(metrics["learning_rate"]) == 0.0
        after = state["params"]
        for a, b in zip(jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)

    def test_grad_clipping_feeds_clipped_grads_to_adamw(self):
        """clip_by_global_norm(1.0) sits before AdamW (train.py:274-278):
        with raw grads of norm 10, the first-moment estimate must be
        (1-b1) * clipped grads, i.e. have global norm (1-b1) * 1.0."""
        import optax

        from differential_transformer_replication_tpu.train import make_optimizer

        cfg = tiny_train_cfg("control")
        params = {"w": jnp.ones((4, 4))}
        tx, _ = make_optimizer(cfg)
        opt_state = tx.init(params)
        grads = {"w": jnp.full((4, 4), 10.0 / 4.0)}  # global norm 10
        _, new_state = tx.update(grads, opt_state, params)
        mu = new_state[1][0].mu  # adamw first moment
        np.testing.assert_allclose(
            float(optax.global_norm(mu)), (1 - cfg.beta1) * 1.0, rtol=1e-5
        )

    def test_grad_norm_metric_is_preclip(self):
        """The logged grad_norm is the pre-clip norm, like torch's
        clip_grad_norm_ return value."""
        cfg = tiny_train_cfg("control")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        x = jax.random.randint(jax.random.PRNGKey(4), (1, 8, 16), 0, 31)
        _, metrics = step(state, {"x": x, "y": jnp.roll(x, -1, -1)})
        assert np.isfinite(float(metrics["grad_norm"])) and float(metrics["grad_norm"]) > 0

    def test_eval_step_deterministic(self):
        cfg = tiny_train_cfg("diff")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        ev = make_eval_step(cfg)
        x = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0, 31)
        l1 = float(ev(state["params"], x, jnp.roll(x, -1, -1)))
        l2 = float(ev(state["params"], x, jnp.roll(x, -1, -1)))
        assert l1 == l2 and np.isfinite(l1)

    def test_eval_many_matches_eval_step_loop(self):
        """One scanned eval_many call == per-batch eval_step calls (the
        O(1)-host-sync eval path, VERDICT r1 item 5)."""
        from differential_transformer_replication_tpu.train.step import (
            make_eval_many,
        )

        cfg = tiny_train_cfg("diff")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        ev = make_eval_step(cfg)
        evm = make_eval_many(cfg)
        xs = jax.random.randint(jax.random.PRNGKey(6), (5, 4, 16), 0, 31)
        ys = jnp.roll(xs, -1, -1)
        many = np.asarray(evm(state["params"], xs, ys))
        singles = np.array(
            [float(ev(state["params"], xs[k], ys[k])) for k in range(5)]
        )
        np.testing.assert_allclose(many, singles, rtol=1e-6)

    def test_last_checkpoint_path_auto_derives_from_checkpoint_path(self):
        """'auto' (the default) derives a sibling of checkpoint_path so
        concurrent runs in one directory never clobber each other's rescue
        checkpoint (ADVICE r1)."""
        cfg = tiny_train_cfg("diff").replace(checkpoint_path="runs/exp7.ckpt")
        assert cfg.resolved_last_checkpoint_path() == "runs/exp7.last.ckpt"
        cfg = cfg.replace(last_checkpoint_path=None)
        assert cfg.resolved_last_checkpoint_path() is None
        cfg = cfg.replace(last_checkpoint_path="explicit.ckpt")
        assert cfg.resolved_last_checkpoint_path() == "explicit.ckpt"

    def test_control_head_multiplier_applied(self):
        """train.py:226 quirk: control trains with doubled heads."""
        cfg = TrainConfig(model=ModelConfig(model="control", **TINY_MODEL), vocab_size=31)
        assert cfg.resolved_model().n_head == 2 * TINY_MODEL["n_head"]
        cfg_diff = TrainConfig(model=ModelConfig(model="diff", **TINY_MODEL), vocab_size=31)
        assert cfg_diff.resolved_model().n_head == TINY_MODEL["n_head"]
