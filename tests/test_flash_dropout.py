"""In-kernel attention-probability dropout (ops/flash.py).

The reference drops out each softmax map independently, after
normalization, with inverted scaling (diff_transformer.py:58-67). The
flash kernels implement this with a counter-based hash mask keyed on
global coordinates; ``dropout_keep_reference`` is the plain-jnp twin of
the kernel's mask generation, so a dense oracle using the SAME masks must
match the kernel bit-for-bit (up to fp32 accumulation order) — an exact
parity test, not a statistical one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.ops import flash as F

S, B, T, H, d = 2, 2, 32, 2, 8
DV = 2 * d
RATE = 0.3


def make_inputs(seed=0):
    ks_ = jax.random.split(jax.random.PRNGKey(seed), 4)
    qs = jax.random.normal(ks_[0], (S, B, T, H, d), jnp.float32)
    ks = jax.random.normal(ks_[1], (S, B, T, H, d), jnp.float32)
    v = jax.random.normal(ks_[2], (B, T, H, DV), jnp.float32)
    coeffs = jax.random.uniform(ks_[3], (S, H), jnp.float32, 0.2, 1.0)
    return qs, ks, v, coeffs


def dense_with_masks(qs, ks, v, coeffs, keep, rate):
    """Dense oracle: softmax -> (given) dropout masks -> coeff combine."""
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("sbthd,sbuhd->sbhtu", qs, ks).astype(jnp.float32) * scale
    causal = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(causal, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)  # (S, B, H, T, T)
    if keep is not None:
        keep_r = keep.reshape(B, H, S, T, T).transpose(2, 0, 1, 3, 4)
        probs = jnp.where(keep_r, probs / (1.0 - rate), 0.0)
    combined = jnp.einsum("sh,sbhtu->bhtu", coeffs, probs)
    return jnp.einsum("bhtu,buhe->bthe", combined, v)


def test_forward_matches_dense_with_same_masks():
    qs, ks, v, coeffs = make_inputs()
    rng = jax.random.PRNGKey(7)
    got = F.multi_stream_flash_attention(
        qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=rng
    )
    keep = F.dropout_keep_reference(F.dropout_seed_from_rng(rng), B * H, S, T, RATE)
    want = dense_with_masks(qs, ks, v, coeffs, keep, RATE)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_grad_matches_dense_with_same_masks():
    qs, ks, v, coeffs = make_inputs(1)
    rng = jax.random.PRNGKey(11)
    keep = F.dropout_keep_reference(F.dropout_seed_from_rng(rng), B * H, S, T, RATE)

    def loss_flash(qs, ks, v):
        out = F.multi_stream_flash_attention(
            qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=rng
        )
        return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

    def loss_dense(qs, ks, v):
        out = dense_with_masks(qs, ks, v, coeffs, keep, RATE)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(qs, ks, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(qs, ks, v)
    for a, b, name in zip(gf, gd, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, err_msg=name
        )


def test_tiled_kernels_match_dense_with_same_masks(monkeypatch):
    """Force the KV-streamed kernel variants (T > threshold) and check the
    same exact parity — the tiled fwd/dq/dkv kernels regenerate identical
    masks from the same global coordinates."""
    monkeypatch.setattr(F, "_KV_TILE_THRESHOLD", 16)
    qs, ks, v, coeffs = make_inputs(2)
    rng = jax.random.PRNGKey(13)
    keep = F.dropout_keep_reference(F.dropout_seed_from_rng(rng), B * H, S, T, RATE)

    def loss_flash(qs, ks, v):
        out = F.multi_stream_flash_attention(
            qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=rng,
            block_q=16, block_k=16, block_q_train=16, block_k_train=16,
        )
        return jnp.sum(out * out)

    def loss_dense(qs, ks, v):
        out = dense_with_masks(qs, ks, v, coeffs, keep, RATE)
        return jnp.sum(out * out)

    np.testing.assert_allclose(
        float(loss_flash(qs, ks, v)), float(loss_dense(qs, ks, v)), rtol=1e-5
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(qs, ks, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(qs, ks, v)
    for a, b, name in zip(gf, gd, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, err_msg=name
        )


def test_rate_zero_is_identity_with_baseline():
    qs, ks, v, coeffs = make_inputs(3)
    base = F.multi_stream_flash_attention(qs, ks, v, coeffs)
    z = F.multi_stream_flash_attention(
        qs, ks, v, coeffs, dropout_rate=0.0, dropout_rng=jax.random.PRNGKey(0)
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(z))
    # no rng key => rate inert (eval semantics, like ops/dropout.py)
    no_key = F.multi_stream_flash_attention(qs, ks, v, coeffs, dropout_rate=RATE)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(no_key))


def test_deterministic_per_key_and_varies_across_keys():
    qs, ks, v, coeffs = make_inputs(4)
    a = F.multi_stream_flash_attention(
        qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=jax.random.PRNGKey(5)
    )
    b = F.multi_stream_flash_attention(
        qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=jax.random.PRNGKey(5)
    )
    c = F.multi_stream_flash_attention(
        qs, ks, v, coeffs, dropout_rate=RATE, dropout_rng=jax.random.PRNGKey(6)
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_mask_keep_fraction():
    keep = F.dropout_keep_reference(
        F.dropout_seed_from_rng(jax.random.PRNGKey(9)), 4, 2, 64, RATE
    )
    frac = float(jnp.mean(keep.astype(jnp.float32)))
    n = keep.size
    sigma = np.sqrt(RATE * (1 - RATE) / n)
    assert abs(frac - (1 - RATE)) < 4 * sigma + 1e-3, frac


def test_mask_decorrelated_across_bh_and_streams():
    keep = F.dropout_keep_reference(
        F.dropout_seed_from_rng(jax.random.PRNGKey(10)), 2, 2, 64, 0.5
    )
    # (BH, S, T, T): any two distinct slices should differ
    assert not np.array_equal(np.asarray(keep[0]), np.asarray(keep[1]))
    assert not np.array_equal(np.asarray(keep[0, 0]), np.asarray(keep[0, 1]))


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_model_forward_with_fused_dropout(kind):
    from differential_transformer_replication_tpu.config import ModelConfig
    from differential_transformer_replication_tpu.models import (
        init_model,
        model_forward,
    )

    cfg = ModelConfig(
        model=kind, vocab_size=64, n_embd=32, n_head=2, n_layer=2,
        block_size=16, dropout=0.25, compute_dtype="float32",
        attention_impl="pallas",
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    y = jnp.roll(x, -1, -1)
    _, loss_train = model_forward(
        params, x, cfg, targets=y, rng=jax.random.PRNGKey(2)
    )
    _, loss_eval = model_forward(params, x, cfg, targets=y, rng=None)
    assert np.isfinite(float(loss_train)) and np.isfinite(float(loss_eval))
    # dropout active on the train path only
    assert float(loss_train) != float(loss_eval)
    # gradient flows through the fused dropout
    g = jax.grad(
        lambda p: model_forward(
            p, x, cfg, targets=y, rng=jax.random.PRNGKey(2)
        )[1]
    )(params)
    gn = float(
        jnp.sqrt(
            sum(jnp.sum(a.astype(jnp.float32) ** 2)
                for a in jax.tree_util.tree_leaves(g))
        )
    )
    assert np.isfinite(gn) and gn > 0
