"""Telemetry-layer tests (obs/): registry + Prometheus exposition, span
tracer, /metrics endpoints, engine/trainer instrumentation, and the
zero-overhead pins (no added recompiles in the jitted hot paths).

All quick (tier-1): tiny models, in-process HTTP servers on ephemeral
ports, a ~10-iteration trainer run.
"""

import json
import math
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request
from functools import lru_cache

import jax
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.models import init_model
from differential_transformer_replication_tpu.obs import (
    NOOP_TRACER,
    Registry,
    SpanTracer,
    start_metrics_server,
)
from differential_transformer_replication_tpu.obs.introspect import (
    lambda_record,
    make_param_summary,
)
from differential_transformer_replication_tpu.obs.registry import StatsMap
from differential_transformer_replication_tpu.serving import (
    ServingClient,
    ServingEngine,
    serve,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")

TINY_MODEL = dict(vocab_size=256, n_embd=32, n_head=2, n_layer=2,
                  block_size=16, dropout=0.0, compute_dtype="float32")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind="control", vocab=59):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind="control", vocab=59):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


# -- a minimal Prometheus text-exposition parser (the test oracle) ------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$'
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """-> (types {name: kind}, samples [(name, {label: value}, float)]).
    Raises on malformed lines — the validity check itself."""
    types, samples = {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert kind in ("counter", "gauge", "histogram"), line
            types[name] = kind
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP "), f"stray comment: {line!r}"
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        labels = {}
        if m.group(2):
            for lm in _LABEL_RE.finditer(m.group(2)):
                labels[lm.group(1)] = (
                    lm.group(2).replace('\\"', '"')
                    .replace("\\n", "\n").replace("\\\\", "\\")
                )
        samples.append((m.group(1), labels, float(m.group(3))))
    return types, samples


def _hist_buckets(samples, name, match=None):
    """le -> cumulative count for one histogram child, in exposition
    order."""
    out = []
    for n, labels, v in samples:
        if n != f"{name}_bucket":
            continue
        if match and any(labels.get(k) != mv for k, mv in match.items()):
            continue
        out.append((labels["le"], v))
    return out


def assert_histogram_valid(samples, name, match=None):
    buckets = _hist_buckets(samples, name, match)
    assert buckets, f"no buckets for {name}"
    assert buckets[-1][0] == "+Inf"
    counts = [c for _, c in buckets]
    assert counts == sorted(counts), f"{name} buckets not monotone"
    count = [v for n, l, v in samples if n == f"{name}_count"
             and (not match or all(l.get(k) == mv
                                   for k, mv in (match or {}).items()))]
    assert count and count[0] == counts[-1]  # _count == +Inf bucket


# -- registry + exposition ---------------------------------------------


class TestRegistry:
    def test_exposition_names_types_and_values(self):
        reg = Registry()
        c = reg.counter("requests_total", "Requests.")
        c.inc()
        c.inc(2)
        g = reg.gauge("queue_depth", "Depth.")
        g.set(7)
        h = reg.histogram("latency_seconds", "Latency.",
                          buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        types, samples = parse_exposition(reg.render())
        assert types == {"requests_total": "counter",
                         "queue_depth": "gauge",
                         "latency_seconds": "histogram"}
        vals = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
        assert vals[("requests_total", ())] == 3
        assert vals[("queue_depth", ())] == 7
        assert_histogram_valid(samples, "latency_seconds")
        assert vals[("latency_seconds_count", ())] == 4
        assert abs(vals[("latency_seconds_sum", ())] - 55.55) < 1e-9
        # exact cumulative ladder
        assert _hist_buckets(samples, "latency_seconds") == [
            ("0.1", 1), ("1", 2), ("10", 3), ("+Inf", 4)
        ]

    def test_labels_and_escaping(self):
        reg = Registry()
        c = reg.counter("events_total", 'Help with \\ and\nnewline.',
                        labelnames=("kind",))
        nasty = 'quote " backslash \\ newline \n end'
        c.inc(kind=nasty)
        c.inc(kind="plain")
        text = reg.render()
        # escaping keeps the exposition line-oriented: exactly one HELP
        # line despite the raw newline in the help text / label value
        assert sum(
            1 for l in text.splitlines() if l.startswith("# HELP")
        ) == 1
        types, samples = parse_exposition(text)
        labels = {l["kind"] for n, l, v in samples if n == "events_total"}
        assert labels == {nasty, "plain"}  # round-trips through escaping

    def test_histogram_label_children_are_independent(self):
        reg = Registry()
        h = reg.histogram("op_seconds", "", labelnames=("op",),
                          buckets=(1.0,))
        h.observe(0.5, op="a")
        h.observe(2.0, op="b")
        _, samples = parse_exposition(reg.render())
        assert_histogram_valid(samples, "op_seconds", match={"op": "a"})
        assert_histogram_valid(samples, "op_seconds", match={"op": "b"})
        assert ("op_seconds_count", {"op": "a"}, 1.0) in samples

    def test_name_and_type_guards(self):
        reg = Registry()
        with pytest.raises(ValueError):
            reg.counter("bad name", "")
        with pytest.raises(ValueError):
            reg.counter("1leading", "")
        reg.counter("ok_total", "")
        with pytest.raises(ValueError):  # same name, different type
            reg.gauge("ok_total", "")
        with pytest.raises(ValueError):
            reg.histogram("h", "", buckets=(1.0, 1.0))
        with pytest.raises(ValueError):
            reg.counter("neg_total", "").inc(-1)

    def test_get_or_create_returns_same_metric(self):
        reg = Registry()
        assert reg.counter("a_total", "") is reg.counter("a_total", "")

    def test_concurrent_increments_do_not_tear(self):
        reg = Registry()
        c = reg.counter("n_total", "")

        def bump():
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000

    def test_stats_map_is_dict_compatible(self):
        reg = Registry()
        stats = StatsMap(reg, {
            "completed": ("x_completed_total", ""),
            "rejected": ("x_rejected_total", ""),
        })
        stats.inc("completed")
        stats["rejected"] += 2  # the compat path
        assert stats["completed"] == 1 and stats["rejected"] == 2
        assert dict(stats) == {"completed": 1, "rejected": 2}
        assert stats.snapshot() == {"completed": 1, "rejected": 2}
        assert "completed" in stats and len(stats) == 2
        # the registry sees the same values — one source of truth
        _, samples = parse_exposition(reg.render())
        vals = {n: v for n, l, v in samples}
        assert vals["x_completed_total"] == 1
        assert vals["x_rejected_total"] == 2


# -- span tracer --------------------------------------------------------


class TestSpanTracer:
    def test_nested_and_threaded_spans_emit_valid_chrome_json(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        tracer = SpanTracer(path, process_name="test", flush_every=3)

        with tracer.span("outer", step=1):
            with tracer.span("inner"):
                time.sleep(0.002)
            tracer.instant("marker", note="hi")
        tracer.counter("depth", queued=3, active=2)

        def worker(i):
            with tracer.span("worker", idx=i):
                time.sleep(0.001)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tracer.close()
        tracer.close()  # idempotent

        events = json.load(open(path))  # valid JSON array
        assert isinstance(events, list)
        by_name = {}
        for ev in events:
            assert {"name", "ph", "pid"} <= set(ev)
            if ev["ph"] in ("X", "i", "C"):
                assert "ts" in ev
            by_name.setdefault(ev["name"], []).append(ev)
        outer, inner = by_name["outer"][0], by_name["inner"][0]
        for ev in (outer, inner):
            assert ev["ph"] == "X" and ev["dur"] >= 0
        # nesting: inner lies within outer on the SAME thread track
        assert inner["tid"] == outer["tid"]
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
        # three worker spans, each carrying its own thread id
        workers = by_name["worker"]
        assert len(workers) == 3
        assert len({w["tid"] for w in workers}) == 3
        assert by_name["marker"][0]["ph"] == "i"
        assert by_name["depth"][0]["args"] == {"queued": 3, "active": 2}
        # metadata names the process for the viewer
        assert any(e["ph"] == "M" for e in events)

    def test_late_events_after_close_are_dropped(self, tmp_path):
        path = str(tmp_path / "t2.trace.json")
        tracer = SpanTracer(path)
        tracer.instant("a")
        tracer.close()
        tracer.instant("b")  # must not corrupt the closed file
        events = json.load(open(path))
        assert "b" not in {e["name"] for e in events}

    def test_noop_tracer_is_free_and_silent(self):
        with NOOP_TRACER.span("x", a=1):
            pass
        NOOP_TRACER.instant("y")
        NOOP_TRACER.counter("z", v=1)
        NOOP_TRACER.flush()
        NOOP_TRACER.close()


# -- serving instrumentation -------------------------------------------


def test_engine_populates_latency_histograms_and_gauges():
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
    )
    outs = eng.generate(_prompts([3, 9, 5], cfg.vocab_size, seed=2),
                        max_new_tokens=4, temperature=0.0)
    assert len(outs) == 3
    types, samples = parse_exposition(eng.registry.render())
    assert types["serving_ttft_seconds"] == "histogram"
    assert types["serving_itl_seconds"] == "histogram"
    assert types["serving_queue_wait_seconds"] == "histogram"
    assert types["serving_slot_occupancy"] == "gauge"
    assert types["serving_kv_utilization"] == "gauge"
    for h in ("serving_ttft_seconds", "serving_itl_seconds",
              "serving_queue_wait_seconds", "serving_engine_step_seconds"):
        assert_histogram_valid(samples, h)
    vals = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    # one TTFT observation per request; ITL fills the remaining tokens
    assert vals[("serving_ttft_seconds_count", ())] == 3
    assert vals[("serving_itl_seconds_count", ())] == 3 * (4 - 1)
    assert vals[("serving_queue_wait_seconds_count", ())] == 3
    # idle engine: gauges fell back to zero after the last retirement
    assert vals[("serving_slot_occupancy", ())] == 0
    assert vals[("serving_kv_utilization", ())] == 0
    assert vals[("serving_slots", ())] == 2
    # finish-reason labels
    assert vals[("serving_requests_finished_total",
                 (("reason", "length"),))] == 3


def test_engine_stats_and_registry_agree_after_chaos_restart():
    """The StatsMap satellite: engine.stats and the /metrics counters
    are the SAME values — including across a crash + slot-pool rebuild
    (reset_after_crash keeps the registry)."""
    cfg, params = _setup("control", vocab=43)  # fresh compile-cache key
    eng = ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
    )
    eng.generate(_prompts([3, 6], cfg.vocab_size, seed=3),
                 max_new_tokens=3, temperature=0.0)
    faults.arm(f"serve_raise@{eng.stats['iterations']}")
    eng.submit(_prompts([4], cfg.vocab_size, seed=4)[0], max_new_tokens=3)
    with pytest.raises(faults.FaultInjected):
        eng.run()
    eng.reset_after_crash()
    eng.run()

    snap = eng.stats.snapshot()
    assert snap["engine_restarts"] == 1
    _, samples = parse_exposition(eng.registry.render())
    vals = {n: v for n, l, v in samples if not l}
    from differential_transformer_replication_tpu.serving.engine import (
        _STAT_SPEC,
    )
    for key, (metric_name, _) in _STAT_SPEC.items():
        assert vals[metric_name] == snap[key], key


def test_engine_observability_adds_zero_recompiles():
    """Overhead pin: histograms, gauges, stats and spans are host-side
    only — the decode closure still compiles exactly once however
    requests come and go, tracer on or off."""
    cfg, params = _setup("control", vocab=41)  # fresh compile-cache key
    serving = ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=8)
    eng = ServingEngine(params, cfg, serving)
    eng.generate(_prompts([2, 7, 5], cfg.vocab_size, seed=5),
                 max_new_tokens=4, temperature=0.0)
    baseline = eng.compile_stats()
    assert baseline["decode"] == 1

    class _CountingTracer:
        def __init__(self):
            self.spans = 0

        def span(self, name, **a):
            self.spans += 1
            return NOOP_TRACER.span(name)

        instant = counter = complete = flush = close = staticmethod(
            lambda *a, **k: None
        )

    tracer = _CountingTracer()
    eng2 = ServingEngine(params, cfg, serving, tracer=tracer)
    eng2.generate(_prompts([4, 9, 3, 6], cfg.vocab_size, seed=6),
                  max_new_tokens=5, temperature=0.7, top_k=3, seed=11)
    assert tracer.spans > 0  # instrumentation actually ran
    assert eng2.compile_stats() == baseline  # zero new compiles


def test_http_metrics_endpoint_round_trip():
    """GET /metrics on a live server returns valid Prometheus text
    exposition including the TTFT/ITL histograms and slot gauges (the
    acceptance criterion)."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
    ))
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({
                "prompt_ids": _prompts([5], cfg.vocab_size, seed=7)[0],
                "max_new_tokens": 4, "temperature": 0.0,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ) as r:
            assert r.status == 200
            ctype = r.headers["Content-Type"]
            body = r.read().decode("utf-8")
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        types, samples = parse_exposition(body)
        assert types["serving_ttft_seconds"] == "histogram"
        assert types["serving_itl_seconds"] == "histogram"
        assert types["serving_slot_occupancy"] == "gauge"
        assert_histogram_valid(samples, "serving_ttft_seconds")
        assert_histogram_valid(samples, "serving_itl_seconds")
        vals = {n: v for n, l, v in samples if not l}
        assert vals["serving_ttft_seconds_count"] >= 1
        assert vals["serving_requests_completed_total"] == 1
        # /health still carries the dict view of the SAME counters
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert health["stats"]["completed"] == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.close()


def test_stats_snapshot_is_consistent_under_load():
    """The locking satellite: /health-style snapshots taken WHILE the
    engine thread hammers the counters never tear (every value is a
    plausible monotone int, never a half-written update)."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg,
        ServingConfig(num_slots=2, prefill_chunk=8, prefill_budget=16),
    ))
    stop = threading.Event()
    seen = []
    errors = []

    def snapshotter():
        last = {}
        while not stop.is_set():
            snap = client.stats
            for k, v in snap.items():
                if not isinstance(v, int) or v < last.get(k, 0):
                    errors.append((k, v, last.get(k)))
            last = {k: max(v, last.get(k, 0)) for k, v in snap.items()}
            seen.append(snap)

    t = threading.Thread(target=snapshotter, daemon=True)
    t.start()
    try:
        outs = client.generate_batch(
            _prompts([3, 8, 5, 6], cfg.vocab_size, seed=8),
            max_new_tokens=6, temperature=0.0, timeout=120,
        )
        assert len(outs) == 4
    finally:
        stop.set()
        t.join(timeout=10)
        client.close()
    assert not errors, errors[:5]
    assert seen and seen[-1]["completed"] <= 4


# -- sidecar exporter ---------------------------------------------------


def test_sidecar_metrics_server_round_trip():
    reg = Registry()
    reg.counter("train_iterations_total", "Steps.").inc(5)
    server = start_metrics_server(reg, port=0, host="127.0.0.1")
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ) as r:
            assert r.status == 200
            body = r.read().decode()
        types, samples = parse_exposition(body)
        assert types["train_iterations_total"] == "counter"
        assert ("train_iterations_total", {}, 5.0) in samples
        # unknown paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=30
            )
    finally:
        server.shutdown()
        server.server_close()


# -- introspection ------------------------------------------------------


class TestIntrospection:
    def test_family_lambda_shapes(self):
        for kind, expect in (("control", None), ("diff", (2,)),
                             ("ndiff", (2, 3))):
            cfg, params = _setup(kind)
            out = jax.device_get(make_param_summary(cfg)(params))
            if expect is None:
                assert "lambdas" not in out
            else:
                assert np.asarray(out["lambdas"]).shape == expect
            assert np.asarray(out["param_norms"]["blocks"]).shape == (2,)

    def test_zero_init_lambda_equals_schedule(self):
        """Fresh params have zero lambda vectors, so the effective
        lambda IS the init schedule (diff) — the paper's t=0 point."""
        from differential_transformer_replication_tpu.ops.lambdas import (
            lambda_init_schedule,
        )

        cfg, params = _setup("diff")
        lams = np.asarray(
            jax.device_get(make_param_summary(cfg)(params))["lambdas"]
        )
        for li in range(2):
            assert abs(lams[li] - lambda_init_schedule(li + 1)) < 1e-6

    def test_lambda_record_key_contract(self):
        cfg, params = _setup("ndiff")
        out = jax.device_get(make_param_summary(cfg)(params))
        rec = lambda_record(out, cfg, grad_norms=np.ones(4))
        assert "lambda_l1_t0" in rec and "lambda_l2_t2" in rec
        assert "lambda_init_l1" in rec
        assert {"param_norm_embed", "param_norm_l1", "param_norm_l2",
                "param_norm_head"} <= set(rec)
        assert {"grad_norm_embed", "grad_norm_l1", "grad_norm_l2",
                "grad_norm_head"} <= set(rec)
        json.dumps(rec)  # JSONL-safe


# -- trainer integration ------------------------------------------------


def _train_cfg(tmp_path, kind="diff", **kw):
    defaults = dict(
        vocab_size=256, dataset="synthetic", num_train_samples=200,
        micro_batch_size=4, grad_acc_steps=1, max_iters=10,
        eval_interval=5, eval_iters=2, log_interval=5,
        learning_rate=3e-3, min_lr=3e-4, warmup_iters=5,
        control_head_multiplier=1,
        tokenizer_dir=str(tmp_path / "tokenizer"),
        checkpoint_path=str(tmp_path / "ckpt"),
        last_checkpoint_path=str(tmp_path / "last_ckpt"),
        metrics_path=str(tmp_path / "metrics.jsonl"),
        trace_path=str(tmp_path / "trace.json"),
        seed=7,
    )
    return TrainConfig(
        model=ModelConfig(model=kind, **TINY_MODEL),
        **{**defaults, **kw},
    )


class TestTrainerObservability:
    def test_tiny_run_emits_telemetry_and_stays_compiled_once(
        self, tmp_path
    ):
        """One tiny diff run covers the trainer tentpole end to end:
        run-header + ts on every record, step-time/data-wait extras,
        introspection records with per-layer lambdas, a valid Chrome
        trace, and the compile-event pin at 1 (obs adds no retraces)."""
        from differential_transformer_replication_tpu.train.trainer import (
            train,
        )

        cfg = _train_cfg(tmp_path)
        train(cfg)

        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        assert lines[0]["record"] == "run_header"
        assert {"config_hash", "jax_version", "device_kind",
                "process_count", "ts"} <= set(lines[0])
        assert all("ts" in l for l in lines)
        step_lines = [l for l in lines if "step_time_ms" in l]
        assert step_lines, "no step records with obs extras"
        for rec in step_lines:
            assert rec["step_time_ms"] > 0
            assert 0.0 <= rec["data_wait_frac"] <= 1.0
            # THE overhead pin: instrumentation added zero retraces
            assert rec["compile_events"] == 1
            # no-memory-stats platforms (the suite's pinned CPU) omit
            # the key rather than logging a fake 0.0
            if rec.get("gpu_memory") is not None:
                assert rec["gpu_memory"] > 0
        intro = [l for l in lines if l.get("record") == "introspection"]
        assert len(intro) == 2  # one per eval interval
        assert {"lambda_l1", "lambda_l2", "lambda_init_l1",
                "param_norm_embed", "param_norm_l1",
                "grad_norm_l1"} <= set(intro[-1])
        # the reference zero-inits BOTH lambda vectors, so exp(lq*lk)
        # starts at a saddle (d/dlq = lk*exp(..) = 0): after 10 steps
        # the effective lambda still sits ON the init schedule — exactly
        # the kind of training pathology this introspection exists to
        # make visible from metrics.jsonl
        assert intro[-1]["lambda_l1"] == pytest.approx(
            intro[-1]["lambda_init_l1"], abs=1e-4
        )

        events = json.load(open(cfg.trace_path))
        names = {e["name"] for e in events if e["ph"] == "X"}
        assert {"data_wait", "dispatch", "eval"} <= names

    def test_control_run_logs_norms_but_no_lambdas(self, tmp_path):
        from differential_transformer_replication_tpu.train.trainer import (
            train,
        )

        cfg = _train_cfg(tmp_path, kind="control", trace_path=None,
                         max_iters=5, eval_interval=5)
        train(cfg)
        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        intro = [l for l in lines if l.get("record") == "introspection"]
        assert intro
        assert not any(k.startswith("lambda_") for k in intro[-1])
        assert "param_norm_l1" in intro[-1]


# -- report tools -------------------------------------------------------


class TestReportTools:
    def _write_stream(self, path):
        recs = [
            {"record": "run_header", "ts": 1.0, "config_hash": "abc",
             "jax_version": "0", "device_kind": "cpu", "process_count": 1},
            {"iter": 5, "loss": 5.0, "learning_rate": 1e-3, "ts": 2.0,
             "step_time_ms": 80.0, "data_wait_frac": 0.1,
             "compile_events": 1, "skipped_steps": 0, "rollbacks": 0,
             "tokens_per_sec": 1000.0},
            {"iter": 5, "train_loss": 5.0, "val_loss": 5.1, "ts": 2.5},
            {"record": "introspection", "iter": 5, "ts": 2.6,
             "lambda_l1": 0.21, "lambda_init_l1": 0.2,
             "param_norm_embed": 3.0, "param_norm_l1": 2.0,
             "param_norm_head": 1.0},
            {"iter": 10, "loss": 4.0, "learning_rate": 5e-4, "ts": 3.0,
             "step_time_ms": 90.0, "data_wait_frac": 0.2,
             "compile_events": 1, "skipped_steps": 1, "rollbacks": 0,
             "tokens_per_sec": 1100.0},
        ]
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
            fh.write('{"torn line')  # killed-run tail must not crash

    def test_metrics_report_summary_and_check(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        self._write_stream(path)
        r = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "metrics_report.py"),
             path, "--check", "--require-loss-decrease",
             "--max-skipped", "1", "--max-compile-events", "1"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        summary = json.loads(r.stdout)
        assert summary["loss_first"] == 5.0
        assert summary["loss_last"] == 4.0
        assert summary["step_time_ms_p50"] == 80.0
        assert summary["skipped_steps_total"] == 1
        assert summary["run_headers"] == 1

    def test_metrics_report_check_fails_on_bad_run(self, tmp_path):
        path = str(tmp_path / "bad.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps({"iter": 5, "loss": 4.0,
                                 "learning_rate": 1e-3}) + "\n")
            fh.write(json.dumps({"iter": 10, "loss": 5.0,
                                 "learning_rate": 1e-3}) + "\n")
        r = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "metrics_report.py"),
             path, "--check", "--require-loss-decrease"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 1
        assert "loss did not decrease" in r.stderr

    def test_lambda_report_ascii(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        self._write_stream(path)
        r = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "lambda_report.py"),
             path, "--ascii"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        assert "L1" in r.stdout and "0.2100" in r.stdout

    def test_lambda_report_no_lambdas_is_clean(self, tmp_path):
        path = str(tmp_path / "empty.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps({"iter": 1, "loss": 1.0}) + "\n")
        r = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "lambda_report.py"),
             path],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0
        assert "no lambda records" in r.stdout


# -- MetricLogger satellites -------------------------------------------


class TestMetricLogger:
    def test_device_memory_none_or_positive(self):
        """The satellite contract: either real stats (positive MB) or
        None — never a fabricated 0.0. The suite's conftest pins the
        CPU backend, where memory_stats() is None."""
        from differential_transformer_replication_tpu.train.metrics import (
            device_memory_mb,
        )

        mem = device_memory_mb()
        assert mem is None or mem > 0

    def test_records_carry_ts_and_omit_memory(self, tmp_path):
        from differential_transformer_replication_tpu.train.metrics import (
            MetricLogger,
        )

        cfg = _train_cfg(tmp_path, metrics_path=str(tmp_path / "x.jsonl"))
        logger = MetricLogger(cfg)
        t0 = time.time()
        logger.log_step(5, 1.25, 1e-3, tokens_per_sec=10.0,
                        extra={"custom": 1})
        logger.log_eval(5, 1.2, 1.3)
        logger.log_record({"record": "introspection", "iter": 5})
        logger.finish()
        lines = [json.loads(l) for l in open(cfg.metrics_path)]
        assert lines[0]["record"] == "run_header"
        step = lines[1]
        assert step["custom"] == 1
        if "gpu_memory" in step:  # only on platforms with memory stats
            assert step["gpu_memory"] > 0
        for rec in lines:
            assert abs(rec["ts"] - t0) < 60
        assert lines[3]["record"] == "introspection"
