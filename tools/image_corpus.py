"""Extract genuine English prose bundled in this container image.

The reference trains on TinyStories (train.py:155), which this
zero-egress image cannot download; the synthetic grammar fallback
(data/corpus.py) has a ~4-PPL entropy floor that cannot separate model
families without the overfit protocol. This tool harvests the REAL
English text the image does carry — no network, no generation:

  1. package README bodies from ``*.dist-info/METADATA`` (~3.4 MB raw),
  2. ``*.md`` / ``*.rst`` docs shipped inside site-packages,
  3. Python docstrings across the major installed libraries, parsed
     with ``ast`` (tensorflow/torch/scipy/sklearn/... ship ~200 MB of
     sources whose docstrings are genuine technical prose).

Cleaning: markdown/rst markup, code blocks, doctest lines, parameter
tables and underline rules are stripped; lines must look like sentences
(>= 4 words, predominantly ASCII letters, not code-shaped); repeated
paragraphs (license boilerplate, copied README sections) are deduped by
normalized hash. Output is one DOCUMENT per line — exactly the
file-dataset format ``data/corpus.py:load_corpus_resolved`` consumes —
so the full pipeline (BPE tokenizer + windows + trainer + ppl_gap) runs
on it unchanged:

    python tools/image_corpus.py --out image_corpus.txt
    python tools/ppl_gap.py --dataset image_corpus.txt ...

This is technical/documentation English, not children's stories — a
different register than TinyStories, but real natural language with
real long-range structure, which is the property the synthetic grammar
lacks. Provenance is printed per source class.
"""

from __future__ import annotations

import argparse
import ast
import glob
import hashlib
import os
import re
import sys

def _site_packages() -> str:
    import site

    for p in site.getsitepackages():
        if p.endswith("site-packages") and os.path.isdir(p):
            return p
    raise RuntimeError("no site-packages directory found")


SITE = _site_packages()

def _discover_packages(base=None) -> tuple:
    """Every REGULAR top-level package directory under ``base`` (default
    site-packages; has an __init__.py) — namespace packages and
    single-file modules are skipped, which is fine for a corpus: the big
    scientific libraries that dominate by volume are all regular
    packages. For stdlib roots (no __init__.py convention differences)
    any directory with .py files qualifies."""
    loose_ok = base is not None and base != SITE  # stdlib roots only
    base = base or SITE
    pkgs = []
    for name in sorted(os.listdir(base)):
        if loose_ok and name in ("site-packages", "dist-packages"):
            # a stdlib root (…/lib/python3.X) CONTAINS site-packages;
            # harvesting it again here would double-read gigabytes and
            # mislabel its provenance as stdlib
            continue
        d = os.path.join(base, name)
        if os.path.isdir(d) and (
            os.path.exists(os.path.join(d, "__init__.py"))
            or (loose_ok and glob.glob(os.path.join(d, "*.py")))
        ):
            pkgs.append(name)
    return tuple(pkgs)

_CODEY = re.compile(
    r"(^\s*(>>>|\.\.\.|def |class |import |from |return |@|\$|\.\. )|::$"
    r"|[{}<>]{2}|={2,}|-{4,}|\|.*\||^\s*[-=~^#*_.]{3,}\s*$)"
)
_BULLET = re.compile(r"^\s*([-*+•]|\d+[.)])\s+")
_MD_NOISE = re.compile(r"(!\[|\]\(http|<[a-zA-Z/][^>]*>|`{3})")
_PARAM_ROW = re.compile(r"^\s*\w+\s*:\s*\S+")  # numpydoc "name : type"


def _prose_line(raw: str) -> str | None:
    """The cleaned line if it reads as English prose, else None."""
    line = raw.rstrip()
    if _MD_NOISE.search(line) or _CODEY.search(line):
        return None
    line = _BULLET.sub("", line).strip()
    line = re.sub(r"[`*_]{1,2}([^`*_]+)[`*_]{1,2}", r"\1", line)  # emphasis
    line = re.sub(r"\[([^\]]+)\]\([^)]*\)", r"\1", line)  # md links
    if len(line.split()) < 4:
        return None
    if _PARAM_ROW.match(line) and len(line.split()) < 8:
        return None
    letters = sum(c.isalpha() or c in " ,.;:'\"()-?!" for c in line)
    if letters / len(line) < 0.85:
        return None
    if not line[:1].isascii() or sum(c.isascii() for c in line) / len(line) < 0.97:
        return None
    return line


def _paragraphs(text: str):
    """Prose paragraphs (joined consecutive prose lines >= 120 chars)."""
    cur = []
    for raw in text.splitlines():
        line = _prose_line(raw)
        if line:
            cur.append(line)
        else:
            if cur:
                para = " ".join(cur)
                if len(para) >= 120:
                    yield para
            cur = []
    if cur:
        para = " ".join(cur)
        if len(para) >= 120:
            yield para


class Corpus:
    def __init__(self):
        self.seen = set()
        self.docs = []
        self.stats = {}

    def add_document(self, text: str, source_class: str, max_doc_chars: int = 2000):
        """Split a file's prose into fresh paragraphs, then pack them into
        documents of TinyStories-like size (one output line each)."""
        fresh = []
        for para in _paragraphs(text):
            key = hashlib.md5(
                re.sub(r"\W+", "", para.lower()).encode()
            ).hexdigest()
            if key in self.seen:
                continue
            self.seen.add(key)
            fresh.append(para)
        if not fresh:
            return
        doc, n = [], 0
        for para in fresh:
            doc.append(para)
            n += len(para)
            if n >= max_doc_chars:
                self._emit(doc, source_class)
                doc, n = [], 0
        if doc:
            self._emit(doc, source_class)

    def _emit(self, paras, source_class):
        text = " ".join(paras).replace("\n", " ").strip()
        self.docs.append(text)
        s = self.stats.setdefault(source_class, {"docs": 0, "chars": 0})
        s["docs"] += 1
        s["chars"] += len(text)


def harvest_metadata(corpus: Corpus) -> None:
    for path in sorted(glob.glob(os.path.join(SITE, "*.dist-info", "METADATA"))):
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                raw = f.read()
        except OSError:
            continue
        # README body follows the first blank line of the RFC-822 header
        body = raw.split("\n\n", 1)
        corpus.add_document(body[1] if len(body) == 2 else "", "metadata_readme")


def harvest_docs(corpus: Corpus) -> None:
    pats = [os.path.join(SITE, "**", f"*.{ext}") for ext in ("md", "rst")]
    pats.append(os.path.join(SITE, "pygame", "docs", "**", "*.rst.txt"))
    for pat in pats:
        for path in sorted(glob.glob(pat, recursive=True)):
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    corpus.add_document(f.read(), "bundled_docs")
            except OSError:
                continue


def harvest_docstrings(corpus: Corpus, packages=None, root_dir=None, tag="") -> None:
    """Docstrings AND source-comment prose from every .py under each
    package of ``root_dir`` (default: site-packages). Comments (runs of
    full-line ``#`` lines, markers stripped) are genuine technical
    English at ~1-3% of source volume — across the ~5.7 GB of installed
    Python they roughly double the harvest (round-4 corpus extension,
    VERDICT r3 item 4)."""
    base = root_dir or SITE
    targets = list(
        packages if packages is not None else _discover_packages(base)
    )
    if root_dir is not None and packages is None:
        # stdlib roots keep most of their docstring prose in SINGLE-FILE
        # top-level modules (argparse.py, typing.py, ...), not packages —
        # harvest them as one pseudo-package
        targets.append(".")
    for pkg in targets:
        root = os.path.join(base, pkg)
        if not os.path.isdir(root):
            continue
        pattern = (
            os.path.join(root, "*.py")
            if pkg == "."
            else os.path.join(root, "**", "*.py")
        )
        for path in sorted(glob.glob(pattern, recursive=True)):
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    src = f.read()
            except OSError:
                continue
            try:
                tree = ast.parse(src)
            except (SyntaxError, ValueError):
                tree = None
            chunks = []
            if tree is not None:
                for node in ast.walk(tree):
                    if isinstance(
                        node,
                        (ast.Module, ast.ClassDef, ast.FunctionDef,
                         ast.AsyncFunctionDef),
                    ):
                        ds = ast.get_docstring(node, clean=True)
                        if ds:
                            chunks.append(ds)
            if chunks:
                corpus.add_document(
                    "\n\n".join(chunks), f"docstrings{tag}:{pkg}"
                )
            comments = _comment_blocks_py(src)
            if comments:
                corpus.add_document(comments, f"py_comments{tag}:{pkg}")


_PY_COMMENT = re.compile(r"^\s*#\s?(.*)$")


def _comment_runs(src: str, line_re) -> list:
    """Runs of consecutive lines matching ``line_re`` (marker stripped by
    its group 1), one block string per run."""
    blocks, cur = [], []
    for raw in src.splitlines():
        m = line_re.match(raw)
        if m:
            cur.append(m.group(1))
        else:
            if cur:
                blocks.append("\n".join(cur))
                cur = []
    if cur:
        blocks.append("\n".join(cur))
    return blocks


def _comment_blocks_py(src: str) -> str:
    """Runs of full-line ``#`` comments as blank-line-separated blocks,
    markers stripped (shebangs, coding cookies, and linter pragmas fall
    out in _prose_line's code-shape filter downstream)."""
    return "\n\n".join(_comment_runs(src, _PY_COMMENT))


_C_BLOCK = re.compile(r"/\*(.*?)\*/", re.S)
_C_LINE = re.compile(r"^\s*//[/!]?\s?(.*)$")
_C_STAR = re.compile(r"^\s*\*+\s?")
_C_EXTS = (".h", ".hpp", ".hh", ".cc", ".cpp", ".cu", ".cuh", ".proto")


def harvest_c_comments(corpus: Corpus, root_dir=None) -> None:
    """Comment prose from the C/C++/CUDA/proto sources the image's
    Python packages bundle (torch/include, tensorflow/include, ... —
    ~500 MB of headers whose /* doc blocks */ and // line runs are real
    API documentation English)."""
    base = root_dir or SITE
    for pkg in _discover_packages(base):
        root = os.path.join(base, pkg)
        paths = [
            os.path.join(dirpath, name)
            for dirpath, _, names in os.walk(root)
            for name in names
            if name.endswith(_C_EXTS)
        ]  # one tree walk, not one recursive glob per extension
        for path in sorted(paths):
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    src = f.read()
            except OSError:
                continue
            blocks = []
            for m in _C_BLOCK.finditer(src):
                body = "\n".join(
                    _C_STAR.sub("", line) for line in m.group(1).splitlines()
                )
                blocks.append(body)
            blocks.extend(_comment_runs(src, _C_LINE))
            if blocks:
                corpus.add_document(
                    "\n\n".join(blocks), f"c_comments:{pkg}"
                )


def harvest_share_doc(corpus: Corpus, root="/usr/share/doc") -> None:
    """Debian package docs: README/changelog/NEWS prose (gzipped or
    plain). License boilerplate repeats across packages and dies in the
    paragraph dedup."""
    import gzip

    for path in sorted(
        glob.glob(os.path.join(root, "**", "*"), recursive=True)
    ):
        name = os.path.basename(path).lower()
        if not os.path.isfile(path):
            continue
        if not any(
            name.startswith(p)
            for p in ("readme", "changelog", "news", "copyright")
        ):
            continue
        try:
            if name.endswith(".gz"):
                with gzip.open(path, "rt", encoding="utf-8", errors="ignore") as f:
                    raw = f.read(4 * 1024 * 1024)
            else:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    raw = f.read(4 * 1024 * 1024)
        except (OSError, EOFError):  # truncated .gz raises EOFError
            continue
        corpus.add_document(raw, "share_doc")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="image_corpus.txt")
    p.add_argument("--max-mb", type=float, default=192.0,
                   help="cap the output size; applied AFTER the shuffle, so "
                        "the cap drops a uniformly random subset of documents "
                        "across all source classes (the per-class stats below "
                        "are counted at harvest time, before any cap)")
    p.add_argument("--shuffle-seed", type=int, default=1337,
                   help="document shuffle seed (<0 disables). Harvest order "
                        "clusters by package, so an UNshuffled stream makes "
                        "the trainer's last-10%% val split a different "
                        "distribution than train (measured: both families "
                        "memorize train and fail val equally); shuffling "
                        "makes the split i.i.d. over sources")
    args = p.parse_args()

    corpus = Corpus()
    harvest_metadata(corpus)
    harvest_docs(corpus)
    harvest_docstrings(corpus)
    # round-4 extensions (VERDICT r3 item 4): source comments across the
    # installed Python, the stdlib trees, the bundled C/C++/CUDA headers,
    # and the Debian doc tree
    for std_root in sorted(glob.glob("/usr/lib/python3.*")) + sorted(
        glob.glob(os.path.expanduser("~/.pyenv/versions/*/lib/python3.*"))
    ):
        if os.path.isdir(std_root):
            tag = ":stdlib" + std_root.rsplit("python", 1)[-1]
            harvest_docstrings(corpus, root_dir=std_root, tag=tag)
    harvest_c_comments(corpus)
    harvest_share_doc(corpus)

    if args.shuffle_seed >= 0:
        import random

        random.Random(args.shuffle_seed).shuffle(corpus.docs)

    total = sum(len(d) for d in corpus.docs)
    if total / 1e6 > args.max_mb:
        keep, acc = [], 0
        for d in corpus.docs:
            if acc / 1e6 > args.max_mb:
                break
            keep.append(d)
            acc += len(d)
        dropped = len(corpus.docs) - len(keep)
        corpus.docs = keep
        total = acc
        how = (
            "randomly selected documents"
            if args.shuffle_seed >= 0
            else "documents from the TAIL of the package-clustered harvest "
                 "order (shuffle disabled — the cap is then systematically "
                 "biased against later packages)"
        )
        print(f"[image_corpus] --max-mb cap dropped {dropped} {how} "
              f"(per-class stats are pre-cap)", file=sys.stderr)

    with open(args.out, "w", encoding="utf-8") as f:
        for doc in corpus.docs:
            f.write(doc + "\n")

    print(f"wrote {len(corpus.docs)} documents, {total / 1e6:.1f} MB "
          f"(~{total // 4} tokens at 4 chars/token) to {args.out}",
          file=sys.stderr)
    for cls in sorted(corpus.stats):
        s = corpus.stats[cls]
        print(f"  {cls}: {s['docs']} docs, {s['chars'] / 1e6:.2f} MB",
              file=sys.stderr)


if __name__ == "__main__":
    main()
