#!/usr/bin/env python
"""Autonomous fleet control plane: SLO-driven autoscaling + canaried
rollout with auto-rollback (ROADMAP item 5).

Closes the loop over surfaces that already exist — the router's
``/fleet/metrics`` aggregation (PR 6), SLO burn math (PR 7,
obs/slo.py), the fleet's drain-aware restart machinery (PR 6/12), and
perf gating (PR 12, tools/perf_gate.py) — so a traffic surge or a bad
checkpoint no longer needs a human:

- **autoscaling** — poll the fleet exposition each tick, compute the
  WINDOWED burn rate of the TTFT/ITL objectives (deltas between
  polls, same math as obs/slo.py's ``slo_burn_rate_window``) plus a
  utilization score (queue depth per slot, slot occupancy, KV-page
  and host-tier pressure). Sustained burn > ``scale_up_burn`` or
  utilization above ``util_high`` scales up; sustained calm scales
  down by DRAINING the least-loaded replica (zero-loss, via
  tools/fleet.py's chaos-proven drain path). Hysteresis
  (``*_sustain`` consecutive ticks), per-direction cooldowns, and
  min/max bounds make a noisy or oscillating signal (the
  ``scale_flap`` fault point) unable to flap the fleet.
- **canaried rollout** — relaunch ONE replica on a new
  checkpoint/config (``Fleet.relaunch_replica``), split a configured
  traffic fraction to it (``Router.set_canary``), judge the window
  with the same burn math tools/slo_report.py uses and the same
  regression slack tools/perf_gate.py uses (``gate_key`` on windowed
  p95 TTFT, canary vs control), then promote or roll back to the
  exact previous argv/env — unattended. When the fleet runs
  ``--quality-telemetry`` the judge gains a MODEL-QUALITY axis
  (obs/quality.py): a canary whose ``serving_quality_drift`` exceeds
  ``canary_max_drift`` or whose constraint validity falls
  ``canary_max_validity_delta`` below control rolls back even when
  every latency gate passes — a perturbed λ or a bad quantization
  scale moves token distributions, not p95.

Every decision is a typed, reasoned JSONL event (obs/events.py) and a
registry metric (``autoscaler_*``). Decisions are BIT-REPRODUCIBLE:
``tick()`` records the extracted signals per tick (``--record``), and
``--replay`` feeds them back through the same pure ``decide()`` state
machine with the recorded clock — byte-identical decisions, no fleet
required. The poller tolerates router restarts and probe blackholes
(a failed poll is a "hold" tick, not a crash), and stale replica
bodies (``fleet_scrape_age_seconds`` beyond ``stale_after_s``) are
treated as missing, not as healthy-at-their-last-scrape.

CLI::

    # fleet + router + autoscaler in one process tree:
    python tools/autoscaler.py --replicas 1 --max-replicas 4 \
        --router-port 8000 --record scaler.jsonl -- --model control
    # offline: re-derive every decision from a recorded signal trace:
    python tools/autoscaler.py --replay scaler.jsonl

No jax import — the control plane must stay alive when the runtime it
steers is the thing misbehaving.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import signal as _signal
import sys
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."
))

from perf_gate import gate_key  # noqa: E402
from slo_report import check as slo_check  # noqa: E402

from differential_transformer_replication_tpu.config import (  # noqa: E402
    AutoscalerConfig,
)
from differential_transformer_replication_tpu.obs.events import (  # noqa: E402
    open_event_log,
)
from differential_transformer_replication_tpu.obs.registry import (  # noqa: E402
    parse_exposition,
)
from differential_transformer_replication_tpu.obs.slo import (  # noqa: E402
    burn_rate,
    good_count_under,
    histogram_from_samples,
)
from differential_transformer_replication_tpu.utils import faults  # noqa: E402


# -- signal extraction ---------------------------------------------------


@dataclass
class Signals:
    """One tick's control inputs, extracted from a fleet exposition.
    Everything ``decide()`` consumes lives here (and only here), so a
    recorded row replays to an identical decision."""

    ok: bool                          # the poll itself succeeded
    burn: Optional[float] = None      # worst windowed TTFT/ITL burn
    util: float = 0.0                 # max utilization score, 0..1
    queue_depth: float = 0.0          # fleet-wide waiting requests
    replicas_up: int = 0              # fleet_replica_up == 1 count
    stale_replicas: int = 0           # bodies older than stale_after_s

    def to_row(self) -> dict:
        return {
            "ok": self.ok, "burn": self.burn, "util": self.util,
            "queue_depth": self.queue_depth,
            "replicas_up": self.replicas_up,
            "stale_replicas": self.stale_replicas,
        }

    @classmethod
    def from_row(cls, row: dict) -> "Signals":
        return cls(
            ok=bool(row.get("ok", False)),
            burn=row.get("burn"),
            util=float(row.get("util", 0.0)),
            queue_depth=float(row.get("queue_depth", 0.0)),
            replicas_up=int(row.get("replicas_up", 0)),
            stale_replicas=int(row.get("stale_replicas", 0)),
        )


# per-replica gauges folded into the utilization score; each maps to a
# 0..1 pressure number in _replica_utils
_UTIL_GAUGES = (
    "serving_queue_depth", "serving_slots", "serving_slot_occupancy",
    "serving_kv_utilization", "serving_kv_pages_total",
    "serving_kv_pages_free", "serving_host_tier_budget_bytes",
    "serving_host_tier_bytes",
)


def _replica_utils(m: Dict[str, float]) -> List[float]:
    """One replica's pressure scores (each 0..1) from its gauges."""
    utils: List[float] = []
    slots = m.get("serving_slots", 0.0)
    if slots > 0:
        utils.append(
            min(1.0, m.get("serving_slot_occupancy", 0.0) / slots)
        )
        # queue pressure saturates once a full slot-pool's worth waits
        utils.append(
            min(1.0, m.get("serving_queue_depth", 0.0) / slots)
        )
    if "serving_kv_utilization" in m:
        utils.append(min(1.0, m["serving_kv_utilization"]))
    pages = m.get("serving_kv_pages_total", 0.0)
    if pages > 0:
        utils.append(min(1.0, max(
            0.0, 1.0 - m.get("serving_kv_pages_free", 0.0) / pages
        )))
    budget = m.get("serving_host_tier_budget_bytes", 0.0)
    if budget > 0:
        utils.append(min(
            1.0, m.get("serving_host_tier_bytes", 0.0) / budget
        ))
    return utils


class SignalExtractor:
    """Turns successive ``/fleet/metrics`` bodies into :class:`Signals`.

    Stateful only for the WINDOWED burn (previous good/count per
    objective — the same delta the SLOMonitor's
    ``slo_burn_rate_window`` gauge takes); everything else is read
    fresh per poll. Stale replicas (scrape age beyond
    ``stale_after_s``) are dropped from the utilization/up counts —
    the router already drops their bodies from the histogram
    aggregate past its own ``metrics_max_age_s`` bound."""

    def __init__(self, cfg: AutoscalerConfig):
        self.cfg = cfg
        self._prev: Dict[str, Tuple[float, float]] = {}

    def extract(self, text: str) -> Signals:
        _, samples = parse_exposition(text)
        burns: List[float] = []
        for name, hist, threshold in (
            ("ttft", "serving_ttft_seconds", self.cfg.ttft_threshold_s),
            ("itl", "serving_itl_seconds", self.cfg.itl_threshold_s),
        ):
            bounds, cumulative, count = histogram_from_samples(
                samples, hist
            )
            good = good_count_under(bounds, cumulative, threshold)
            p_good, p_count = self._prev.get(name, (0.0, 0.0))
            # a shrinking fleet (replica removed from the aggregate)
            # makes the cumulative counts step backwards: reset the
            # window rather than reporting negative traffic
            d_count = count - p_count
            d_good = good - p_good
            self._prev[name] = (good, count)
            if d_count > 0 and d_good >= 0:
                err = max(0.0, (d_count - d_good) / d_count)
                b = burn_rate(err, self.cfg.slo_target)
                if b is not None:
                    burns.append(b)
        per: Dict[str, Dict[str, float]] = {}
        ages: Dict[str, float] = {}
        up = 0
        for n, labels, v in samples:
            rep = labels.get("replica")
            if n == "fleet_scrape_age_seconds" and rep:
                ages[rep] = v
            elif n == "fleet_replica_up" and v >= 1:
                up += 1
            elif n in _UTIL_GAUGES and rep:
                per.setdefault(rep, {})[n] = v
        stale = {
            rep for rep, age in ages.items()
            if self.cfg.stale_after_s > 0 and age > self.cfg.stale_after_s
        }
        utils: List[float] = []
        queue = 0.0
        for rep, m in per.items():
            if rep in stale:
                continue  # missing, not healthy-at-its-last-scrape
            utils.extend(_replica_utils(m))
            queue += max(0.0, m.get("serving_queue_depth", 0.0))
        return Signals(
            ok=True,
            burn=max(burns) if burns else None,
            util=max(utils) if utils else 0.0,
            queue_depth=queue,
            replicas_up=up,
            stale_replicas=len(stale),
        )


# -- the decision state machine ------------------------------------------


@dataclass
class Decision:
    """One tick's ruling; ``target`` is the replica count AFTER it."""

    tick: int
    action: str                # "up" | "down" | "hold"
    reason: str
    target: int
    burn: Optional[float]
    util: float

    def to_row(self) -> dict:
        return {
            "tick": self.tick, "action": self.action,
            "reason": self.reason, "target": self.target,
            "burn": self.burn, "util": self.util,
        }


class Autoscaler:
    """Hysteresis/cooldown scaling state machine + its driver loop.

    ``decide(signals, now)`` is PURE given the instance state (no
    clock reads, no I/O, no randomness), which is what makes recorded
    traces replay bit-identically. ``tick()`` wraps it with the
    impure parts: polling, fault injection, events, metrics,
    recording, and actuation."""

    def __init__(self, cfg: AutoscalerConfig,
                 poll: Optional[Callable[[], str]] = None,
                 actuator=None,
                 registry=None,
                 events=None,
                 now_fn: Callable[[], float] = time.monotonic,
                 record_path: Optional[str] = None,
                 initial_replicas: Optional[int] = None):
        self.cfg = cfg
        self.poll = poll
        self.actuator = actuator
        self.events = events if events is not None else open_event_log(
            None, process="autoscaler"
        )
        self._now = now_fn
        self._record_path = record_path
        self._record_fh = None
        self.extractor = SignalExtractor(cfg)
        self.current = (
            initial_replicas if initial_replicas is not None
            else (actuator.replicas() if actuator is not None
                  else cfg.min_replicas)
        )
        self._tick = 0
        self._consec_high = 0
        self._consec_low = 0
        self._last_action_t: Optional[float] = None
        self._last_action: str = ""
        self._poll_failures = 0
        self._target_gauge = None
        if registry is not None:
            self._target_gauge = registry.gauge(
                "autoscaler_replicas_target",
                "Replica count the autoscaler is steering toward.",
            )
            self._burn_gauge = registry.gauge(
                "autoscaler_burn_observed",
                "Windowed SLO burn the last decision keyed on.",
            )
            self._util_gauge = registry.gauge(
                "autoscaler_util_observed",
                "Utilization score the last decision keyed on.",
            )
            self._decision_counter = registry.counter(
                "autoscaler_decisions_total",
                "Scaling decisions by action.",
                labelnames=("action",),
            )
            self._target_gauge.set(self.current)

    # -- the pure ruling ----------------------------------------------

    def decide(self, sig: Signals, now: float) -> Decision:
        tick = self._tick
        self._tick += 1
        cfg = self.cfg
        if not sig.ok:
            # a blackholed/restarting router is a HOLD, not a crash —
            # and not evidence in either direction, so the hysteresis
            # streaks freeze instead of resetting
            self._poll_failures += 1
            return Decision(
                tick, "hold",
                f"poll failed ({self._poll_failures} consecutive); "
                "holding at last-known state",
                self.current, None, 0.0,
            )
        self._poll_failures = 0
        burn, util = sig.burn, sig.util
        high = (burn is not None and burn > cfg.scale_up_burn) \
            or util > cfg.util_high
        low = (burn is None or burn < cfg.scale_down_burn) \
            and util < cfg.util_low
        self._consec_high = self._consec_high + 1 if high else 0
        self._consec_low = self._consec_low + 1 if low else 0
        since = (
            None if self._last_action_t is None
            else now - self._last_action_t
        )

        def _fmt(v):
            return "none" if v is None else f"{v:.3f}"

        basis = (f"burn={_fmt(burn)} util={util:.3f} "
                 f"queue={sig.queue_depth:.0f} "
                 f"stale={sig.stale_replicas}")
        action, reason = "hold", f"steady ({basis})"
        if high and self._consec_high >= cfg.scale_up_sustain:
            if self.current >= cfg.max_replicas:
                reason = f"pressure sustained but at max_replicas " \
                         f"({cfg.max_replicas}); {basis}"
            elif since is not None and since < cfg.cooldown_up_s:
                reason = (f"pressure sustained but in cooldown "
                          f"({since:.1f}s < {cfg.cooldown_up_s}s "
                          f"since {self._last_action}); {basis}")
            else:
                action = "up"
                reason = (f"{self._consec_high} consecutive ticks over "
                          f"burn>{cfg.scale_up_burn} or "
                          f"util>{cfg.util_high}; {basis}")
        elif low and self._consec_low >= cfg.scale_down_sustain:
            if self.current <= cfg.min_replicas:
                reason = f"calm sustained but at min_replicas " \
                         f"({cfg.min_replicas}); {basis}"
            elif since is not None and since < cfg.cooldown_down_s:
                reason = (f"calm sustained but in cooldown "
                          f"({since:.1f}s < {cfg.cooldown_down_s}s "
                          f"since {self._last_action}); {basis}")
            else:
                action = "down"
                reason = (f"{self._consec_low} consecutive ticks under "
                          f"burn<{cfg.scale_down_burn} and "
                          f"util<{cfg.util_low}; {basis}")
        if action != "hold":
            self.current += 1 if action == "up" else -1
            self._consec_high = 0
            self._consec_low = 0
            self._last_action_t = now
            self._last_action = f"scale_{action}"
        return Decision(tick, action, reason, self.current, burn, util)

    # -- the impure driver --------------------------------------------

    def _record(self, now: float, sig: Signals,
                decision: Decision) -> None:
        if self._record_path is None:
            return
        if self._record_fh is None:
            self._record_fh = open(self._record_path, "a",
                                   encoding="utf-8")
        self._record_fh.write(json.dumps({
            "tick": decision.tick, "now": now,
            "signals": sig.to_row(), "decision": decision.to_row(),
        }) + "\n")
        self._record_fh.flush()

    def tick(self) -> Decision:
        now = self._now()
        poll_error = None
        if self.poll is None:
            sig = Signals(ok=False)
            poll_error = "no poll source configured"
        else:
            try:
                sig = self.extractor.extract(self.poll())
            except Exception as e:  # router restart / blackhole / 5xx
                sig = Signals(ok=False)
                poll_error = repr(e)
        # scale_flap@A-B: an oscillating capacity signal INJECTED at
        # the signal layer (tick parity flips saturated<->idle), so the
        # recorded trace carries the flap and hysteresis must absorb
        # it; decide() itself stays fault-free and pure
        if sig.ok and faults.scale_flap_at(self._tick):
            if self._tick % 2 == 0:
                sig.burn, sig.util = 99.0, 1.0
            else:
                sig.burn, sig.util = 0.0, 0.0
        decision = self.decide(sig, now)
        self.events.emit(
            "autoscaler_decision", tick=decision.tick,
            action=decision.action, reason=decision.reason,
            target=decision.target, burn=decision.burn,
            util=decision.util, queue_depth=sig.queue_depth,
            stale_replicas=sig.stale_replicas,
            replicas_up=sig.replicas_up,
            **({"poll_error": poll_error} if poll_error else {}),
        )
        if self._target_gauge is not None:
            self._target_gauge.set(decision.target)
            if decision.burn is not None:
                self._burn_gauge.set(decision.burn)
            self._util_gauge.set(decision.util)
            self._decision_counter.inc(action=decision.action)
        self._record(now, sig, decision)
        if decision.action != "hold" and self.actuator is not None:
            try:
                if decision.action == "up":
                    self.actuator.scale_up()
                else:
                    self.actuator.scale_down()
                self.events.emit(
                    "autoscaler_scaled", action=decision.action,
                    replicas=decision.target,
                )
            except Exception as e:
                # actuation failed (mid-scale SIGKILL, drain refusal):
                # put the target back so the state machine re-earns the
                # decision instead of believing a scale that never took
                self.current = (
                    self.current - 1 if decision.action == "up"
                    else self.current + 1
                )
                self.events.emit(
                    "autoscaler_scale_failed", action=decision.action,
                    error=repr(e), replicas=self.current,
                )
        return decision

    def run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            self.tick()
            stop.wait(self.cfg.poll_interval_s)

    def close(self) -> None:
        if self._record_fh is not None:
            self._record_fh.close()
            self._record_fh = None


def replay(rows: Sequence[dict], cfg: AutoscalerConfig,
           initial_replicas: Optional[int] = None) -> List[Decision]:
    """Re-derive every decision from a recorded signal trace — same
    state machine, recorded clock, no fleet. Byte-identical output is
    the reproducibility contract tests/test_autoscaler.py pins."""
    scaler = Autoscaler(cfg, initial_replicas=initial_replicas)
    return [
        scaler.decide(Signals.from_row(row.get("signals", {})),
                      float(row.get("now", 0.0)))
        for row in rows
    ]


# -- actuation over a live fleet + in-process router ---------------------


class FleetActuator:
    """Applies scale decisions to a tools/fleet.py ``Fleet`` fronted by
    an in-process ``Router`` (the integrated-CLI topology)."""

    def __init__(self, fleet, router):
        self.fleet = fleet
        self.router = router

    def replicas(self) -> int:
        return len(self.fleet.replicas)

    def scale_up(self, n: int = 1) -> List[str]:
        urls = self.fleet.scale_up(n)
        for u in urls:
            self.router.add_replica(u)
        return urls

    def scale_down(self) -> str:
        # least-loaded victim by the ROUTER's load score (never the
        # canary mid-judgment): live-migrate its ACTIVE decodes to the
        # surviving peers (router.migrate_out — drain time is page
        # transfer, not max_new_tokens), then drain it out through the
        # fleet's zero-loss path and drop it from rotation + admission
        canary_url, _ = self.router.canary()
        scores = {r.url: r.score() for r in self.router.replicas}
        kwargs = {
            "score_of": lambda u: None if u == canary_url
            else scores.get(u),
        }
        pre_drain = getattr(self.router, "migrate_out", None)
        if pre_drain is not None:
            kwargs["pre_drain"] = pre_drain
        url = self.fleet.scale_down(**kwargs)
        self.router.remove_replica(url)
        return url


# -- canaried rollout ----------------------------------------------------


def histogram_quantile(bounds: Sequence[float],
                       cumulative: Sequence[float], count: float,
                       q: float) -> Optional[float]:
    """Smallest bucket bound covering quantile ``q`` of a (windowed)
    histogram; ``inf`` when it falls in the overflow bucket, None when
    the histogram is empty. Upper-bound honest: the true quantile is
    <= the returned edge."""
    if count <= 0:
        return None
    target = q * count
    for b, c in zip(bounds, cumulative):
        if c >= target:
            return b
    return math.inf


def _sum_samples(samples, name: str) -> Optional[float]:
    """Sum of all samples with this exact name (label children and
    per-replica gauged samples collapse); None when absent."""
    vals = [v for n, _, v in samples if n == name]
    return sum(vals) if vals else None


def _gauge_values(samples, name: str) -> List[float]:
    return [v for n, _, v in samples if n == name]


def window_stats(pairs: Sequence[Tuple[str, str]],
                 ttft_threshold_s: float, slo_target: float) -> dict:
    """TTFT + quality stats over a canary window from (before, after)
    exposition snapshots of one or more replicas: delta the cumulative
    buckets per bound (restart-safe: a counter that stepped backwards
    clamps to zero), sum across replicas, then judge the window alone.

    Quality keys (obs/quality.py; all None when the replicas do not
    run ``--quality-telemetry``): ``entropy_mean`` / ``margin_mean``
    are windowed means from the serving_token_entropy /
    serving_logit_margin histograms' ``_sum``/``_count`` deltas;
    ``drift`` is the WORST (max) finite ``serving_quality_drift``
    gauge in the after bodies (gauges are levels, not counters — the
    after snapshot IS the window's verdict); ``validity`` is the
    worst (min) ``serving_constraint_validity_rate``."""
    by_bound: Dict[float, float] = {}
    total = 0.0
    q_sums = {"entropy": [0.0, 0.0], "margin": [0.0, 0.0]}
    drift: Optional[float] = None
    validity: Optional[float] = None
    for before, after in pairs:
        _, s0 = parse_exposition(before or "")
        _, s1 = parse_exposition(after or "")
        b0, c0, n0 = histogram_from_samples(s0, "serving_ttft_seconds")
        b1, c1, n1 = histogram_from_samples(s1, "serving_ttft_seconds")
        prev = dict(zip(b0, c0))
        for b, c in zip(b1, c1):
            by_bound[b] = by_bound.get(b, 0.0) \
                + max(0.0, c - prev.get(b, 0.0))
        total += max(0.0, n1 - n0)
        for key, hist in (("entropy", "serving_token_entropy"),
                          ("margin", "serving_logit_margin")):
            sum1 = _sum_samples(s1, f"{hist}_sum")
            cnt1 = _sum_samples(s1, f"{hist}_count")
            if sum1 is None or cnt1 is None:
                continue
            sum0 = _sum_samples(s0, f"{hist}_sum") or 0.0
            cnt0 = _sum_samples(s0, f"{hist}_count") or 0.0
            q_sums[key][0] += max(0.0, sum1 - sum0)
            q_sums[key][1] += max(0.0, cnt1 - cnt0)
        for v in _gauge_values(s1, "serving_quality_drift"):
            if math.isfinite(v):
                drift = v if drift is None else max(drift, v)
            elif not math.isnan(v):  # inf = incompatible fingerprint
                drift = v
        for v in _gauge_values(s1, "serving_constraint_validity_rate"):
            if math.isfinite(v):
                validity = v if validity is None else min(validity, v)
    bounds = sorted(by_bound)
    cumulative = [by_bound[b] for b in bounds]
    good = good_count_under(bounds, cumulative, ttft_threshold_s)
    err = None if total <= 0 else max(0.0, (total - good) / total)
    return {
        "count": total,
        "error_ratio": err,
        "burn_rate": burn_rate(err, slo_target),
        "target": slo_target,
        "p95_ttft_s": histogram_quantile(bounds, cumulative, total,
                                         0.95),
        "entropy_mean": (q_sums["entropy"][0] / q_sums["entropy"][1]
                         if q_sums["entropy"][1] else None),
        "margin_mean": (q_sums["margin"][0] / q_sums["margin"][1]
                        if q_sums["margin"][1] else None),
        "drift": drift,
        "validity": validity,
    }


class _GateArgs:
    """The two attributes slo_report.check() reads."""

    def __init__(self, max_burn: float):
        self.max_burn = max_burn
        self.require_traffic = False


def judge_canary(canary: dict, control: dict,
                 cfg: AutoscalerConfig) -> Tuple[str, str]:
    """Promote-or-rollback ruling from two :func:`window_stats` dicts.
    Reuses the fleet's existing judges: slo_report's burn-gate check
    for the canary's own SLO burn, and perf_gate's regression slack
    (``gate_key``, control as baseline) for p95 TTFT. Thin evidence
    (< ``canary_min_requests`` in the window) is a ROLLBACK — an
    unjudgeable canary must not be promoted by default."""
    if canary["count"] < cfg.canary_min_requests:
        return "rollback", (
            f"inconclusive: {canary['count']:.0f} canary requests in "
            f"window (need {cfg.canary_min_requests}); refusing to "
            "promote on thin evidence"
        )
    violations = slo_check(
        {"canary_ttft": canary}, _GateArgs(cfg.canary_max_burn)
    )
    if violations:
        return "rollback", violations[0]
    c_p95 = canary.get("p95_ttft_s")
    ctl_p95 = control.get("p95_ttft_s")
    if ctl_p95 is not None and math.isfinite(ctl_p95):
        if c_p95 is None or not math.isfinite(c_p95):
            return "rollback", (
                "canary window p95 TTFT beyond the histogram range "
                f"while control served {ctl_p95:.3f}s"
            )
        verdict = gate_key(
            [{"p95_ttft_s": ctl_p95}, {"p95_ttft_s": c_p95}],
            "p95_ttft_s:lower", window=1,
            max_regress=cfg.canary_max_regress, mad_factor=0.0,
            min_history=2,
        )
        if verdict["status"] == "regressed":
            return "rollback", (
                f"canary p95 TTFT {c_p95:.3f}s regressed past control "
                f"{ctl_p95:.3f}s + {cfg.canary_max_regress:.0%} slack"
            )
    # -- quality axis (obs/quality.py) -----------------------------------
    # A canary can be latency-flat and still WRONG: a perturbed λ or a
    # bad int8 scale moves the token-quality distributions, not p95.
    # None = the fleet does not run quality telemetry (gates pass, not
    # fail-closed: quality is opt-in); NaN never reaches here (the
    # drift gauge's "no signal" degradation is 0.0).
    drift = canary.get("drift")
    if (cfg.canary_max_drift > 0 and drift is not None
            and not math.isnan(drift) and drift > cfg.canary_max_drift):
        return "rollback", (
            f"canary quality drift {drift:.3f} past budget "
            f"{cfg.canary_max_drift:.3f} (PSI vs reference "
            "fingerprint) — latency alone would have promoted"
        )
    c_validity = canary.get("validity")
    if cfg.canary_max_validity_delta > 0 and c_validity is not None:
        ctl_validity = control.get("validity")
        base = (ctl_validity
                if ctl_validity is not None
                and math.isfinite(ctl_validity) else 1.0)
        if base - c_validity > cfg.canary_max_validity_delta:
            return "rollback", (
                f"canary constraint validity {c_validity:.3f} fell "
                f"more than {cfg.canary_max_validity_delta:.3f} below "
                f"control {base:.3f} — latency alone would have "
                "promoted"
            )
    return "promote", (
        "canary inside burn, latency, and quality budgets over "
        f"{canary['count']:.0f}-request window"
    )


class CanaryController:
    """One canaried rollout: relaunch a replica on new args, split
    traffic, judge the window, promote or roll back. Unattended — a
    regressed canary (e.g. the ``canary_regress`` fault) comes back
    on its ORIGINAL argv/env with zero operator input."""

    def __init__(self, fleet, router, cfg: AutoscalerConfig,
                 events=None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 fetch: Optional[Callable[[str], str]] = None):
        self.fleet = fleet
        self.router = router
        self.cfg = cfg
        self.events = events if events is not None else open_event_log(
            None, process="canary"
        )
        self._sleep = sleep_fn
        self._fetch = fetch if fetch is not None else self._http_fetch

    @staticmethod
    def _http_fetch(url: str) -> str:
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            return r.read().decode("utf-8", "replace")

    def _ready_check(self):
        router = self.router

        def ok(r) -> bool:
            rep = next(
                (x for x in router.replicas if x.url == r.url), None
            )
            return rep is None or rep.eligible()

        return ok

    def _snapshot(self, urls: Sequence[str]) -> Dict[str, str]:
        out = {}
        for u in urls:
            try:
                out[u] = self._fetch(u)
            except OSError:
                out[u] = ""  # a dead control replica judges as empty
        return out

    def run(self, server_args: Optional[Sequence[str]] = None,
            extra_env: Optional[dict] = None,
            index: Optional[int] = None) -> dict:
        """Execute one rollout; returns the judgment record (also
        emitted as events). Zero-failed-requests is the router's job:
        the canary drains in/out through the same SIGTERM path a
        rolling restart uses, and its traffic share comes back to the
        control pool the moment ``set_canary(None)`` lands."""
        if index is None:
            index = max(r.index for r in self.fleet.replicas)
        replica = next(
            r for r in self.fleet.replicas if r.index == index
        )
        url = replica.url
        self.events.emit("canary_started", replica=index, url=url,
                         fraction=self.cfg.canary_fraction)
        old_argv, old_env = self.fleet.relaunch_replica(
            index, server_args=server_args, extra_env=extra_env,
            ready_check=self._ready_check(),
        )
        self.router.set_canary(url, self.cfg.canary_fraction)
        control_urls = [
            r.url for r in self.fleet.replicas if r.url != url
        ]
        try:
            before = self._snapshot([url] + control_urls)
            self._sleep(self.cfg.canary_window_s)
            after = self._snapshot([url] + control_urls)
        finally:
            # judgment happens OFF the split: the canary keeps serving
            # only if promoted, and a judge crash must not leave a
            # fraction of traffic pinned to an unjudged replica
            self.router.set_canary(None)
        canary_stats = window_stats(
            [(before.get(url, ""), after.get(url, ""))],
            self.cfg.ttft_threshold_s, self.cfg.slo_target,
        )
        control_stats = window_stats(
            [(before.get(u, ""), after.get(u, "")) for u in control_urls],
            self.cfg.ttft_threshold_s, self.cfg.slo_target,
        )
        verdict, reason = judge_canary(canary_stats, control_stats,
                                       self.cfg)
        self.events.emit(
            "canary_judged", replica=index, verdict=verdict,
            reason=reason, canary=canary_stats, control=control_stats,
        )
        if verdict == "promote":
            self.events.emit("canary_promoted", replica=index)
        else:
            self.fleet.relaunch_replica(
                index, argv=old_argv, env=old_env,
                ready_check=self._ready_check(),
            )
            self.events.emit("canary_rolled_back", replica=index)
        record = {
            "verdict": verdict, "reason": reason, "replica": index,
            "canary": canary_stats, "control": control_stats,
        }
        self.events.flush()
        return record


# -- CLI -----------------------------------------------------------------


def _http_poll(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")


def main() -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--replay", default=None,
                   help="re-derive decisions from a --record JSONL "
                        "trace and print them (no fleet, no clock)")
    p.add_argument("--replicas", type=int, default=1,
                   help="initial fleet size (live mode)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--scale-up-burn", type=float, default=1.0)
    p.add_argument("--scale-down-burn", type=float, default=0.5)
    p.add_argument("--up-sustain", type=int, default=3)
    p.add_argument("--down-sustain", type=int, default=6)
    p.add_argument("--cooldown-up", type=float, default=5.0)
    p.add_argument("--cooldown-down", type=float, default=15.0)
    p.add_argument("--ttft", type=float, default=1.0)
    p.add_argument("--itl", type=float, default=0.25)
    p.add_argument("--target", type=float, default=0.99)
    p.add_argument("--stale-after", type=float, default=5.0)
    p.add_argument("--canary-max-drift", type=float, default=0.25,
                   help="canary judge rolls back when the canary's "
                        "serving_quality_drift (PSI vs reference "
                        "fingerprint) exceeds this; 0 = quality drift "
                        "gate off")
    p.add_argument("--canary-max-validity-delta", type=float,
                   default=0.05,
                   help="canary judge rolls back when the canary's "
                        "constraint validity rate falls this far "
                        "below control's; 0 = gate off")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--router-port", type=int, default=8000)
    p.add_argument("--record", default=None,
                   help="append per-tick signal+decision JSONL rows "
                        "(the --replay input)")
    p.add_argument("--event-log", default=None)
    p.add_argument("--fleet-log", default=None)
    p.add_argument("server_args", nargs=argparse.REMAINDER,
                   help="-- then serving.server CLI args per replica")
    args = p.parse_args()

    cfg = AutoscalerConfig(
        poll_interval_s=args.poll_interval,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        scale_up_burn=args.scale_up_burn,
        scale_down_burn=args.scale_down_burn,
        scale_up_sustain=args.up_sustain,
        scale_down_sustain=args.down_sustain,
        cooldown_up_s=args.cooldown_up,
        cooldown_down_s=args.cooldown_down,
        ttft_threshold_s=args.ttft,
        itl_threshold_s=args.itl,
        slo_target=args.target,
        stale_after_s=args.stale_after,
        canary_max_drift=args.canary_max_drift,
        canary_max_validity_delta=args.canary_max_validity_delta,
    )

    if args.replay:
        rows = []
        with open(args.replay, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        for d in replay(rows, cfg, initial_replicas=args.replicas):
            print(json.dumps(d.to_row()))
        return 0

    from fleet import Fleet  # noqa: E402 (tools/ sibling)

    from differential_transformer_replication_tpu.config import (
        RouterConfig,
    )
    from differential_transformer_replication_tpu.serving.router import (
        Router,
        serve_router,
    )

    server_args = list(args.server_args)
    if server_args and server_args[0] == "--":
        server_args = server_args[1:]
    fleet = Fleet(args.replicas, server_args=server_args,
                  host=args.host, fleet_log=args.fleet_log)
    print(f"[autoscaler] launching {args.replicas} replicas: "
          f"{fleet.urls}", file=sys.stderr)
    fleet.start()
    router = Router(
        fleet.urls, RouterConfig(),
        events=open_event_log(args.event_log, process="router"),
    ).start()
    httpd = serve_router(router, args.host, args.router_port)
    metrics_url = (
        f"http://{args.host}:{args.router_port}/fleet/metrics"
    )
    scaler = Autoscaler(
        cfg,
        poll=lambda: _http_poll(metrics_url),
        actuator=FleetActuator(fleet, router),
        registry=router.registry,
        events=open_event_log(args.event_log, process="autoscaler"),
        record_path=args.record,
    )
    stop = threading.Event()

    def _stop_all(signum, frame):
        del frame
        print(f"[autoscaler] signal {signum}: stopping",
              file=sys.stderr)
        stop.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _stop_all)
    _signal.signal(_signal.SIGINT, _stop_all)
    loop = threading.Thread(target=scaler.run, args=(stop,),
                            name="autoscaler", daemon=True)
    loop.start()
    print(f"[autoscaler] steering {metrics_url} between "
          f"{cfg.min_replicas} and {cfg.max_replicas} replicas",
          file=sys.stderr)
    try:
        httpd.serve_forever()
    finally:
        stop.set()
        loop.join(5.0)
        httpd.server_close()
        scaler.close()
        router.close()
        router.events.close()
        fleet.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
