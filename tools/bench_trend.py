#!/usr/bin/env python
"""Render the committed bench trajectory as one machine-readable line.

The driver archives every bench round at the repo root —
``BENCH_r0*.json`` (single-chip train step: tokens/sec, vs_baseline,
mfu_6nd, and the run's final loss in the stderr ``tail``) and
``MULTICHIP_r0*.json`` (the 8-device dry-run result). This tool reads
that history and prints ONE JSON summary line, so "are we still getting
faster round over round?" is a jq expression instead of five file
opens::

    python tools/bench_trend.py                  # repo-root BENCH_r*/MULTICHIP_r*
    python tools/bench_trend.py --ascii          # + sparklines on stderr
    python tools/bench_trend.py BENCH_r0*.json   # explicit round files

Baseline math is IMPORTED from tools/perf_gate.py (median + MAD over
the trailing window) so this trend view and the CI gate judge a
trajectory identically — the summary's per-series ``baseline`` block is
exactly what ``perf_gate.py --key`` would gate the next round against.

Caveat carried in the output: rounds r01–r05 predate the PR 9–10 fused
kernels (Pallas SwiGLU/norm, decode attention, int8 KV) — their numbers
measure the pre-kernel hot path, so the next hardware round is expected
to step, not drift. Stdlib only.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_gate import baseline_stats  # noqa: E402  (shared gate math)

PREDATE_NOTE = (
    "rounds r01-r05 predate the PR 9-10 fused kernels "
    "(Pallas SwiGLU/norm, decode attention, int8 KV): their numbers "
    "measure the pre-kernel hot path"
)

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(xs: List[Optional[float]]) -> str:
    vals = [x for x in xs if x is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for x in xs:
        if x is None:
            out.append(" ")
        else:
            out.append(_SPARK[int((x - lo) / span * (len(_SPARK) - 1))])
    return "".join(out)


def _round_of(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else 0


def load_round(path: str) -> Optional[dict]:
    """One round archive -> its summary row, or None for an absent,
    empty, or torn file (a killed bench run's half-written archive
    must degrade to 'that round is missing', never a traceback)."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    out = {
        "round": _round_of(path),
        "file": os.path.basename(path),
        "rc": doc.get("rc") if isinstance(doc, dict) else None,
    }
    if isinstance(parsed, dict):
        out["value"] = parsed.get("value")
        out["vs_baseline"] = parsed.get("vs_baseline")
        out["mfu_6nd"] = parsed.get("mfu_6nd")
    # the run's final training loss only appears in the archived stderr
    # tail ("loss=9.0810"); a missing tail degrades to None
    tail = doc.get("tail", "") if isinstance(doc, dict) else ""
    m = re.search(r"loss=([0-9.]+)", tail or "")
    out["loss"] = float(m.group(1).rstrip(".")) if m else None
    return out


def _series(rounds: List[dict], key: str) -> List[Optional[float]]:
    return [r.get(key) for r in rounds]


def _baseline(series: List[Optional[float]], window: int) -> Optional[dict]:
    vals = [v for v in series if v is not None]
    if len(vals) < 2:
        return None
    med, noise = baseline_stats(vals[-window:])
    return {"median": round(med, 4), "mad": round(noise, 4),
            "window_n": min(window, len(vals))}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("files", nargs="*",
                   help="round archives (default: BENCH_r*.json next "
                        "to the repo root, MULTICHIP_r*.json alongside)")
    p.add_argument("--multichip", action="append", default=None,
                   help="MULTICHIP round archives (default: globbed "
                        "beside the BENCH files)")
    p.add_argument("--window", type=int, default=5,
                   help="trailing rounds forming the baseline block "
                        "(perf_gate math)")
    p.add_argument("--ascii", action="store_true",
                   help="also draw per-series sparklines on stderr")
    args = p.parse_args()

    def _insufficient(detail):
        # bootstrap state (absent/empty/torn history): one JSON line +
        # exit 2, the same contract as tools/perf_gate.py — never a
        # traceback, distinguishable from a real trend failure
        print(json.dumps({
            "metric": "bench_trend",
            "status": "insufficient_history",
            "detail": detail,
            "hint": "insufficient history, run a bench round "
                    "(bench.py) to bootstrap the trajectory",
            "ok": False,
        }))
        print("CHECK FAILED: insufficient history, run a bench round",
              file=sys.stderr)
        return 2

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench_files = args.files or sorted(
        glob.glob(os.path.join(root, "BENCH_r*.json")), key=_round_of
    )
    if not bench_files:
        return _insufficient("no BENCH_r*.json rounds found")
    bench_files = sorted(bench_files, key=_round_of)
    multichip_files = sorted(
        args.multichip if args.multichip is not None else
        glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(bench_files[0])) or ".",
            "MULTICHIP_r*.json",
        )),
        key=_round_of,
    )

    rounds = [r for r in (load_round(p_) for p_ in bench_files)
              if r is not None]
    if not rounds:
        return _insufficient(
            f"{len(bench_files)} BENCH file(s) named but none "
            "readable (absent, empty, or torn)"
        )
    series = {
        key: _series(rounds, key)
        for key in ("value", "vs_baseline", "mfu_6nd", "loss")
    }
    multichip_ok = []
    for p_ in multichip_files:
        try:
            with open(p_, encoding="utf-8") as fh:
                doc = json.load(fh)
            multichip_ok.append(bool(doc.get("ok")))
        except (OSError, json.JSONDecodeError):
            multichip_ok.append(False)

    summary = {
        "metric": "bench_trend",
        "rounds": [r["round"] for r in rounds],
        "tokens_per_sec": series["value"],
        "vs_baseline": series["vs_baseline"],
        "mfu_6nd": series["mfu_6nd"],
        "loss": series["loss"],
        "multichip_ok": multichip_ok,
        # perf_gate's exact baseline math over the same window: what
        # the NEXT round will be judged against
        "baseline": {
            key: _baseline(series[key], args.window)
            for key in ("value", "vs_baseline", "mfu_6nd")
        },
        "note": PREDATE_NOTE,
    }
    print(json.dumps(summary))
    if args.ascii:
        for key in ("value", "vs_baseline", "mfu_6nd", "loss"):
            vals = series[key]
            shown = [f"{v:g}" if v is not None else "-" for v in vals]
            print(f"[bench_trend] {key:14s} {sparkline(vals)}  "
                  f"({' '.join(shown)})", file=sys.stderr)
        print(f"[bench_trend] NOTE: {PREDATE_NOTE}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
