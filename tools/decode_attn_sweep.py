"""Decode-attention kernel sweep — ffn_sweep.py's sibling for
ops/decode_attention.py.

Times the fused Pallas single-query (slot-pool) attention kernel against
its plain-XLA twin across the axes that matter for serving capacity:

  - cache length M (the ring/block size — the HBM stream per slot),
  - slot count B (the batched pool width),
  - model family (control S=1, diff S=2, ndiff S=N combine streams),
  - KV dtype (bf16/float vs per-head-scale int8 with in-kernel dequant).

One JSON line per (impl, family, B, M, kv_dtype) case with ms/step and
the max |pallas - xla| parity delta for that case's inputs, e.g.::

    {"impl": "pallas", "model": "diff", "batch": 8, "cache_len": 512,
     "kv_dtype": "int8", "ms_per_step": ..., "max_abs_diff": ...}

Timing is readback-synced like flash_sweep.py/ffn_sweep.py
(block_until_ready returns early on the axon platform, BASELINE.md).

    python tools/decode_attn_sweep.py --batches 8 32 --cache-lens 512 2048
    python tools/decode_attn_sweep.py --smoke   # tier-1 CI gate: tiny
                                                # shapes, interpret-mode
                                                # kernel, parity-asserted
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp


def _sync(out) -> None:
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(x.astype(jnp.float32))), out
    )


_FAMILY_STREAMS = {"control": 1, "diff": 2, "ndiff": 4}


def _case_inputs(model, B, M, H, d, kv_dtype, dtype, seed=0):
    """Random pool-shaped decode inputs: per-stream queries, a ring
    cache filled to staggered per-row depths (like a live slot pool),
    quantized when kv_dtype == "int8"."""
    from differential_transformer_replication_tpu.ops.decode_attention import (
        quantize_kv,
    )

    S = _FAMILY_STREAMS[model]
    dv = d if model == "control" else 2 * d
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    qs = jax.random.normal(ks[0], (S, B, H, d), dtype)
    k = jax.random.normal(ks[1], (S, B, H, M, d), dtype)
    v = jax.random.normal(ks[2], (B, H, M, dv), dtype)
    # staggered fill depths across rows, full cache on row 0; clamp at
    # 0 (min one visible slot) — B > M/2 strides below the ring floor,
    # where the reference's all-masked softmax is NaN
    pos = jnp.maximum(
        M - 1 - (jnp.arange(B) * max(1, M // (2 * B))), 0
    ).astype(jnp.int32)
    coeffs = jax.random.uniform(
        ks[3], (S, H), jnp.float32, minval=-1.0, maxval=1.0
    )
    scales = None
    if kv_dtype == "int8":
        kq, ksc = quantize_kv(k)
        vq, vsc = quantize_kv(v)
        k, v, scales = kq, vq, (ksc, vsc)
    return qs, k, v, pos, coeffs, scales


def bench_case(model, B, M, H, d, kv_dtype, steps, dtype):
    """One sweep case: returns [(impl, seconds/step)] plus the parity
    delta between the two impls on identical inputs."""
    from differential_transformer_replication_tpu.ops.decode_attention import (
        decode_attention,
        decode_attention_reference,
        dequantize_kv,
    )

    qs, k, v, pos, coeffs, scales = _case_inputs(
        model, B, M, H, d, kv_dtype, dtype
    )

    if scales is None:

        def fused(qs, k, v, pos, coeffs):
            return decode_attention(qs, k, v, pos, coeffs)

        def reference(qs, k, v, pos, coeffs):
            return decode_attention_reference(qs, k, v, pos, coeffs)

        args = (qs, k, v, pos, coeffs)
    else:
        ksc, vsc = scales

        def fused(qs, k, v, pos, coeffs, ksc, vsc):
            return decode_attention(
                qs, k, v, pos, coeffs, k_scale=ksc, v_scale=vsc
            )

        def reference(qs, k, v, pos, coeffs, ksc, vsc):
            return decode_attention_reference(
                qs, dequantize_kv(k, ksc, qs.dtype),
                dequantize_kv(v, vsc, qs.dtype), pos, coeffs,
            )

        args = (qs, k, v, pos, coeffs, ksc, vsc)

    out = {}
    results = {}
    for impl, fn in (("pallas", fused), ("xla", reference)):
        jf = jax.jit(fn)
        results[impl] = jf(*args)
        _sync(results[impl])  # compile + warm
        t0 = time.perf_counter()
        r = None
        for _ in range(steps):
            r = jf(*args)
        _sync(r)
        out[impl] = (time.perf_counter() - t0) / steps
    diff = float(
        jnp.max(
            jnp.abs(
                results["pallas"].astype(jnp.float32)
                - results["xla"].astype(jnp.float32)
            )
        )
    )
    return out, diff


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--models", nargs="+",
                   default=["control", "diff", "ndiff"],
                   choices=["control", "diff", "ndiff"])
    p.add_argument("--batches", type=int, nargs="+", default=[8, 32],
                   help="slot-pool widths")
    p.add_argument("--cache-lens", type=int, nargs="+",
                   default=[512, 2048], help="ring cache lengths M")
    p.add_argument("--kv-dtypes", nargs="+", default=["bf16", "int8"],
                   choices=["bf16", "int8"])
    p.add_argument("--n-head", type=int, default=4)
    p.add_argument("--head-size", type=int, default=96,
                   help="per-head q/k width (the diff recipe's 96)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--smoke", action="store_true",
                   help="tiny interpret-mode shapes + parity assertions; "
                        "seconds on CPU (the tier-1 gate)")
    p.add_argument("--out", default=None,
                   help="also append the JSON lines to this file")
    args = p.parse_args()

    if args.smoke:
        args.batches, args.cache_lens = [4], [32]
        args.n_head, args.head_size = 2, 16
        args.steps, args.dtype = 2, "float32"

    rows = []
    for model in args.models:
        for B in args.batches:
            for M in args.cache_lens:
                for kvd in args.kv_dtypes:
                    secs, diff = bench_case(
                        model, B, M, args.n_head, args.head_size, kvd,
                        args.steps, jnp.dtype(args.dtype),
                    )
                    for impl, s in secs.items():
                        row = {
                            "impl": impl, "model": model, "batch": B,
                            "cache_len": M, "kv_dtype": kvd,
                            "n_head": args.n_head,
                            "head_size": args.head_size,
                            "dtype": args.dtype,
                            "ms_per_step": round(s * 1e3, 4),
                            "max_abs_diff": diff,
                        }
                        rows.append(row)
                        print(json.dumps(row))
                    if args.smoke:
                        # both impls consumed IDENTICAL (already
                        # quantized) inputs, so the only divergence is
                        # the online-vs-materialized softmax accumulation
                        # order — tile-level fp32 noise, not quant error
                        assert diff < 1e-5, (
                            f"{model}/{kvd}: pallas vs xla diverged "
                            f"by {diff}"
                        )
    if args.out:
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    by = {}
    for r in rows:
        key = (r["model"], r["batch"], r["cache_len"], r["kv_dtype"])
        by.setdefault(key, {})[r["impl"]] = r["ms_per_step"]
    for key, d in sorted(by.items()):
        if "xla" in d and "pallas" in d and d["pallas"] > 0:
            print(
                f"# {key[0]} B={key[1]} M={key[2]} {key[3]}: "
                f"fused speedup {d['xla'] / d['pallas']:.2f}x",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
