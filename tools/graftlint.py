"""graftlint — JAX hazard linter for this repo (thin CLI wrapper).

The real engine lives in differential_transformer_replication_tpu/
analysis/ (rules.py = catalog, lint.py = AST engine, cli.py = this
interface); this wrapper exists so the documented invocation works
from a fresh checkout with no install step::

    python tools/graftlint.py differential_transformer_replication_tpu/
    python tools/graftlint.py --json ... | python -m json.tool

Installed form (pyproject ``[project.scripts]``): ``graftlint <paths>``.
Pure stdlib — never imports jax, so it runs anywhere in milliseconds.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from differential_transformer_replication_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
