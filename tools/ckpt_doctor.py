#!/usr/bin/env python
"""List / verify / repair a checkpoint directory tree.

Walks checkpoint dirs — a single checkpoint, or a run directory holding
rotating ``step-*`` checkpoints plus ``best``/``last`` dirs — re-hashes
every file against its integrity manifest (train/ckpt_writer.py), and
prints one status line per checkpoint followed by ONE JSON summary line
(like tools/metrics_report.py), so a cron job or CI step can gate on
checkpoint health::

    python tools/ckpt_doctor.py runs/exp.steps
    python tools/ckpt_doctor.py runs/ --check           # CI gate
    python tools/ckpt_doctor.py runs/exp.steps --repair # prune corrupt
    python tools/ckpt_doctor.py old_run.ckpt --adopt-legacy

Statuses: ``verified`` (manifest present, all digests match),
``corrupt`` (manifest present but a file is missing/truncated/flipped),
``legacy`` (pre-manifest checkpoint: state.msgpack + meta.json, no
manifest), ``incomplete`` (files but no certifiable checkpoint — e.g. a
save killed before the manifest write).

``--repair`` deletes corrupt and incomplete ``step-*`` dirs with the
crash-safe manifest-first ordering; non-rotation dirs (best/last) are
never deleted — they are reported for the operator. ``--adopt-legacy``
stamps a manifest onto legacy dirs (certifying their CURRENT bytes, so
later bit-rot is caught even though past history is unknowable).
``--check`` exits non-zero when corruption remains or no verified
checkpoint exists.

Stdlib-only: train/ckpt_writer.py is loaded by file path, so this runs
(fast) on machines without jax — a storage node, a CI runner.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional, Tuple

_CKPT_WRITER_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..",
    "differential_transformer_replication_tpu", "train", "ckpt_writer.py",
)


def load_ckpt_module(path: str = _CKPT_WRITER_PATH):
    spec = importlib.util.spec_from_file_location("_doctor_ckpt_writer", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _dir_bytes(path: str) -> int:
    total = 0
    for name in os.listdir(path):
        fp = os.path.join(path, name)
        if os.path.isfile(fp):
            total += os.path.getsize(fp)
    return total


def _looks_like_checkpoint(path: str, ckpt) -> bool:
    return os.path.isdir(path) and (
        os.path.isfile(os.path.join(path, ckpt.MANIFEST_NAME))
        or os.path.isfile(os.path.join(path, "state.msgpack"))
        or ckpt.parse_step_dir(os.path.basename(path)) is not None
    )


def discover(paths: List[str], ckpt) -> List[str]:
    """Checkpoint dirs under the given paths: each path is either a
    checkpoint itself or a tree walked recursively (so a run directory
    containing `<exp>.steps/step-*` subtrees heals in one invocation).
    Checkpoint dirs are not descended into — their contents are data,
    not more checkpoints."""
    found = []
    for path in paths:
        if _looks_like_checkpoint(path, ckpt):
            found.append(path)
        elif os.path.isdir(path):
            for dirpath, dirnames, _ in os.walk(path):
                kept = []
                for name in sorted(dirnames):
                    child = os.path.join(dirpath, name)
                    if _looks_like_checkpoint(child, ckpt):
                        found.append(child)
                    else:
                        kept.append(name)
                dirnames[:] = kept
    return found


def diagnose(path: str, ckpt) -> Tuple[str, Optional[int], str]:
    """(status, step, detail) for one checkpoint dir."""
    try:
        manifest = ckpt.verify_checkpoint(path)
        return "verified", manifest.get("step"), ""
    except ckpt.CheckpointError as e:
        if os.path.isfile(os.path.join(path, ckpt.MANIFEST_NAME)):
            return "corrupt", _meta_step(path), str(e)
    if os.path.isfile(os.path.join(path, "state.msgpack")) and os.path.isfile(
        os.path.join(path, "meta.json")
    ):
        return "legacy", _meta_step(path), "no integrity manifest"
    return "incomplete", None, "no certifiable checkpoint content"


def _meta_step(path: str) -> Optional[int]:
    try:
        with open(os.path.join(path, "meta.json")) as f:
            return int(json.load(f)["iter_num"])
    except Exception:  # noqa: BLE001 — best-effort annotation only
        return None


def run(args: argparse.Namespace) -> int:
    ckpt = load_ckpt_module()
    dirs = discover(args.paths, ckpt)
    summary = {
        "checkpoints": len(dirs), "verified": 0, "corrupt": 0,
        "legacy": 0, "incomplete": 0, "total_bytes": 0,
        "newest_verified": None, "newest_verified_step": None,
    }
    repaired, adopted = [], []
    for path in dirs:
        status, step, detail = diagnose(path, ckpt)
        if status == "legacy" and args.adopt_legacy:
            ckpt.write_manifest(path, step=step if step is not None else -1)
            adopted.append(path)
            status, step, detail = diagnose(path, ckpt)
        if status in ("corrupt", "incomplete") and args.repair:
            if ckpt.parse_step_dir(os.path.basename(path)) is not None:
                ckpt.delete_checkpoint_dir(path)
                repaired.append(path)
                print(f"{path}: {status} -> deleted ({detail})",
                      file=sys.stderr)
                continue
            detail += " [not a step-* dir; refusing to auto-delete]"
        summary[status] += 1
        summary["total_bytes"] += _dir_bytes(path)
        if status == "verified" and (
            summary["newest_verified_step"] is None
            or (step or -1) > summary["newest_verified_step"]
        ):
            summary["newest_verified"] = path
            summary["newest_verified_step"] = step
        line = f"{path}: {status}"
        if step is not None:
            line += f" (step {step})"
        if detail:
            line += f" — {detail}"
        print(line, file=sys.stderr)
    if repaired:
        summary["repaired"] = repaired
    if adopted:
        summary["adopted"] = adopted
    print(json.dumps(summary))
    if args.check:
        bad = []
        if summary["corrupt"] or summary["incomplete"]:
            bad.append(
                f"{summary['corrupt']} corrupt + {summary['incomplete']} "
                "incomplete checkpoint(s) present"
            )
        if summary["verified"] == 0:
            bad.append("no verified checkpoint in the tree")
        for b in bad:
            print(f"CHECK FAILED: {b}", file=sys.stderr)
        return 1 if bad else 0
    return 0


def main() -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="+",
                   help="checkpoint dir(s) or tree root(s)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when corruption remains or no verified "
                        "checkpoint exists")
    p.add_argument("--repair", action="store_true",
                   help="delete corrupt/incomplete step-* dirs "
                        "(manifest-first crash-safe ordering)")
    p.add_argument("--adopt-legacy", action="store_true",
                   help="stamp integrity manifests onto pre-manifest "
                        "checkpoints (certifies their current bytes)")
    return run(p.parse_args())


if __name__ == "__main__":
    sys.exit(main())
