"""Measure the reference implementation's training throughput.

Imports the reference's own model classes from /root/reference (no code is
copied) and drives forward+backward+AdamW steps with synthetic token data at
the reference recipe shapes (train.py:60-69). Records tokens/sec for the
model-select switch's flagship (DiffTransformer, train.py:205-212).

torch in this image is CPU-only, so this measures the reference on host CPU;
the number is recorded in BASELINE.md with that caveat. Usage:

    python tools/measure_reference.py [--micro-batch 8] [--steps 3]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

REFERENCE_PATH = "/root/reference"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--micro-batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--model", default="diff", choices=("control", "diff", "ndiff"))
    args = ap.parse_args()

    sys.path.insert(0, REFERENCE_PATH)
    import torch

    torch.manual_seed(1337)

    # Reference recipe: train.py:60-64 (8L/768d/4-head/block-512), vocab
    # 12000 (train.py:41), AdamW recipe train.py:236-241.
    vocab, n_embd, n_head, n_layer, block = 12000, 768, 4, 8, 512
    if args.model == "diff":
        from diff_transformer import DiffTransformer

        model = DiffTransformer(vocab, n_embd, n_head, n_layer, block, 0.0)
    elif args.model == "control":
        from control import StandardTransformer

        # control gets doubled heads (train.py:226)
        model = StandardTransformer(vocab, n_embd, n_head * 2, n_layer, block, 0.0)
    else:
        from Ndiff_transformer import AlternatingDiffTransformer

        model = AlternatingDiffTransformer(vocab, n_embd, n_head, n_layer, block, 0.0, n_terms=4)

    opt = torch.optim.AdamW(
        model.parameters(), lr=3.2e-4, betas=(0.9, 0.95), weight_decay=0.1
    )
    B, T = args.micro_batch, block
    x = torch.randint(0, vocab, (B, T))
    y = torch.randint(0, vocab, (B, T))

    def step() -> None:
        opt.zero_grad(set_to_none=True)
        _, loss = model(x, y)
        loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
        opt.step()

    for _ in range(args.warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(args.steps):
        step()
    dt = time.perf_counter() - t0

    tps = args.steps * B * T / dt
    n_params = sum(p.numel() for p in model.parameters())
    print(
        json.dumps(
            {
                "impl": f"reference-torch-{args.model}",
                "device": "cpu",
                "micro_batch": B,
                "block_size": T,
                "steps": args.steps,
                "sec_per_step": dt / args.steps,
                "tokens_per_sec": tps,
                "n_params": n_params,
            }
        )
    )


if __name__ == "__main__":
    main()
