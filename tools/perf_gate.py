#!/usr/bin/env python
"""Noise-aware performance-regression gate over the repo's perf JSON.

Every perf surface in this repo already speaks one-line JSON —
``bench.py`` (tokens/sec, mfu_6nd), ``tools/serve_bench.py`` (tok/s,
TTFT/ITL percentiles), the continuous profiler's ``device_profile``
records (busy ms, per-bucket ms, mfu; obs/device_profile.py), and the
committed ``BENCH_r0*.json`` round archives. This tool turns any such
trajectory into a CI gate::

    # newest-last file list (the committed bench history):
    python tools/perf_gate.py BENCH_r0*.json --key value --key mfu_6nd
    # a serve_bench history file (--out appends one line per run):
    python tools/perf_gate.py serve_hist.jsonl --key value \
        --key itl_ms.p95:lower
    # the trainer's continuous device profiles:
    python tools/perf_gate.py --from-metrics-jsonl metrics.jsonl \
        --key mfu --key bucket_ms.flash_attention:lower

Inputs are positional JSON files in TRAJECTORY ORDER (newest last);
each file may be a single JSON document, a JSONL stream (every line a
sample, in order), or a driver-wrapped round archive (the
``BENCH_r0*.json`` shape — the sample is its ``parsed`` field).
``--from-metrics-jsonl`` reads a trainer/serving metrics stream and
keeps only ``{"record": "device_profile"}`` rows (``--record`` picks a
different type).

**Keys** are dotted paths into each sample (``itl_ms.p95`` descends
nested dicts), with an optional direction suffix — ``:higher`` (more
is better: throughput, mfu) or ``:lower`` (latency, per-bucket ms).
Unsuffixed keys are inferred: names containing ms/latency/itl/ttft/
time/busy gate lower-is-better, everything else higher.

**Baseline math** (shared with tools/bench_trend.py): the baseline is
the MEDIAN of the trailing ``--window`` samples before the newest, and
the noise scale is their MAD (median absolute deviation, scaled by
1.4826 to estimate sigma). The newest sample regresses when it is
worse than the baseline by more than
``max(--max-regress * |baseline|, --mad-factor * 1.4826 * MAD)`` — so
a noisy history widens its own gate instead of flapping, and a tight
history enforces the fractional bound.

Output: ONE JSON summary line (``slo_report``-style). Exit codes:
0 = every key within bounds, 1 = regression, 2 = insufficient history
(fewer than ``--min-history`` samples carrying a key) or unusable
input. Stdlib only — runs in CI next to metrics_report/slo_report with
no jax.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import List, Optional, Tuple

# MAD -> sigma for normally distributed noise; the standard consistency
# constant, spelled out so the gate formula is reproducible by hand.
MAD_SIGMA = 1.4826

# Direction inference tokenizes the key path on ./_ so "tokens_per_sec"
# (higher-better) never trips on the "s"/"ms" latency hints.
_LOWER_BETTER_TOKENS = frozenset((
    "ms", "s", "itl", "ttft", "latency", "busy", "time", "seconds",
    "stall", "blocked", "wait",
))


def median(xs: List[float]) -> float:
    ys = sorted(xs)
    n = len(ys)
    mid = n // 2
    return ys[mid] if n % 2 else (ys[mid - 1] + ys[mid]) / 2.0


def mad(xs: List[float], center: Optional[float] = None) -> float:
    """Median absolute deviation — the robust noise scale (one outlier
    round cannot widen the gate the way a stddev would)."""
    if center is None:
        center = median(xs)
    return median([abs(x - center) for x in xs])


def baseline_stats(history: List[float]) -> Tuple[float, float]:
    """(median, mad) of a trailing window — THE baseline math, imported
    by tools/bench_trend.py so both tools judge a trajectory
    identically."""
    m = median(history)
    return m, mad(history, m)


def parse_key_spec(spec: str) -> Tuple[str, str, str]:
    """``"itl_ms.p95:lower"`` -> (path, direction, display name)."""
    if ":" in spec:
        path, direction = spec.rsplit(":", 1)
        if direction not in ("higher", "lower"):
            raise ValueError(
                f"key direction must be 'higher' or 'lower', got "
                f"{direction!r} in {spec!r}"
            )
    else:
        path = spec
        tokens = re.split(r"[._]", path.lower())
        direction = (
            "lower"
            if any(t in _LOWER_BETTER_TOKENS for t in tokens)
            else "higher"
        )
    return path, direction, spec


def lookup(doc: dict, path: str):
    """Dotted-path descent; None when any hop is absent or non-numeric."""
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def _docs_from_text(text: str, path: str) -> List[dict]:
    """One file -> ordered sample docs. Accepts a single JSON document,
    a JSONL stream, or the driver round archive whose sample is the
    ``parsed`` field. Torn JSONL tail lines are skipped (a killed run
    must not wedge the gate)."""
    text = text.strip()
    if not text:
        return []
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict):
                docs.append(d)
        if not docs:
            raise ValueError(f"{path}: neither JSON nor JSONL")
        return docs
    if isinstance(doc, list):
        return [d for d in doc if isinstance(d, dict)]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    if isinstance(doc.get("parsed"), dict):
        return [doc["parsed"]]  # BENCH_r0*.json round archive
    return [doc]


def load_samples(paths: List[str], record: Optional[str] = None,
                 from_jsonl: Optional[str] = None,
                 missing: Optional[List[str]] = None) -> List[dict]:
    """``missing`` (when given) collects paths that do not exist yet —
    an ABSENT history file is the bootstrap state (no bench round has
    appended to it), not a usage error: the caller reports it as
    insufficient history (exit 2), never a traceback."""
    docs: List[dict] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                text = fh.read()
        except FileNotFoundError:
            if missing is None:
                raise
            missing.append(p)
            continue
        docs.extend(_docs_from_text(text, p))
    if from_jsonl:
        want = record or "device_profile"
        try:
            fh = open(from_jsonl, encoding="utf-8")
        except FileNotFoundError:
            if missing is None:
                raise
            missing.append(from_jsonl)
            fh = None
        if fh is not None:
            with fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(d, dict) and d.get("record") == want:
                        docs.append(d)
    elif record:
        docs = [d for d in docs if d.get("record") == record]
    return docs


def gate_key(samples: List[dict], spec: str, window: int,
             max_regress: float, mad_factor: float,
             min_history: int) -> dict:
    """Judge one key over the trajectory; the per-key summary entry."""
    path, direction, name = parse_key_spec(spec)
    series = [
        (i, v) for i, v in
        ((i, lookup(d, path)) for i, d in enumerate(samples))
        if v is not None
    ]
    out: dict = {"key": name, "path": path, "direction": direction,
                 "n": len(series)}
    if len(series) < min_history:
        out["status"] = "insufficient_history"
        out["min_history"] = min_history
        return out
    values = [v for _, v in series]
    newest = values[-1]
    history = values[:-1][-window:]
    if not history:
        # --min-history 1 with a single sample: nothing to compare
        # against is insufficient history, not a crash
        out["status"] = "insufficient_history"
        out["min_history"] = max(min_history, 2)
        return out
    base, noise = baseline_stats(history)
    slack = max(max_regress * abs(base), mad_factor * MAD_SIGMA * noise)
    delta = (newest - base) if direction == "higher" else (base - newest)
    regressed = delta < -slack
    out.update({
        "status": "regressed" if regressed else "ok",
        "newest": newest,
        "baseline_median": round(base, 6),
        "baseline_mad": round(noise, 6),
        "allowed_slack": round(slack, 6),
        "delta_vs_baseline": round(newest - base, 6),
        "window_n": len(history),
    })
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("files", nargs="*",
                   help="perf JSON files in trajectory order (newest "
                        "LAST); single-doc JSON, JSONL, or BENCH_r* "
                        "round archives")
    p.add_argument("--from-metrics-jsonl", default=None, dest="from_jsonl",
                   help="read device_profile records from a trainer/"
                        "serving metrics.jsonl stream (the spelling "
                        "shared with metrics_report/slo_report)")
    p.add_argument("--record", default=None,
                   help="with --from-metrics-jsonl (or plain JSONL "
                        "inputs): gate this record type instead of "
                        "device_profile")
    p.add_argument("--key", action="append", default=None,
                   help="dotted path into each sample, optional "
                        ":higher/:lower direction suffix (repeat; "
                        "default: value)")
    p.add_argument("--window", type=int, default=5,
                   help="trailing samples (before the newest) forming "
                        "the baseline")
    p.add_argument("--max-regress", type=float, default=0.10,
                   help="fractional regression bound vs the baseline "
                        "median (0.10 = 10%%)")
    p.add_argument("--mad-factor", type=float, default=3.0,
                   help="noise bound: regressions within this many "
                        "MAD-sigmas of the baseline are not gated")
    p.add_argument("--min-history", type=int, default=3,
                   help="samples (including the newest) a key needs "
                        "before it can gate; fewer exits 2")
    args = p.parse_args()

    if not args.files and not args.from_jsonl:
        p.error("give perf JSON files and/or --from-metrics-jsonl")
    missing: List[str] = []
    try:
        samples = load_samples(args.files, record=args.record,
                               from_jsonl=args.from_jsonl,
                               missing=missing)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "perf_gate", "error": str(e)}))
        print(f"CHECK FAILED: {e}", file=sys.stderr)
        return 2
    if not samples or missing:
        # the bootstrap state: named history files absent, or every
        # input empty (no bench round has appended yet). One JSON line
        # + exit 2 — never a traceback, distinguishable from a
        # regression (exit 1) so CI treats it as "go run the bootstrap
        # round". A MISSING file fails even when other files yielded
        # samples: silently gating a partial trajectory would pass the
        # very series the absent file was supposed to gate.
        print(json.dumps({
            "metric": "perf_gate",
            "status": "insufficient_history",
            "samples": len(samples),
            "missing_files": missing,
            "hint": "insufficient history, run a bench round "
                    "(bench.py / serve_bench.py --out) to bootstrap "
                    "the trajectory",
            "ok": False,
        }))
        print("CHECK FAILED: insufficient history, run a bench round"
              + (f" (missing: {', '.join(missing)})" if missing else ""),
              file=sys.stderr)
        return 2
    specs = args.key or ["value"]
    try:
        keys = [
            gate_key(samples, spec, args.window, args.max_regress,
                     args.mad_factor, args.min_history)
            for spec in specs
        ]
    except ValueError as e:
        print(json.dumps({"metric": "perf_gate", "error": str(e)}))
        print(f"CHECK FAILED: {e}", file=sys.stderr)
        return 2
    regressed = [k["key"] for k in keys if k["status"] == "regressed"]
    insufficient = [
        k["key"] for k in keys if k["status"] == "insufficient_history"
    ]
    summary = {
        "metric": "perf_gate",
        "samples": len(samples),
        "window": args.window,
        "max_regress": args.max_regress,
        "mad_factor": args.mad_factor,
        "keys": keys,
        "regressed": regressed,
        "insufficient": insufficient,
        "ok": not regressed and not insufficient,
    }
    print(json.dumps(summary))
    for k in keys:
        if k["status"] == "regressed":
            print(
                f"CHECK FAILED: {k['key']} regressed — newest "
                f"{k['newest']} vs baseline median "
                f"{k['baseline_median']} (allowed slack "
                f"{k['allowed_slack']})", file=sys.stderr,
            )
        elif k["status"] == "insufficient_history":
            print(
                f"CHECK FAILED: {k['key']} has {k['n']} samples, needs "
                f"{k['min_history']} (insufficient history)",
                file=sys.stderr,
            )
    if regressed:
        return 1
    if insufficient:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
