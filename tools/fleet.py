#!/usr/bin/env python
"""Local serving fleet: launch, supervise, and rolling-restart N
replicas behind the health-aware router.

The serving-side sibling of tools/train_supervisor.py (whose
restart-budget / exponential-backoff / exit-classification pattern it
reuses): each replica is one ``serving.server`` process on its own
port, and the fleet keeps them alive —

  - a CRASHED replica (segfault, OOM kill, SIGKILL preemption) is
    relaunched after ``backoff_base * 2^restarts`` seconds (capped),
    up to ``--max-restarts`` per replica; the router ejects it while
    it is down and slowly re-admits it once ``/ready`` answers again;
  - a replica that exits CLEANLY (rc 0 — e.g. an operator's SIGTERM
    drain outside a rolling restart) is NOT relaunched: someone asked
    it to stop;
  - ``rolling_restart()`` upgrades the fleet with zero dropped
    requests: one replica at a time, SIGTERM (the server drains —
    admission stops with 503 + Retry-After, in-flight requests
    finish), wait for exit, relaunch, wait for ``/ready``, then move
    to the next. The router sees ``draining`` and routes around the
    replica the whole time — connection-free removal.

CLI (replicas + router in one process tree)::

    python tools/fleet.py --replicas 2 --router-port 8000 \
        -- --model control --num-slots 4

Everything after ``--`` is passed through to every replica's
``serving.server`` CLI verbatim. SIGHUP triggers a rolling restart;
SIGTERM/SIGINT drain and stop the whole fleet. Every launch/exit
appends one JSON line to ``--fleet-log`` for forensics.

No jax import — the fleet must stay alive when the runtime it babysits
is the thing crashing. (serving/router.py and serving/retry.py are
stdlib-only and safe to import here; the package's serving/__init__
resolves its jax-heavy exports lazily.)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."
))

from train_supervisor import backoff_s, classify_exit  # noqa: E402

from differential_transformer_replication_tpu.obs.events import (  # noqa: E402
    open_event_log,
)

SERVER_MODULE = "differential_transformer_replication_tpu.serving.server"


def pick_free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (best-effort: released before the
    replica binds it, so a collision is possible but vanishingly rare
    on a loopback test host)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def wait_http_ready(url: str, timeout_s: float = 120.0,
                    interval_s: float = 0.1) -> bool:
    """Poll ``GET <url>/ready`` until it answers 200. A reachable 503
    (draining/restarting) keeps polling — the process is up but not
    admitting; transport errors mean it is still booting."""
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        try:
            with urllib.request.urlopen(url + "/ready", timeout=2.0) as r:
                if r.status == 200:
                    return True
        except urllib.error.HTTPError:
            pass  # alive, not ready yet
        except OSError:
            pass  # not listening yet
        time.sleep(interval_s)
    return False


class ReplicaProc:
    """One replica's process slot: argv, port, restart accounting."""

    def __init__(self, index: int, host: str, port: int,
                 argv: List[str], env: Optional[dict]):
        self.index = index
        self.host = host
        self.port = port
        self.argv = argv
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.expected_exit = False  # rolling restart / fleet stop
        self.gave_up = False        # restart budget exhausted

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Fleet:
    """Launch + supervise N local replicas; see module docstring.

    Programmatic surface (what tests/test_router.py's chaos test
    drives): ``start()``, ``urls``, ``rolling_restart()``, ``kill()``
    (chaos: SIGKILL one replica and let supervision relaunch it),
    ``stop()``.
    """

    def __init__(self, num_replicas: int,
                 server_args: Optional[Sequence[str]] = None,
                 host: str = "127.0.0.1",
                 ports: Optional[Sequence[int]] = None,
                 python: str = sys.executable,
                 env: Optional[dict] = None,
                 max_restarts: int = 3,
                 backoff_base: float = 0.5,
                 backoff_max: float = 10.0,
                 ready_timeout_s: float = 120.0,
                 drain_exit_timeout_s: float = 60.0,
                 fleet_log: Optional[str] = None,
                 replica_env: Optional[Dict[int, dict]] = None):
        if num_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {num_replicas}")
        self.host = host
        self.max_restarts = max_restarts
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.ready_timeout_s = ready_timeout_s
        self.drain_exit_timeout_s = drain_exit_timeout_s
        self.fleet_log = fleet_log
        # structured JSONL (obs/events.py): same shape as the router's
        # and replicas' event logs, so fleet forensics join on ts
        self._events = open_event_log(fleet_log, process="fleet")
        ports = list(ports) if ports else [
            pick_free_port(host) for _ in range(num_replicas)
        ]
        if len(ports) != num_replicas:
            raise ValueError(
                f"{num_replicas} replicas but {len(ports)} ports"
            )
        # kept as templates so scale_up()/relaunch_replica() can mint
        # NEW replica slots long after __init__
        self._python = python
        self._server_args = list(server_args or [])
        self._base_env = dict(env) if env is not None else None
        self._replica_env = dict(replica_env or {})
        self._next_index = num_replicas
        self.replicas = [
            self._make_replica(i, port) for i, port in enumerate(ports)
        ]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        # restart relaunch deadlines (monotonic ts), per replica index
        self._relaunch_at: Dict[int, float] = {}

    def _make_replica(self, index: int, port: int,
                      server_args: Optional[Sequence[str]] = None,
                      extra_env: Optional[dict] = None) -> ReplicaProc:
        """Build one replica slot from the fleet's templates.
        ``server_args`` replaces the shared extra args for this slot
        (canary: new checkpoint/config); ``extra_env`` layers on top of
        the per-index env overrides (canary: arm a fault on one
        replica)."""
        extra = (self._server_args if server_args is None
                 else list(server_args))

        def _render(arg: str) -> str:
            # per-replica templating: shared server_args naming a file
            # path ("--trace-path", "--event-log") must not make N
            # replicas clobber one file — "{replica}"/"{port}" expand
            # per process
            return (arg.replace("{replica}", str(index))
                       .replace("{port}", str(port)))

        # per-replica env overrides (chaos tests arm DTX_FAULTS on
        # ONE replica; the others must stay healthy)
        base = dict(self._base_env) if self._base_env is not None else None
        override = dict(self._replica_env.get(index) or {})
        if extra_env:
            override.update(extra_env)
        if override:
            base = dict(os.environ) if base is None else base
            base.update(override)
        return ReplicaProc(
            index, self.host, port,
            [self._python, "-m", SERVER_MODULE,
             "--host", self.host, "--port", str(port)]
            + [_render(a) for a in extra],
            env=base,
        )

    # -- observability -------------------------------------------------

    @property
    def urls(self) -> List[str]:
        return [r.url for r in self.replicas]

    def _log(self, record: dict) -> None:
        printable = {"time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                     **record}
        print(f"[fleet] {json.dumps(printable)}", file=sys.stderr)
        record = dict(record)
        self._events.emit(record.pop("event", "fleet_event"), **record)
        self._events.flush()  # fleet events are rare; land them now

    # -- lifecycle -----------------------------------------------------

    def _launch(self, r: ReplicaProc) -> None:
        r.proc = subprocess.Popen(r.argv, env=r.env)
        self._log({"event": "launch", "replica": r.index,
                   "port": r.port, "pid": r.proc.pid,
                   "restarts": r.restarts})

    def start(self, wait_ready: bool = True) -> "Fleet":
        for r in self.replicas:
            self._launch(r)
        if wait_ready:
            for r in self.replicas:
                if not wait_http_ready(r.url, self.ready_timeout_s):
                    self.stop()
                    raise RuntimeError(
                        f"replica {r.index} ({r.url}) not ready within "
                        f"{self.ready_timeout_s}s"
                    )
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="fleet-supervisor",
            daemon=True,
        )
        self._supervisor.start()
        return self

    def _supervise_loop(self) -> None:
        """Relaunch crashed replicas with backoff + restart budget
        (train_supervisor semantics, one budget per replica)."""
        while not self._stop.wait(0.05):
            now = time.monotonic()
            for r in self.replicas:
                with self._lock:
                    if (r.expected_exit or r.gave_up or r.proc is None
                            or r.proc.poll() is None):
                        continue
                    due = self._relaunch_at.get(r.index)
                    if due is None:
                        rc = r.proc.returncode
                        outcome = classify_exit(rc)
                        self._log({"event": "exit", "replica": r.index,
                                   "rc": rc, "outcome": outcome})
                        if outcome == "clean":
                            # someone asked it to stop; honor that
                            r.gave_up = True
                            continue
                        if r.restarts >= self.max_restarts:
                            self._log({
                                "event": "give_up", "replica": r.index,
                                "restarts": r.restarts,
                            })
                            r.gave_up = True
                            continue
                        delay = backoff_s(r.restarts, self.backoff_base,
                                          self.backoff_max)
                        r.restarts += 1
                        self._relaunch_at[r.index] = now + delay
                        self._log({"event": "backoff", "replica": r.index,
                                   "delay_s": round(delay, 3),
                                   "restart": r.restarts})
                    elif due <= now:
                        del self._relaunch_at[r.index]
                        self._launch(r)

    def kill(self, index: int) -> None:
        """Chaos helper: SIGKILL one replica (uncatchable, no drain).
        Supervision relaunches it on the backoff schedule."""
        r = self.replicas[index]
        if r.alive():
            r.proc.send_signal(signal.SIGKILL)

    def wait_ready(self, index: int,
                   timeout_s: Optional[float] = None) -> bool:
        return wait_http_ready(
            self.replicas[index].url,
            self.ready_timeout_s if timeout_s is None else timeout_s,
        )

    # -- rolling restart / scaling ------------------------------------

    def _migrate_drain(self, r: ReplicaProc, pre_drain) -> None:
        """Best-effort live migration before the SIGTERM drain: hand
        ``pre_drain`` (the router's ``migrate_out``, or a closure
        POSTing the router's ``/drain``) the victim's URL so ACTIVE
        decodes move to peers FIRST — drain time becomes page-transfer
        time instead of ``max_new_tokens``' worth of decoding. A
        failure here only means the classic finish-in-place drain does
        the work; the requests are never harmed."""
        if pre_drain is None or not r.alive():
            return
        try:
            result = pre_drain(r.url)
        except Exception as e:
            self._log({"event": "drain_migrate_failed",
                       "replica": r.index, "error": repr(e)})
            return
        self._log({"event": "drain_migrate", "replica": r.index,
                   **(result if isinstance(result, dict) else {})})

    def _drain_exit(self, r: ReplicaProc) -> None:
        """SIGTERM (the server drains: admission stops, in-flight
        requests finish), wait for exit, escalate to SIGKILL on a
        wedged straggler."""
        if r.alive():
            r.proc.send_signal(signal.SIGTERM)
            try:
                r.proc.wait(self.drain_exit_timeout_s)
            except subprocess.TimeoutExpired:
                self._log({"event": "drain_timeout_kill",
                           "replica": r.index})
                r.proc.kill()
                r.proc.wait(10)

    def _restart_one(self, r: ReplicaProc, ready_check=None,
                     pre_drain=None) -> None:
        """Drain one replica, relaunch it (on whatever argv/env the
        slot now carries), wait for /ready and the optional
        ``ready_check`` gate, then grant a fresh supervision lease.
        ``pre_drain(url)`` (optional — the router's ``migrate_out``)
        live-migrates ACTIVE decodes to peers before the SIGTERM."""
        with self._lock:
            r.expected_exit = True  # supervisor: hands off
            self._relaunch_at.pop(r.index, None)
        try:
            self._log({"event": "rolling_drain", "replica": r.index})
            self._migrate_drain(r, pre_drain)
            self._drain_exit(r)
            self._launch(r)
            if not wait_http_ready(r.url, self.ready_timeout_s):
                raise RuntimeError(
                    f"replica {r.index} ({r.url}) did not come back "
                    f"within {self.ready_timeout_s}s after rolling "
                    "restart"
                )
            if ready_check is not None:
                end = time.monotonic() + self.ready_timeout_s
                while not ready_check(r):
                    if time.monotonic() >= end:
                        raise RuntimeError(
                            f"replica {r.index} ({r.url}) ready but "
                            "not re-admitted (ready_check) within "
                            f"{self.ready_timeout_s}s"
                        )
                    time.sleep(0.05)
            with self._lock:
                # a deliberate operator restart grants a fresh
                # supervision lease — without this, a replica that
                # had exhausted its budget (or exited cleanly once)
                # would be revived yet silently unsupervised
                r.gave_up = False
                r.restarts = 0
            self._log({"event": "rolling_done", "replica": r.index})
        finally:
            with self._lock:
                r.expected_exit = False

    def rolling_restart(self, ready_check=None, pre_drain=None) -> None:
        """Drain-aware, one replica at a time; see module docstring.
        Raises when a replica fails to come back — continuing would
        take the NEXT replica down too and shrink the fleet to zero.

        ``ready_check(replica)`` (optional) gates the move to the next
        replica beyond the replica's own ``/ready``: pass a probe of
        the ROUTER's view (replica re-admitted, i.e. state ``up``) so
        the restart never drains replica k+1 while the router is still
        slow-re-admitting replica k — the zero-eligible window that
        would shed requests.

        ``pre_drain(url)`` (optional) live-migrates each replica's
        ACTIVE decodes to peers before its SIGTERM (pass the router's
        ``migrate_out``) — the restart's wall-clock stops depending on
        the longest in-flight ``max_new_tokens``."""
        for r in list(self.replicas):
            self._restart_one(r, ready_check=ready_check,
                              pre_drain=pre_drain)

    def relaunch_replica(self, index: int,
                         server_args: Optional[Sequence[str]] = None,
                         extra_env: Optional[dict] = None,
                         argv: Optional[List[str]] = None,
                         env: Optional[dict] = None,
                         ready_check=None, pre_drain=None):
        """Drain ONE replica and relaunch it on a different command
        line — the canary-rollout primitive. ``server_args`` replaces
        the fleet's shared extra args for this slot (new checkpoint /
        config) and ``extra_env`` layers env on top; ``argv``/``env``
        override verbatim instead (rollback passes back exactly what
        this method returned). Returns the PREVIOUS ``(argv, env)``.
        """
        r = next((x for x in self.replicas if x.index == index), None)
        if r is None:
            raise ValueError(f"no replica with index {index}")
        old = (list(r.argv),
               dict(r.env) if r.env is not None else None)
        if argv is not None:
            r.argv = list(argv)
            r.env = dict(env) if env is not None else None
        elif server_args is not None or extra_env:
            fresh = self._make_replica(
                index, r.port, server_args=server_args,
                extra_env=extra_env,
            )
            r.argv, r.env = fresh.argv, fresh.env
        if pre_drain is None:
            self._restart_one(r, ready_check=ready_check)
        else:
            self._restart_one(r, ready_check=ready_check,
                              pre_drain=pre_drain)
        return old

    def scale_up(self, n: int = 1, wait_ready: bool = True) -> List[str]:
        """Launch ``n`` NEW replica slots (fresh indices, fresh restart
        budgets, OS-assigned ports) and hand them to supervision.
        Returns their URLs (register them with the router next)."""
        if n < 1:
            raise ValueError(f"scale_up needs n >= 1, got {n}")
        with self._lock:
            added = []
            for _ in range(n):
                idx = self._next_index
                self._next_index += 1
                added.append(
                    self._make_replica(idx, pick_free_port(self.host))
                )
            # publish before launching: the supervisor skips slots with
            # no process, so a half-launched batch is never relaunched
            self.replicas = self.replicas + added
        for r in added:
            self._launch(r)
        self._log({"event": "scale_up", "n": n,
                   "replicas": [r.index for r in added],
                   "fleet_size": len(self.replicas)})
        if wait_ready:
            for r in added:
                if not wait_http_ready(r.url, self.ready_timeout_s):
                    raise RuntimeError(
                        f"scaled-up replica {r.index} ({r.url}) not "
                        f"ready within {self.ready_timeout_s}s"
                    )
        return [r.url for r in added]

    def scale_down(self, index: Optional[int] = None,
                   score_of=None, pre_drain=None) -> str:
        """Drain ONE replica out of the fleet, zero-loss, and RELEASE
        its supervision lease (slot removed, pending relaunch
        cancelled) — a later scale_up mints a fresh slot with a fresh
        restart budget instead of inheriting this one's scars.

        Victim selection: explicit ``index`` wins; else the
        LEAST-LOADED replica by ``score_of(url)`` (pass the router's
        load score — draining the busiest replica would orphan the
        most in-flight work onto its siblings); else the highest
        index. ``pre_drain(url)`` (optional — the router's
        ``migrate_out``) live-migrates the victim's ACTIVE decodes to
        the surviving peers before its SIGTERM. Returns the removed
        replica's URL."""
        with self._lock:
            candidates = [r for r in self.replicas if not r.expected_exit]
            if len(self.replicas) <= 1 or not candidates:
                raise ValueError("cannot scale below one replica")
            if index is not None:
                victim = next(
                    (r for r in candidates if r.index == index), None
                )
                if victim is None:
                    raise ValueError(f"no replica with index {index}")
            else:
                victim = None
                if score_of is not None:
                    scored = []
                    for r in candidates:
                        s = score_of(r.url)
                        if s is not None:
                            scored.append((s, r.index, r))
                    if scored:
                        victim = min(scored)[2]
                if victim is None:
                    victim = max(candidates, key=lambda r: r.index)
            victim.expected_exit = True  # supervisor hands off
            self._relaunch_at.pop(victim.index, None)
        self._log({"event": "scale_down_drain", "replica": victim.index,
                   "fleet_size": len(self.replicas)})
        self._migrate_drain(victim, pre_drain)
        self._drain_exit(victim)
        with self._lock:
            self.replicas = [r for r in self.replicas if r is not victim]
            self._relaunch_at.pop(victim.index, None)
        self._log({
            "event": "scale_down_done", "replica": victim.index,
            "rc": victim.proc.returncode if victim.proc else None,
            "fleet_size": len(self.replicas),
        })
        return victim.url

    # -- shutdown ------------------------------------------------------

    def stop(self, drain: bool = True) -> None:
        """SIGTERM everything (graceful drain), escalate to SIGKILL on
        stragglers, stop supervision."""
        self._stop.set()
        with self._lock:
            for r in self.replicas:
                r.expected_exit = True
        if self._supervisor is not None:
            self._supervisor.join(5.0)
            self._supervisor = None
        for r in self.replicas:
            if r.alive():
                r.proc.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL
                )
        deadline = time.monotonic() + (
            self.drain_exit_timeout_s if drain else 10.0
        )
        for r in self.replicas:
            if r.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                r.proc.wait(left)
            except subprocess.TimeoutExpired:
                self._log({"event": "stop_kill", "replica": r.index})
                r.proc.kill()
                r.proc.wait(10)
            self._log({"event": "stopped", "replica": r.index,
                       "rc": r.proc.returncode})
        # SIGTERM path: the buffered event tail must land (the atexit
        # net in obs/events.py is the last resort, not the plan)
        self._events.close()


def main() -> None:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--base-port", type=int, default=0,
                   help="first replica port (consecutive from here); "
                        "0 = OS-assigned free ports")
    p.add_argument("--router-port", type=int, default=8000)
    p.add_argument("--max-restarts", type=int, default=3,
                   help="per-replica crash-relaunch budget")
    p.add_argument("--backoff-base", type=float, default=0.5)
    p.add_argument("--backoff-max", type=float, default=10.0)
    p.add_argument("--ready-timeout", type=float, default=120.0)
    p.add_argument("--fleet-log", default=None,
                   help="append one JSON line per fleet event "
                        "(obs/events.py shape)")
    p.add_argument("--router-trace-path", default=None,
                   help="write the IN-PROCESS router's span trace "
                        "(pick/forward/retry/hedge; the clock "
                        "reference tools/trace_stitch.py wants first) "
                        "to this path")
    p.add_argument("--router-event-log", default=None,
                   help="append the router's structured JSONL events "
                        "(request finished/failed/retried, replica "
                        "ejection/re-admission) to this path")
    p.add_argument("--hedge-factor", type=float, default=0.0,
                   help="router hedging knob (0 = off); see "
                        "RouterConfig.hedge_factor")
    p.add_argument("server_args", nargs=argparse.REMAINDER,
                   help="-- then extra serving.server CLI args passed "
                        "to every replica")
    args = p.parse_args()

    server_args = list(args.server_args)
    if server_args and server_args[0] == "--":
        server_args = server_args[1:]
    ports = None
    if args.base_port:
        ports = [args.base_port + i for i in range(args.replicas)]

    fleet = Fleet(
        args.replicas, server_args=server_args, host=args.host,
        ports=ports, max_restarts=args.max_restarts,
        backoff_base=args.backoff_base, backoff_max=args.backoff_max,
        ready_timeout_s=args.ready_timeout, fleet_log=args.fleet_log,
    )
    print(f"[fleet] launching {args.replicas} replicas: "
          f"{fleet.urls}", file=sys.stderr)
    fleet.start()

    # the router rides in this process: stdlib-only import chain
    from differential_transformer_replication_tpu.config import (
        RouterConfig,
    )
    from differential_transformer_replication_tpu.serving.router import (
        Router,
        serve_router,
    )

    router_tracer = None
    if args.router_trace_path:
        from differential_transformer_replication_tpu.obs.spans import (
            SpanTracer,
        )

        router_tracer = SpanTracer(args.router_trace_path,
                                   process_name="router")
    router = Router(
        fleet.urls,
        RouterConfig(hedge_factor=args.hedge_factor),
        tracer=router_tracer,
        events=open_event_log(args.router_event_log, process="router"),
    ).start()
    httpd = serve_router(router, args.host, args.router_port)

    stopping = threading.Event()

    def _stop_all(signum, frame):
        del frame
        print(f"[fleet] signal {signum}: stopping fleet", file=sys.stderr)
        stopping.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    by_url = {rep.url: rep for rep in router.replicas}

    def _router_readmitted(r) -> bool:
        # gate the rolling restart on the ROUTER's view, not just the
        # replica's own /ready — see Fleet.rolling_restart
        rep = by_url.get(r.url)
        return rep is None or rep.eligible()

    def _rolling(signum, frame):
        del frame
        print("[fleet] SIGHUP: rolling restart", file=sys.stderr)

        def run():
            try:
                fleet.rolling_restart(ready_check=_router_readmitted,
                                      pre_drain=router.migrate_out)
            except Exception as e:
                print(f"[fleet] rolling restart FAILED: {e!r}",
                      file=sys.stderr)

        threading.Thread(target=run, daemon=True).start()

    signal.signal(signal.SIGTERM, _stop_all)
    signal.signal(signal.SIGINT, _stop_all)
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _rolling)

    print(f"[fleet] router on http://{args.host}:{args.router_port} "
          f"over {fleet.urls} — SIGHUP = rolling restart",
          file=sys.stderr)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        router.close()
        if router_tracer is not None:
            router_tracer.close()
        router.events.close()
        fleet.stop()


if __name__ == "__main__":
    main()
