#!/usr/bin/env python
"""Local serving fleet: launch, supervise, and rolling-restart N
replicas behind the health-aware router.

The serving-side sibling of tools/train_supervisor.py (whose
restart-budget / exponential-backoff / exit-classification pattern it
reuses): each replica is one ``serving.server`` process on its own
port, and the fleet keeps them alive —

  - a CRASHED replica (segfault, OOM kill, SIGKILL preemption) is
    relaunched after ``backoff_base * 2^restarts`` seconds (capped),
    up to ``--max-restarts`` per replica; the router ejects it while
    it is down and slowly re-admits it once ``/ready`` answers again;
  - a replica that exits CLEANLY (rc 0 — e.g. an operator's SIGTERM
    drain outside a rolling restart) is NOT relaunched: someone asked
    it to stop;
  - ``rolling_restart()`` upgrades the fleet with zero dropped
    requests: one replica at a time, SIGTERM (the server drains —
    admission stops with 503 + Retry-After, in-flight requests
    finish), wait for exit, relaunch, wait for ``/ready``, then move
    to the next. The router sees ``draining`` and routes around the
    replica the whole time — connection-free removal.

CLI (replicas + router in one process tree)::

    python tools/fleet.py --replicas 2 --router-port 8000 \
        -- --model control --num-slots 4

Everything after ``--`` is passed through to every replica's
``serving.server`` CLI verbatim. SIGHUP triggers a rolling restart;
SIGTERM/SIGINT drain and stop the whole fleet. Every launch/exit
appends one JSON line to ``--fleet-log`` for forensics.

No jax import — the fleet must stay alive when the runtime it babysits
is the thing crashing. (serving/router.py and serving/retry.py are
stdlib-only and safe to import here; the package's serving/__init__
resolves its jax-heavy exports lazily.)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."
))

from train_supervisor import backoff_s, classify_exit  # noqa: E402

from differential_transformer_replication_tpu.obs.events import (  # noqa: E402
    open_event_log,
)

SERVER_MODULE = "differential_transformer_replication_tpu.serving.server"


def pick_free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (best-effort: released before the
    replica binds it, so a collision is possible but vanishingly rare
    on a loopback test host)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def wait_http_ready(url: str, timeout_s: float = 120.0,
                    interval_s: float = 0.1) -> bool:
    """Poll ``GET <url>/ready`` until it answers 200. A reachable 503
    (draining/restarting) keeps polling — the process is up but not
    admitting; transport errors mean it is still booting."""
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        try:
            with urllib.request.urlopen(url + "/ready", timeout=2.0) as r:
                if r.status == 200:
                    return True
        except urllib.error.HTTPError:
            pass  # alive, not ready yet
        except OSError:
            pass  # not listening yet
        time.sleep(interval_s)
    return False


class ReplicaProc:
    """One replica's process slot: argv, port, restart accounting."""

    def __init__(self, index: int, host: str, port: int,
                 argv: List[str], env: Optional[dict]):
        self.index = index
        self.host = host
        self.port = port
        self.argv = argv
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.expected_exit = False  # rolling restart / fleet stop
        self.gave_up = False        # restart budget exhausted

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Fleet:
    """Launch + supervise N local replicas; see module docstring.

    Programmatic surface (what tests/test_router.py's chaos test
    drives): ``start()``, ``urls``, ``rolling_restart()``, ``kill()``
    (chaos: SIGKILL one replica and let supervision relaunch it),
    ``stop()``.
    """

    def __init__(self, num_replicas: int,
                 server_args: Optional[Sequence[str]] = None,
                 host: str = "127.0.0.1",
                 ports: Optional[Sequence[int]] = None,
                 python: str = sys.executable,
                 env: Optional[dict] = None,
                 max_restarts: int = 3,
                 backoff_base: float = 0.5,
                 backoff_max: float = 10.0,
                 ready_timeout_s: float = 120.0,
                 drain_exit_timeout_s: float = 60.0,
                 fleet_log: Optional[str] = None,
                 replica_env: Optional[Dict[int, dict]] = None):
        if num_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {num_replicas}")
        self.host = host
        self.max_restarts = max_restarts
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.ready_timeout_s = ready_timeout_s
        self.drain_exit_timeout_s = drain_exit_timeout_s
        self.fleet_log = fleet_log
        # structured JSONL (obs/events.py): same shape as the router's
        # and replicas' event logs, so fleet forensics join on ts
        self._events = open_event_log(fleet_log, process="fleet")
        ports = list(ports) if ports else [
            pick_free_port(host) for _ in range(num_replicas)
        ]
        if len(ports) != num_replicas:
            raise ValueError(
                f"{num_replicas} replicas but {len(ports)} ports"
            )
        extra = list(server_args or [])

        def _render(arg: str, i: int, port: int) -> str:
            # per-replica templating: shared server_args naming a file
            # path ("--trace-path", "--event-log") must not make N
            # replicas clobber one file — "{replica}"/"{port}" expand
            # per process
            return (arg.replace("{replica}", str(i))
                       .replace("{port}", str(port)))

        def _env_for(i: int) -> Optional[dict]:
            # per-replica env overrides (chaos tests arm DTX_FAULTS on
            # ONE replica; the others must stay healthy)
            base = dict(env) if env is not None else None
            override = (replica_env or {}).get(i)
            if override:
                base = dict(os.environ) if base is None else base
                base.update(override)
            return base

        self.replicas = [
            ReplicaProc(
                i, host, port,
                [python, "-m", SERVER_MODULE,
                 "--host", host, "--port", str(port)]
                + [_render(a, i, port) for a in extra],
                env=_env_for(i),
            )
            for i, port in enumerate(ports)
        ]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        # restart relaunch deadlines (monotonic ts), per replica index
        self._relaunch_at: Dict[int, float] = {}

    # -- observability -------------------------------------------------

    @property
    def urls(self) -> List[str]:
        return [r.url for r in self.replicas]

    def _log(self, record: dict) -> None:
        printable = {"time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                     **record}
        print(f"[fleet] {json.dumps(printable)}", file=sys.stderr)
        record = dict(record)
        self._events.emit(record.pop("event", "fleet_event"), **record)
        self._events.flush()  # fleet events are rare; land them now

    # -- lifecycle -----------------------------------------------------

    def _launch(self, r: ReplicaProc) -> None:
        r.proc = subprocess.Popen(r.argv, env=r.env)
        self._log({"event": "launch", "replica": r.index,
                   "port": r.port, "pid": r.proc.pid,
                   "restarts": r.restarts})

    def start(self, wait_ready: bool = True) -> "Fleet":
        for r in self.replicas:
            self._launch(r)
        if wait_ready:
            for r in self.replicas:
                if not wait_http_ready(r.url, self.ready_timeout_s):
                    self.stop()
                    raise RuntimeError(
                        f"replica {r.index} ({r.url}) not ready within "
                        f"{self.ready_timeout_s}s"
                    )
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="fleet-supervisor",
            daemon=True,
        )
        self._supervisor.start()
        return self

    def _supervise_loop(self) -> None:
        """Relaunch crashed replicas with backoff + restart budget
        (train_supervisor semantics, one budget per replica)."""
        while not self._stop.wait(0.05):
            now = time.monotonic()
            for r in self.replicas:
                with self._lock:
                    if (r.expected_exit or r.gave_up or r.proc is None
                            or r.proc.poll() is None):
                        continue
                    due = self._relaunch_at.get(r.index)
                    if due is None:
                        rc = r.proc.returncode
                        outcome = classify_exit(rc)
                        self._log({"event": "exit", "replica": r.index,
                                   "rc": rc, "outcome": outcome})
                        if outcome == "clean":
                            # someone asked it to stop; honor that
                            r.gave_up = True
                            continue
                        if r.restarts >= self.max_restarts:
                            self._log({
                                "event": "give_up", "replica": r.index,
                                "restarts": r.restarts,
                            })
                            r.gave_up = True
                            continue
                        delay = backoff_s(r.restarts, self.backoff_base,
                                          self.backoff_max)
                        r.restarts += 1
                        self._relaunch_at[r.index] = now + delay
                        self._log({"event": "backoff", "replica": r.index,
                                   "delay_s": round(delay, 3),
                                   "restart": r.restarts})
                    elif due <= now:
                        del self._relaunch_at[r.index]
                        self._launch(r)

    def kill(self, index: int) -> None:
        """Chaos helper: SIGKILL one replica (uncatchable, no drain).
        Supervision relaunches it on the backoff schedule."""
        r = self.replicas[index]
        if r.alive():
            r.proc.send_signal(signal.SIGKILL)

    def wait_ready(self, index: int,
                   timeout_s: Optional[float] = None) -> bool:
        return wait_http_ready(
            self.replicas[index].url,
            self.ready_timeout_s if timeout_s is None else timeout_s,
        )

    # -- rolling restart ----------------------------------------------

    def rolling_restart(self, ready_check=None) -> None:
        """Drain-aware, one replica at a time; see module docstring.
        Raises when a replica fails to come back — continuing would
        take the NEXT replica down too and shrink the fleet to zero.

        ``ready_check(replica)`` (optional) gates the move to the next
        replica beyond the replica's own ``/ready``: pass a probe of
        the ROUTER's view (replica re-admitted, i.e. state ``up``) so
        the restart never drains replica k+1 while the router is still
        slow-re-admitting replica k — the zero-eligible window that
        would shed requests."""
        for r in self.replicas:
            with self._lock:
                r.expected_exit = True  # supervisor: hands off
                self._relaunch_at.pop(r.index, None)
            try:
                self._log({"event": "rolling_drain", "replica": r.index})
                if r.alive():
                    r.proc.send_signal(signal.SIGTERM)
                    try:
                        r.proc.wait(self.drain_exit_timeout_s)
                    except subprocess.TimeoutExpired:
                        self._log({"event": "drain_timeout_kill",
                                   "replica": r.index})
                        r.proc.kill()
                        r.proc.wait(10)
                self._launch(r)
                if not wait_http_ready(r.url, self.ready_timeout_s):
                    raise RuntimeError(
                        f"replica {r.index} ({r.url}) did not come back "
                        f"within {self.ready_timeout_s}s after rolling "
                        "restart"
                    )
                if ready_check is not None:
                    end = time.monotonic() + self.ready_timeout_s
                    while not ready_check(r):
                        if time.monotonic() >= end:
                            raise RuntimeError(
                                f"replica {r.index} ({r.url}) ready but "
                                "not re-admitted (ready_check) within "
                                f"{self.ready_timeout_s}s"
                            )
                        time.sleep(0.05)
                with self._lock:
                    # a deliberate operator restart grants a fresh
                    # supervision lease — without this, a replica that
                    # had exhausted its budget (or exited cleanly once)
                    # would be revived yet silently unsupervised
                    r.gave_up = False
                    r.restarts = 0
                self._log({"event": "rolling_done", "replica": r.index})
            finally:
                with self._lock:
                    r.expected_exit = False

    # -- shutdown ------------------------------------------------------

    def stop(self, drain: bool = True) -> None:
        """SIGTERM everything (graceful drain), escalate to SIGKILL on
        stragglers, stop supervision."""
        self._stop.set()
        with self._lock:
            for r in self.replicas:
                r.expected_exit = True
        if self._supervisor is not None:
            self._supervisor.join(5.0)
            self._supervisor = None
        for r in self.replicas:
            if r.alive():
                r.proc.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL
                )
        deadline = time.monotonic() + (
            self.drain_exit_timeout_s if drain else 10.0
        )
        for r in self.replicas:
            if r.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                r.proc.wait(left)
            except subprocess.TimeoutExpired:
                self._log({"event": "stop_kill", "replica": r.index})
                r.proc.kill()
                r.proc.wait(10)
            self._log({"event": "stopped", "replica": r.index,
                       "rc": r.proc.returncode})
        # SIGTERM path: the buffered event tail must land (the atexit
        # net in obs/events.py is the last resort, not the plan)
        self._events.close()


def main() -> None:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--base-port", type=int, default=0,
                   help="first replica port (consecutive from here); "
                        "0 = OS-assigned free ports")
    p.add_argument("--router-port", type=int, default=8000)
    p.add_argument("--max-restarts", type=int, default=3,
                   help="per-replica crash-relaunch budget")
    p.add_argument("--backoff-base", type=float, default=0.5)
    p.add_argument("--backoff-max", type=float, default=10.0)
    p.add_argument("--ready-timeout", type=float, default=120.0)
    p.add_argument("--fleet-log", default=None,
                   help="append one JSON line per fleet event "
                        "(obs/events.py shape)")
    p.add_argument("--router-trace-path", default=None,
                   help="write the IN-PROCESS router's span trace "
                        "(pick/forward/retry/hedge; the clock "
                        "reference tools/trace_stitch.py wants first) "
                        "to this path")
    p.add_argument("--router-event-log", default=None,
                   help="append the router's structured JSONL events "
                        "(request finished/failed/retried, replica "
                        "ejection/re-admission) to this path")
    p.add_argument("--hedge-factor", type=float, default=0.0,
                   help="router hedging knob (0 = off); see "
                        "RouterConfig.hedge_factor")
    p.add_argument("server_args", nargs=argparse.REMAINDER,
                   help="-- then extra serving.server CLI args passed "
                        "to every replica")
    args = p.parse_args()

    server_args = list(args.server_args)
    if server_args and server_args[0] == "--":
        server_args = server_args[1:]
    ports = None
    if args.base_port:
        ports = [args.base_port + i for i in range(args.replicas)]

    fleet = Fleet(
        args.replicas, server_args=server_args, host=args.host,
        ports=ports, max_restarts=args.max_restarts,
        backoff_base=args.backoff_base, backoff_max=args.backoff_max,
        ready_timeout_s=args.ready_timeout, fleet_log=args.fleet_log,
    )
    print(f"[fleet] launching {args.replicas} replicas: "
          f"{fleet.urls}", file=sys.stderr)
    fleet.start()

    # the router rides in this process: stdlib-only import chain
    from differential_transformer_replication_tpu.config import (
        RouterConfig,
    )
    from differential_transformer_replication_tpu.serving.router import (
        Router,
        serve_router,
    )

    router_tracer = None
    if args.router_trace_path:
        from differential_transformer_replication_tpu.obs.spans import (
            SpanTracer,
        )

        router_tracer = SpanTracer(args.router_trace_path,
                                   process_name="router")
    router = Router(
        fleet.urls,
        RouterConfig(hedge_factor=args.hedge_factor),
        tracer=router_tracer,
        events=open_event_log(args.router_event_log, process="router"),
    ).start()
    httpd = serve_router(router, args.host, args.router_port)

    stopping = threading.Event()

    def _stop_all(signum, frame):
        del frame
        print(f"[fleet] signal {signum}: stopping fleet", file=sys.stderr)
        stopping.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    by_url = {rep.url: rep for rep in router.replicas}

    def _router_readmitted(r) -> bool:
        # gate the rolling restart on the ROUTER's view, not just the
        # replica's own /ready — see Fleet.rolling_restart
        rep = by_url.get(r.url)
        return rep is None or rep.eligible()

    def _rolling(signum, frame):
        del frame
        print("[fleet] SIGHUP: rolling restart", file=sys.stderr)

        def run():
            try:
                fleet.rolling_restart(ready_check=_router_readmitted)
            except Exception as e:
                print(f"[fleet] rolling restart FAILED: {e!r}",
                      file=sys.stderr)

        threading.Thread(target=run, daemon=True).start()

    signal.signal(signal.SIGTERM, _stop_all)
    signal.signal(signal.SIGINT, _stop_all)
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _rolling)

    print(f"[fleet] router on http://{args.host}:{args.router_port} "
          f"over {fleet.urls} — SIGHUP = rolling restart",
          file=sys.stderr)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        router.close()
        if router_tracer is not None:
            router_tracer.close()
        router.events.close()
        fleet.stop()


if __name__ == "__main__":
    main()
