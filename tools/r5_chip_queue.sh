#!/bin/bash
# Round-5 sequential chip jobs, launched after the control 40k leg exits.
# Each stage logs to results/; failures don't block later stages.
set -u
cd /root/repo

CONTROL_PID=${1:?usage: r5_chip_queue.sh <control_train_pid>}
while kill -0 "$CONTROL_PID" 2>/dev/null; do sleep 20; done
echo "[queue] control trainer exited at $(date)"

# 1. Attention probe on the control 40k checkpoints (3 seeds), matching
#    the diff probes already recorded. The probe must use the PER-RUN
#    IMMUTABLE tokenizer copy (tokenizer/cache-<key>/), not the shared
#    mutable `tokenizer` dir a concurrent run can clobber (ADVICE r5
#    finding 2) — resolve it by matching the checkpoint's recorded
#    content fingerprint against the cache entries; fall back to the
#    shared dir (the fingerprint guard still aborts loudly on mismatch).
TOK_DIR=$(python - results/recipe40k_control/best.ckpt tokenizer <<'EOF'
import glob, json, sys
from differential_transformer_replication_tpu.data.tokenizer import (
    load_tokenizer, tokenizer_fingerprint,
)
ckpt, tokdir = sys.argv[1], sys.argv[2]
try:
    want = json.load(open(f"{ckpt}/meta.json")).get("tokenizer_fingerprint")
except Exception:  # missing OR corrupt meta: degrade to the shared dir
    want = None
for d in sorted(glob.glob(f"{tokdir}/cache-*")):
    try:
        if want and tokenizer_fingerprint(load_tokenizer(d)) == want:
            print(d)
            break
    except Exception:
        pass
else:
    print(tokdir)
EOF
)
echo "[queue] probe tokenizer: $TOK_DIR"
for s in 0 1 2; do
  python tools/attn_probe.py \
    --checkpoint results/recipe40k_control/best.ckpt \
    --checkpoint results/recipe40k_control/last.ckpt \
    --tokenizer "$TOK_DIR" --corpus /tmp/imgcorpus4/image_corpus.txt \
    --trials 8 --seed $s --out results/attn_probe_control40k_s$s.json \
    || echo "[queue] control probe seed $s FAILED"
done
echo "[queue] probes done $(date)"

# 2. Five-config bench on the round-5 kernels.
python tools/bench_configs.py --out results/bench_configs_r5.json \
  || echo "[queue] bench_configs FAILED"
echo "[queue] bench_configs done $(date)"

# 3. Batched decode bench (VERDICT r4 item 5).
python tools/decode_bench.py --batches 1 8 32 --new-tokens 1024 \
  --out results/decode_bench_r5.json \
  || echo "[queue] decode_bench FAILED"
echo "[queue] decode_bench done $(date)"

# 4. Saturated matched-wall-clock leg, seeds 1338/1339 (VERDICT item 7;
#    protocol of results/ppl_gap_image_mwc_s1337.json).
for s in 1338 1339; do
  python tools/ppl_gap.py --models diff --iters 5253 \
    --n-layer 8 --n-embd 768 --n-head 4 --block-size 512 \
    --vocab-size 12000 \
    --dataset /tmp/imgcorpus/image_corpus.txt --num-train-samples 200000 \
    --eval-iters 100 --seed $s --attention-impl xla \
    --out results/ppl_gap_image_mwc_s$s.json \
    || echo "[queue] mwc seed $s FAILED"
done
echo "[queue] ALL DONE $(date)"
