#!/usr/bin/env python
"""Summarize a run's metrics.jsonl; optionally gate on it (``--check``).

Reads the trainer's JSONL stream (train/metrics.py) and prints ONE JSON
summary line — loss trajectory, step-time percentiles, data-stall
fraction, anomaly-guard totals, throughput, and the continuous
profiler's device_profile rows (last-seen MFU/busy-ms + capture-failure
count; obs/device_profile.py) — so a post-run script (or a human) gets
the health of a run without scraping stdout::

    python tools/metrics_report.py metrics.jsonl
    python tools/metrics_report.py metrics.jsonl --check \
        --max-stall-frac 0.5 --require-loss-decrease

``--check`` exits non-zero (listing every violated gate on stderr) when
the run looks unhealthy: non-finite losses, loss not decreasing, too
much data stall, too many guard skips/rollbacks. Restart-aware: the
stream may contain multiple ``run_header`` records (supervisor
relaunches append); the summary covers the whole stream and reports the
incarnation count.
"""

from __future__ import annotations

import argparse
import json
import math
import sys


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q / 100 * (len(xs) - 1)))))
    return xs[idx]


def load(path: str) -> dict:
    headers, steps, evals, intro, device = [], [], [], [], []
    quality = []
    unknown: dict = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed run
            kind = rec.get("record")
            if kind == "run_header":
                headers.append(rec)
            elif kind == "introspection":
                intro.append(rec)
            elif kind == "device_profile":
                # the continuous profiler's rows (obs/device_profile.py)
                device.append(rec)
            elif kind == "quality":
                # serving-side model-quality rows (obs/quality.py:
                # quality_row — entropy/margin means, PSI drift, λ)
                quality.append(rec)
            elif kind is not None:
                # typed records this tool does not understand are
                # COUNTED, not silently dropped — a new record type
                # shows up in the summary the day it ships
                unknown[kind] = unknown.get(kind, 0) + 1
            elif "val_loss" in rec:
                evals.append(rec)
            elif "loss" in rec:
                steps.append(rec)
    return {"headers": headers, "steps": steps, "evals": evals,
            "intro": intro, "device": device, "quality": quality,
            "unknown": unknown}


def summarize(recs: dict) -> dict:
    steps, evals = recs["steps"], recs["evals"]
    losses = [r["loss"] for r in steps]
    step_ms = [r["step_time_ms"] for r in steps if "step_time_ms" in r]
    stall = [r["data_wait_frac"] for r in steps if "data_wait_frac" in r]
    tps = [r["tokens_per_sec"] for r in steps if "tokens_per_sec" in r]
    out = {
        "run_headers": len(recs["headers"]),
        "config_hashes": sorted(
            {h.get("config_hash") for h in recs["headers"]} - {None}
        ),
        # build-info identity (mirrors the build_info gauge on
        # /metrics): which runtimes produced this stream — aggregated
        # streams with mixed versions are a red flag worth surfacing
        "jax_versions": sorted(
            {h.get("jax_version") for h in recs["headers"]} - {None}
        ),
        "roles": sorted(
            {h.get("role", "trainer") for h in recs["headers"]}
        ) if recs["headers"] else [],
        "step_records": len(steps),
        "eval_records": len(evals),
        "introspection_records": len(recs["intro"]),
    }
    if losses:
        out["loss_first"] = losses[0]
        out["loss_last"] = losses[-1]
        out["loss_min"] = min(losses)
        out["loss_all_finite"] = all(math.isfinite(v) for v in losses)
    if evals:
        out["val_loss_last"] = evals[-1]["val_loss"]
        out["val_loss_best"] = min(r["val_loss"] for r in evals)
    if step_ms:
        out["step_time_ms_p50"] = _percentile(step_ms, 50)
        out["step_time_ms_p95"] = _percentile(step_ms, 95)
        out["step_time_ms_p99"] = _percentile(step_ms, 99)
    if stall:
        out["data_stall_frac_mean"] = round(sum(stall) / len(stall), 4)
    if tps:
        out["tokens_per_sec_mean"] = round(sum(tps) / len(tps), 1)
    skips = [r["skipped_steps"] for r in steps if "skipped_steps" in r]
    rolls = [r["rollbacks"] for r in steps if "rollbacks" in r]
    if skips:
        out["skipped_steps_total"] = skips[-1]  # cumulative counter
    if rolls:
        out["rollbacks_total"] = rolls[-1]
    compiles = [r["compile_events"] for r in steps if "compile_events" in r]
    if compiles:
        out["compile_events_last"] = compiles[-1]
    device = recs.get("device", [])
    if device:
        # continuous on-device profiling (obs/device_profile.py): the
        # last-seen derived MFU and the cumulative capture/failure
        # counts the rows carry
        out["device_profile_records"] = len(device)
        mfus = [r["mfu"] for r in device if "mfu" in r]
        if mfus:
            out["device_mfu_last"] = mfus[-1]
        busy = [r["busy_ms"] for r in device if "busy_ms" in r]
        if busy:
            out["device_busy_ms_last"] = busy[-1]
        fails = [
            r["capture_failures"] for r in device
            if "capture_failures" in r
        ]
        out["device_profile_capture_failures"] = (
            fails[-1] if fails
            else sum(1 for r in device if "error" in r)
        )
    quality = recs.get("quality", [])
    if quality:
        out["quality_records"] = len(quality)
        drifts = [
            r["drift"] for r in quality
            if isinstance(r.get("drift"), (int, float))
            and not math.isnan(r["drift"])
        ]
        if drifts:
            out["quality_drift_max"] = round(max(drifts), 6)
        for key in ("entropy_mean", "margin_mean"):
            vals = [r[key] for r in quality
                    if isinstance(r.get(key), (int, float))]
            if vals:
                out[f"quality_{key}_last"] = vals[-1]
    if recs.get("unknown"):
        out["unknown_records"] = recs["unknown"]
    return out


def check(summary: dict, args) -> list:
    """Gate violations; empty = healthy."""
    bad = []
    if summary["step_records"] == 0:
        bad.append("no step records found")
        return bad
    if not summary.get("loss_all_finite", True):
        bad.append("non-finite loss values in the stream")
    if args.require_loss_decrease and summary.get("loss_last", 0) >= \
            summary.get("loss_first", 0):
        bad.append(
            f"loss did not decrease ({summary.get('loss_first')} -> "
            f"{summary.get('loss_last')})"
        )
    stall = summary.get("data_stall_frac_mean")
    if stall is not None and stall > args.max_stall_frac:
        bad.append(
            f"data stall fraction {stall} > {args.max_stall_frac} "
            "(input pipeline is starving the device)"
        )
    if summary.get("skipped_steps_total", 0) > args.max_skipped:
        bad.append(
            f"{summary['skipped_steps_total']} anomaly-guard skips > "
            f"{args.max_skipped}"
        )
    if summary.get("rollbacks_total", 0) > args.max_rollbacks:
        bad.append(
            f"{summary['rollbacks_total']} rollbacks > {args.max_rollbacks}"
        )
    if args.max_compile_events and summary.get(
        "compile_events_last", 0
    ) > args.max_compile_events:
        bad.append(
            f"{summary['compile_events_last']} train-step compile "
            f"entries > {args.max_compile_events} (retrace pathology)"
        )
    if summary.get(
        "device_profile_capture_failures", 0
    ) > args.max_capture_failures:
        bad.append(
            f"{summary['device_profile_capture_failures']} device-"
            f"profile capture failures > {args.max_capture_failures} "
            "(the continuous profiler is not landing its samples)"
        )
    max_drift = getattr(args, "max_drift", 0.0)
    if max_drift and summary.get("quality_drift_max", 0.0) > max_drift:
        bad.append(
            f"quality drift {summary['quality_drift_max']} > "
            f"{max_drift} (PSI vs reference fingerprint; "
            "obs/quality.py)"
        )
    return bad


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics", nargs="?", default=None,
                   help="path to a run's metrics.jsonl")
    p.add_argument("--from-metrics-jsonl", default=None, dest="from_jsonl",
                   help="same as the positional path — the flag shared "
                        "with tools/slo_report.py so CI gates can point "
                        "both tools at one stream with one spelling")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when any health gate fails")
    p.add_argument("--require-loss-decrease", action="store_true",
                   help="gate: last logged loss must be below the first")
    p.add_argument("--max-stall-frac", type=float, default=0.9,
                   help="gate: mean data_wait_frac ceiling")
    p.add_argument("--max-skipped", type=int, default=0,
                   help="gate: anomaly-guard skipped-step budget")
    p.add_argument("--max-rollbacks", type=int, default=0,
                   help="gate: anomaly-guard rollback budget")
    p.add_argument("--max-compile-events", type=int, default=0,
                   help="gate: train-step compile-cache ceiling "
                        "(0 = gate off; steady state is 1)")
    p.add_argument("--max-capture-failures", type=int, default=0,
                   help="gate: device-profile capture-failure budget "
                        "(obs/device_profile.py; applies only when the "
                        "stream carries device_profile records)")
    p.add_argument("--max-drift", type=float, default=0.0,
                   help="gate: quality-drift ceiling over the stream's "
                        '{"record": "quality"} rows (PSI vs reference '
                        "fingerprint, obs/quality.py; 0 = gate off)")
    args = p.parse_args()

    path = args.from_jsonl or args.metrics
    if not path:
        p.error("give a metrics.jsonl path (positional or "
                "--from-metrics-jsonl)")
    if args.from_jsonl and args.metrics:
        p.error("give the path once, not both positionally and via "
                "--from-metrics-jsonl")
    summary = summarize(load(path))
    print(json.dumps(summary))
    if args.check:
        bad = check(summary, args)
        for b in bad:
            print(f"CHECK FAILED: {b}", file=sys.stderr)
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
