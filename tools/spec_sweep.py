"""Speculative-decoding sweep — ffn_sweep.py's sibling for
serving/spec.py + the fused multi-row verify step.

One JSON line per case, sweeping:

  - drafter kind: the n-gram/prompt-lookup fallback, a model drafter
    sharing the target's params ("self" — the acceptance~1 upper bound
    of the verify machinery), or a smaller random-init control drafter
    beside each target family (the realistic pairing; random weights
    mean near-zero acceptance, which is exactly the overhead floor
    worth charting),
  - draft length k (the compiled verify-ladder rung),
  - verify formulation: "exact" (bit-identical unroll) vs "batched"
    (the fused multi-query kernel pass),
  - target family (control / diff / ndiff).

Each case runs the SAME greedy workload non-spec and spec-enabled on
fresh engines (jitted closures are module-cached, so the measured pass
is warm) and reports acceptance rate, tok/s for both arms, the
speedup, and greedy token agreement.

    python tools/spec_sweep.py [--draft-lens 2,4,8] [--requests 16]
    python tools/spec_sweep.py --smoke    # tier-1 CI gate: parity-
                                          # asserted tiny cases, seconds
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import numpy as np

from serve_bench import spec_workload  # noqa: E402  (shared driver)


def run_case(model_cfg, params, drafter, mode, verify, k, prompts,
             new_tokens, clients, seed):
    """One sweep case: baseline + spec arms, warm pass + measured pass
    each. Returns the JSON-ready result dict."""
    from differential_transformer_replication_tpu.config import (
        ServingConfig,
    )
    from differential_transformer_replication_tpu.serving import (
        ServingClient,
        ServingEngine,
    )

    def _arm(spec_on):
        serving = ServingConfig(
            num_slots=min(8, len(prompts)), prefill_chunk=8,
            prefill_budget=32,
            spec_mode=mode if spec_on else "",
            spec_draft_len=k, spec_verify=verify,
            max_seq_len=model_cfg.block_size + new_tokens,
        )
        stats = None
        for _ in range(2):  # warm pass, then measured pass
            engine = ServingEngine(
                params, model_cfg, serving,
                spec_drafter=drafter if spec_on else None,
            )
            client = ServingClient(engine)
            wall, toks, outs = spec_workload(
                client, prompts, new_tokens, clients, seed, 0.0
            )
            stats = engine.spec_stats() if spec_on else None
            client.close()
        return wall, toks, outs, stats

    b_wall, b_toks, b_out, _ = _arm(False)
    s_wall, s_toks, s_out, stats = _arm(True)
    total = sum(len(t) for t in b_out.values())
    agree = sum(
        1 for i, t in b_out.items()
        for a, b in zip(t, s_out.get(i, [])) if a == b
    )
    b_tps = b_toks / b_wall
    s_tps = s_toks / s_wall
    return {
        "metric": "spec_sweep_case",
        "model": model_cfg.model,
        "drafter": mode if mode == "ngram" else "model",
        "spec_verify": verify,
        "draft_len": k,
        "acceptance_rate": stats["acceptance_rate"],
        "proposed": stats["proposed"],
        "accepted": stats["accepted"],
        "baseline_tok_per_s": round(b_tps, 1),
        "spec_tok_per_s": round(s_tps, 1),
        "speedup": round(s_tps / b_tps, 3) if b_tps else None,
        "greedy_token_match_rate": round(agree / max(1, total), 5),
        "n_requests": len(prompts),
        "new_tokens": new_tokens,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--models", default="control,diff,ndiff")
    p.add_argument("--draft-lens", default="2,4,8")
    p.add_argument("--verify", default="exact,batched")
    p.add_argument("--drafters", default="ngram,self,control",
                   help="comma list: ngram | self (model drafter = "
                        "target params) | control (small random-init "
                        "control drafter)")
    p.add_argument("--n-embd", type=int, default=64)
    p.add_argument("--n-layer", type=int, default=2)
    p.add_argument("--n-head", type=int, default=2)
    p.add_argument("--block-size", type=int, default=64)
    p.add_argument("--vocab-size", type=int, default=128)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="also append the JSON lines to this file")
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 CI gate: one tiny case per drafter "
                        "kind, greedy parity ASSERTED for the exact "
                        "verify mode")
    args = p.parse_args()

    if args.smoke:
        args.models = "control"
        args.draft_lens = "4"
        args.verify = "exact,batched"
        args.drafters = "ngram,self"
        args.n_embd, args.n_layer, args.block_size = 32, 2, 32
        args.vocab_size, args.requests, args.clients = 61, 6, 3
        args.new_tokens = 10

    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
    )
    from differential_transformer_replication_tpu.models import (
        init_model,
    )

    rng = np.random.default_rng(args.seed)
    lines = []
    for kind in args.models.split(","):
        cfg = ModelConfig(
            model=kind, vocab_size=args.vocab_size, n_embd=args.n_embd,
            n_head=args.n_head, n_layer=args.n_layer,
            block_size=args.block_size, dropout=0.0, n_terms=3,
            compute_dtype="float32",
        )
        params = init_model(jax.random.PRNGKey(args.seed), cfg)
        max_prompt = max(2, args.block_size - args.new_tokens - 1)
        prompts = []
        for _ in range(args.requests):
            n = int(rng.integers(2, min(12, max_prompt) + 1))
            period = int(rng.integers(2, min(5, n + 1)))
            cyc = rng.integers(0, args.vocab_size, size=period).tolist()
            prompts.append((cyc * (n // period + 1))[:n])
        for dk in args.drafters.split(","):
            if dk == "ngram":
                mode, drafter = "ngram", None
            elif dk == "self":
                mode, drafter = "model", (params, cfg)
            else:  # a smaller random-init control drafter
                d_cfg = ModelConfig(
                    model="control", vocab_size=args.vocab_size,
                    n_embd=max(16, args.n_embd // 2), n_head=args.n_head,
                    n_layer=1, block_size=args.block_size, dropout=0.0,
                    compute_dtype="float32",
                )
                mode = "model"
                drafter = (
                    init_model(jax.random.PRNGKey(args.seed + 1), d_cfg),
                    d_cfg,
                )
            for verify in args.verify.split(","):
                for k in (int(x) for x in args.draft_lens.split(",")):
                    line = run_case(
                        cfg, params, drafter, mode, verify, k, prompts,
                        args.new_tokens, args.clients, args.seed,
                    )
                    line["drafter"] = dk
                    print(json.dumps(line))
                    lines.append(line)
                    if args.smoke and verify == "exact":
                        assert line["greedy_token_match_rate"] == 1.0, (
                            f"exact-verify greedy parity broke: {line}"
                        )
                    if args.smoke and dk == "self":
                        assert line["acceptance_rate"] == 1.0, (
                            f"self-drafter must accept everything: "
                            f"{line}"
                        )
    if args.out:
        with open(args.out, "a") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")


if __name__ == "__main__":
    main()
