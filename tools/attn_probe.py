"""Mechanistic attention-allocation probe: signal vs noise at a query.

The Differential Transformer paper's §3.3 probe (arXiv:2410.05258): embed
one NEEDLE sentence carrying an answer span inside a context of distractor
prose, append a query that asks for the answer, and measure how much
attention the final query position allocates to the answer span versus the
distractor context. The paper's claim — the motivation for the whole
architecture (diff_transformer.py:70: ``att1 - lam*att2``) — is that
differential attention cancels attention noise: more mass on the answer,
less on distractors, than a parameter-matched vanilla control. This probe
measures that claim DIRECTLY on trained checkpoints, independent of
val-loss regimes (VERDICT r3 item 3: the val-loss signal drowns under
memorization on the image corpus; attention allocation does not).

Method. For each trial: draw distractor documents from a corpus file,
splice the needle's token sequence at a controlled depth, end the window
with the query prefix (the needle sentence minus its answer), and run the
checkpointed model capturing each layer's attention row at the final
position. The residual stream itself is advanced by the MODEL'S OWN
``block_forward`` (models/{control,diff}.py) — the probe only recomputes
the per-layer attention maps (projection + softmax math mirrored from
``_attn``; diff maps are the signed ``a1 - lam*a2`` rows). Reported per
model: the fraction of (absolute) attention row mass on the answer span,
on the needle sentence, and on the distractor context ("noise"), plus the
paper's signal-to-noise ratio, averaged over heads and layers and broken
out by needle depth.

    python tools/attn_probe.py --checkpoint results/recipe40k/best.ckpt \
        --tokenizer tokenizer/cache-<key> --corpus image_corpus.txt
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _load_model(ckpt: str):
    from differential_transformer_replication_tpu.train.checkpoint import (
        load_params_for_inference,
    )

    params, model_cfg, meta = load_params_for_inference(ckpt)
    return params, model_cfg, meta.get("tokenizer_fingerprint")


def _attention_rows(params, cfg, idx):
    """Per-layer signed attention rows of the FINAL position:
    list of (H, T) float32 arrays, one per layer. The stream advances via
    the model's own block_forward; only the maps are recomputed here
    (mirroring models/control.py:_attn and models/diff.py:_attn)."""
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.models import model_module
    from differential_transformer_replication_tpu.ops import (
        apply_rope,
        causal_mask,
        rope_cos_sin,
    )
    from differential_transformer_replication_tpu.ops.attention import (
        masked_softmax,
    )
    from differential_transformer_replication_tpu.ops.lambdas import (
        diff_lambda,
        lambda_init_schedule,
    )
    from differential_transformer_replication_tpu.models import common

    mod = model_module(cfg)
    B, T = idx.shape
    x = mod.embed(params, idx, cfg)
    cos, sin = (
        rope_cos_sin(cfg.head_size, T) if cfg.model != "diff" else (None, None)
    )
    mask = causal_mask(T)
    rows = []
    for li, blk in enumerate(params["blocks"], 1):
        xn = common.apply_layer_norm(x, blk["ln1"])
        p = blk["attn"]
        scale = 1.0 / math.sqrt(cfg.head_size)
        if cfg.model == "control":
            q = jnp.einsum("bte,ehd->bthd", xn, p["wq"].astype(xn.dtype))
            k = jnp.einsum("bte,ehd->bthd", xn, p["wk"].astype(xn.dtype))
            q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
            s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
            att = masked_softmax(s, mask)  # (B, H, T, T) f32
            rows.append(att[:, :, -1, :])
        elif cfg.model == "diff":
            qs = jnp.einsum("bte,sehd->sbthd", xn, p["wq"].astype(xn.dtype))
            ks = jnp.einsum("bte,sehd->sbthd", xn, p["wk"].astype(xn.dtype))
            lam = diff_lambda(
                p["lambda_q"][0], p["lambda_k"][0],
                p["lambda_q"][1], p["lambda_k"][1],
                lambda_init_schedule(li),
            )
            a1 = masked_softmax(
                jnp.einsum("bthd,bshd->bhts", qs[0], ks[0]) * scale, mask
            )
            a2 = masked_softmax(
                jnp.einsum("bthd,bshd->bhts", qs[1], ks[1]) * scale, mask
            )
            att = a1 - lam[None, :, None, None] * a2  # signed map, :70
            rows.append(att[:, :, -1, :])
        else:  # ndiff: signed sum of n RoPE'd maps (Ndiff_transformer.py:117-123)
            from differential_transformer_replication_tpu.ops.lambdas import (
                ndiff_lambdas,
                ndiff_signs,
            )

            n = p["wq"].shape[0]
            qs = jnp.einsum("bte,nehd->nbthd", xn, p["wq"].astype(xn.dtype))
            ks = jnp.einsum("bte,nehd->nbthd", xn, p["wk"].astype(xn.dtype))
            qs, ks = apply_rope(qs, cos, sin), apply_rope(ks, cos, sin)
            lams = ndiff_lambdas(
                p["lambda_q"], p["lambda_k"], lambda_init_schedule(li)
            )
            coeff = ndiff_signs(n)[:, None].astype(jnp.float32) * lams
            att = sum(
                coeff[i][None, :, None, None]
                * masked_softmax(
                    jnp.einsum("bthd,bshd->bhts", qs[i], ks[i]) * scale, mask
                )
                for i in range(n)
            )
            rows.append(att[:, :, -1, :])
        x = mod.block_forward(x, blk, li, cfg, cos, sin, mask)
    return rows  # n_layer x (B, H, T)


def _build_windows(tok, corpus_lines, block_size, depth, trials, rng):
    """(tokens (trials, T), spans): each window = distractor prose with the
    needle spliced at ``depth`` fraction and the query prefix at the end.
    span = (answer_start, answer_end, needle_start, needle_end, query_start)
    token indices."""
    import numpy as np

    answers = ["porcupine", "copper", "lantern", "violet", "harbor",
               "walnut", "meteor", "saddle", "pepper", "granite"]
    windows, spans = [], []
    for t in range(trials):
        word = answers[t % len(answers)]
        needle = (
            f" The secret access code hidden in this report is {word}."
        )
        query = " The secret access code hidden in this report is"
        nd = tok.encode(needle).ids
        qy = tok.encode(query).ids
        ans = tok.encode(f" {word}.").ids
        # answer span = the needle's tail tokens matching the answer word
        a_len = len(ans)
        body_budget = block_size - len(nd) - len(qy)
        pre_n = int(body_budget * depth)
        pre, post = [], []
        while len(pre) < pre_n:
            pre.extend(tok.encode(rng.choice(corpus_lines)).ids)
        pre = pre[:pre_n]
        while len(post) < body_budget - pre_n:
            post.extend(tok.encode(rng.choice(corpus_lines)).ids)
        post = post[: body_budget - pre_n]
        toks = pre + nd + post + qy
        n_start = len(pre)
        windows.append(np.asarray(toks, np.int32))
        spans.append(
            (
                n_start + len(nd) - a_len,  # answer start
                n_start + len(nd),  # answer end
                n_start,
                n_start + len(nd),
                len(toks) - len(qy),
            )
        )
    return np.stack(windows), spans


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", required=True, action="append",
                   help="checkpoint dir (repeatable: probe several models "
                        "on identical windows)")
    p.add_argument("--tokenizer", required=True)
    p.add_argument("--corpus", required=True,
                   help="text file, one document per line (distractors)")
    p.add_argument("--depths", type=float, nargs="+",
                   default=[0.2, 0.5, 0.8])
    p.add_argument("--trials", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import numpy as np

    from differential_transformer_replication_tpu.data.tokenizer import (
        load_tokenizer,
    )

    tok = load_tokenizer(args.tokenizer)
    with open(args.corpus, encoding="utf-8") as f:
        corpus_lines = [l for l in f.read().splitlines() if len(l) > 200]

    if tok.get_vocab_size() == 0:
        raise SystemExit(f"empty tokenizer at {args.tokenizer!r}")

    results = {}
    from differential_transformer_replication_tpu.data.tokenizer import (
        check_tokenizer_matches,
    )

    for ckpt in args.checkpoint:
        params, cfg, fp = _load_model(ckpt)
        # fail loud on vocab-size AND content-fingerprint mismatches — a
        # wrong same-size tokenizer yields valid ids and silently
        # measures the model on gibberish windows (data/tokenizer.py)
        check_tokenizer_matches(tok, cfg.vocab_size, fp, context=ckpt)
        per_depth = {}
        for depth in args.depths:
            rng = random.Random(args.seed)  # identical windows per model
            windows, spans = _build_windows(
                tok, corpus_lines, cfg.block_size, depth, args.trials, rng
            )
            rows = _attention_rows(params, cfg, windows)
            frac_ans, frac_needle, frac_noise, snr = [], [], [], []
            for b, (a0, a1, n0, n1, q0) in enumerate(spans):
                # average |row| allocation over layers and heads
                for layer_rows in rows:
                    r = np.abs(np.asarray(layer_rows[b], np.float32))
                    total = r.sum(-1) + 1e-9  # (H,)
                    ans = r[:, a0:a1].sum(-1) / total
                    ndl = r[:, n0:n1].sum(-1) / total
                    ctx = (r[:, :n0].sum(-1) + r[:, n1:q0].sum(-1)) / total
                    frac_ans.append(ans.mean())
                    frac_needle.append(ndl.mean())
                    frac_noise.append(ctx.mean())
                    # per-token signal-to-noise: answer tokens vs mean
                    # distractor token (span sizes differ)
                    per_ans = r[:, a0:a1].mean(-1)
                    n_ctx = max(n0 + (q0 - n1), 1)
                    per_ctx = (r[:, :n0].sum(-1) + r[:, n1:q0].sum(-1)) / n_ctx
                    snr.append((per_ans / (per_ctx + 1e-9)).mean())
            per_depth[depth] = {
                "frac_answer": float(np.mean(frac_ans)),
                "frac_needle": float(np.mean(frac_needle)),
                "frac_distractors": float(np.mean(frac_noise)),
                "snr_per_token": float(np.mean(snr)),
            }
        results[ckpt] = {"model": cfg.model, "depths": per_depth}
        print(f"{ckpt} ({cfg.model}):")
        for d, m in per_depth.items():
            print(
                f"  depth {d}: answer {m['frac_answer']:.4f} | needle "
                f"{m['frac_needle']:.4f} | distractors "
                f"{m['frac_distractors']:.4f} | SNR {m['snr_per_token']:.2f}"
            )

    if args.out:
        with open(args.out, "w") as f:
            json.dump(
                {"config": vars(args), "results": results}, f, indent=1
            )


if __name__ == "__main__":
    main()
