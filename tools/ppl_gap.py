"""The Diff-vs-control perplexity-gap experiment.

The reference repo exists to show the Differential Transformer reaching a
lower val loss than a parameter-matched vanilla control (the paper's
claim, arXiv:2410.05258); its only instrument for that is eyeballing
wandb curves from manually re-commented train.py runs (train.py:205-230).
This harness runs the comparison as one command: train each requested
model family on the SAME data, seed, and recipe, evaluate on the same
held-out windows, and emit a JSON summary with val loss/PPL per family
and the diff-vs-control gap — the BASELINE.json north-star quantity.

Usage (defaults are a scaled-down recipe that finishes in minutes on one
chip; pass --full for the reference 8L/768d/40k recipe):

    python tools/ppl_gap.py --iters 2000 --out ppl_gap.json
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--models", nargs="+", default=["control", "diff"],
                   choices=["control", "diff", "ndiff"])
    p.add_argument("--iters", type=int, default=2000)
    p.add_argument("--n-layer", type=int, default=4)
    p.add_argument("--n-embd", type=int, default=256)
    p.add_argument("--n-head", type=int, default=4)
    p.add_argument("--block-size", type=int, default=256)
    p.add_argument("--micro-batch-size", type=int, default=32)
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--vocab-size", type=int, default=4096)
    p.add_argument("--num-train-samples", type=int, default=100_000)
    p.add_argument("--eval-iters", type=int, default=50)
    p.add_argument("--seed", type=int, default=1337)
    p.add_argument("--attention-impl", default="xla", choices=["xla", "pallas"])
    p.add_argument("--full", action="store_true",
                   help="preset: the FULL reference recipe (8L/768d/block-512/"
                        "40k iters, TinyStories 1M docs, BPE-12k, 200 eval "
                        "batches). Explicitly passed flags still win.")
    p.add_argument("--out", default="ppl_gap.json")
    args = p.parse_args()

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.trainer import train

    if args.full:
        # the reference recipe, train.py:57-93 — applied only where the
        # user left the default, so e.g. `--full --iters 5000` shortens
        # the run instead of being silently clobbered
        preset = dict(
            n_layer=8, n_embd=768, n_head=4, block_size=512, iters=40_000,
            vocab_size=12_000, dataset="tinystories",
            num_train_samples=1_000_000, eval_iters=200,
        )
        for name, value in preset.items():
            if getattr(args, name) == p.get_default(name):
                setattr(args, name, value)

    results = {}
    for kind in args.models:
        model = ModelConfig(
            model=kind,
            vocab_size=args.vocab_size,
            n_embd=args.n_embd,
            n_head=args.n_head,
            n_layer=args.n_layer,
            block_size=args.block_size,
            dropout=0.0,
            attention_impl=args.attention_impl,
            compute_dtype="bfloat16",
        )
        cfg = TrainConfig(
            model=model,
            micro_batch_size=args.micro_batch_size,
            max_iters=args.iters,
            eval_interval=max(args.iters // 4, 1),
            eval_iters=args.eval_iters,
            warmup_iters=min(1000, args.iters // 10),
            dataset=args.dataset,
            num_train_samples=args.num_train_samples,
            vocab_size=args.vocab_size,
            seed=args.seed,
            checkpoint_path=f"ppl_gap_{kind}.ckpt",
            metrics_path=f"ppl_gap_{kind}.jsonl",
        )
        print(f"=== training {kind} ({args.iters} iters) ===")
        t0 = time.time()
        train(cfg)
        # read the last eval record back for the final val loss — only the
        # primary process writes (and should report) on multi-host runs
        import jax

        if jax.process_index() != 0:
            continue
        val_loss = None
        with open(cfg.metrics_path) as f:
            for line in f:
                rec = json.loads(line)
                if "val_loss" in rec:
                    val_loss = rec["val_loss"]
        results[kind] = {
            "val_loss": val_loss,
            "val_ppl": math.exp(val_loss) if val_loss is not None else None,
            "wall_s": round(time.time() - t0, 1),
        }

    import jax

    if jax.process_index() != 0:
        return  # only the primary writes the summary
    summary = {"config": vars(args), "results": results}
    if "control" in results and "diff" in results:
        c, d = results["control"]["val_loss"], results["diff"]["val_loss"]
        if c is not None and d is not None:
            summary["diff_minus_control_val_loss"] = round(d - c, 5)
            summary["diff_vs_control_ppl_ratio"] = round(
                math.exp(d) / math.exp(c), 5
            )
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()
