"""The Diff-vs-control perplexity-gap experiment.

The reference repo exists to show the Differential Transformer reaching a
lower val loss than a parameter-matched vanilla control (the paper's
claim, arXiv:2410.05258); its only instrument for that is eyeballing
wandb curves from manually re-commented train.py runs (train.py:205-230).
This harness runs the comparison as one command: train each requested
model family on the SAME data, seed, and recipe, evaluate the FINAL
parameters on the same held-out windows, and emit a JSON summary with
val loss/PPL per family and the diff-vs-control gap — the BASELINE.json
north-star quantity.

Usage (defaults are a scaled-down recipe that finishes in minutes on one
chip; pass --full for the reference 8L/768d/40k recipe):

    python tools/ppl_gap.py --iters 2000 --out ppl_gap.json
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# small-scale defaults; --full swaps in the reference recipe for any flag
# the user did not pass explicitly (argparse defaults are None sentinels
# so "explicitly passed the small default" still wins over the preset)
_SMALL = dict(
    iters=2000, n_layer=4, n_embd=256, n_head=4, block_size=256,
    vocab_size=4096, dataset="synthetic", num_train_samples=100_000,
    eval_iters=50,
)
_FULL = dict(
    iters=40_000, n_layer=8, n_embd=768, n_head=4, block_size=512,
    vocab_size=12_000, dataset="tinystories", num_train_samples=1_000_000,
    eval_iters=200,
)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--models", nargs="+", default=["control", "diff"],
                   choices=["control", "diff", "ndiff"])
    for name, small in _SMALL.items():
        flag = "--" + name.replace("_", "-")
        p.add_argument(flag, type=type(small), default=None,
                       help=f"default {small} (with --full: {_FULL[name]})")
    p.add_argument("--micro-batch-size", type=int, default=32)
    p.add_argument("--seed", type=int, default=1337)
    p.add_argument("--attention-impl", default="xla", choices=["xla", "pallas"])
    p.add_argument("--full", action="store_true",
                   help="preset: the FULL reference recipe (8L/768d/block-512/"
                        "40k iters, TinyStories 1M docs, BPE-12k, 200 eval "
                        "batches, eval every 500). Explicit flags still win.")
    p.add_argument("--checkpoint-min-interval-s", type=float, default=0.0,
                   help="throttle best-checkpoint disk writes (trainer "
                        "flag; a recipe-scale write costs ~3 min on this "
                        "image's tunneled chip)")
    p.add_argument("--no-last-ckpt", action="store_true",
                   help="skip the resumable last-state checkpoint (saves "
                        "one multi-minute exit write per family when the "
                        "run will not be resumed)")
    p.add_argument("--out", default="ppl_gap.json")
    args = p.parse_args()

    preset = _FULL if args.full else _SMALL
    for name, value in preset.items():
        if getattr(args, name) is None:
            setattr(args, name, value)

    import jax
    import numpy as np

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.step import make_eval_many
    from differential_transformer_replication_tpu.train.trainer import (
        build_data,
        estimate_loss,
        train,
    )

    primary = jax.process_index() == 0
    results = {}
    for kind in args.models:
        model = ModelConfig(
            model=kind,
            vocab_size=args.vocab_size,
            n_embd=args.n_embd,
            n_head=args.n_head,
            n_layer=args.n_layer,
            block_size=args.block_size,
            dropout=0.0,
            attention_impl=args.attention_impl,
            compute_dtype="bfloat16",
        )
        cfg = TrainConfig(
            model=model,
            micro_batch_size=args.micro_batch_size,
            max_iters=args.iters,
            # the reference evaluates every 500 iters (train.py:71); for
            # short runs keep at least a mid-run checkpoint opportunity
            eval_interval=min(500, max(args.iters // 4, 1)),
            eval_iters=args.eval_iters,
            warmup_iters=min(1000, args.iters // 10),
            dataset=args.dataset,
            num_train_samples=args.num_train_samples,
            vocab_size=args.vocab_size,
            seed=args.seed,
            checkpoint_path=f"ppl_gap_{kind}.ckpt",
            last_checkpoint_path=(
                None if args.no_last_ckpt else f"ppl_gap_{kind}_last.ckpt"
            ),
            checkpoint_min_interval_s=args.checkpoint_min_interval_s,
            metrics_path=f"ppl_gap_{kind}.jsonl",
        )
        print(f"=== training {kind} ({args.iters} iters) ===")
        t0 = time.time()
        state = train(cfg)
        wall = round(time.time() - t0, 1)
        # evaluate the FINAL parameters directly — no metrics-file round
        # trip, and the number always reflects end-of-training exactly
        tokenizer, vocab_size, train_ds, val_ds = build_data(cfg)
        eval_cfg = cfg.replace(vocab_size=vocab_size)
        losses = estimate_loss(
            make_eval_many(eval_cfg), state["params"], train_ds, val_ds,
            eval_cfg, np.random.default_rng(cfg.seed + 1),
        )
        results[kind] = {
            "train_loss": losses["train"],
            "val_loss": losses["val"],
            "val_ppl": math.exp(losses["val"]),
            "wall_s": wall,
        }

    if not primary:
        return  # only the primary writes the summary
    summary = {"config": vars(args), "results": results}
    if "control" in results and "diff" in results:
        c, d = results["control"]["val_loss"], results["diff"]["val_loss"]
        summary["diff_minus_control_val_loss"] = round(d - c, 5)
        summary["diff_vs_control_ppl_ratio"] = round(math.exp(d) / math.exp(c), 5)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()
