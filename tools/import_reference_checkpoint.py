"""Convert a reference (PyTorch) checkpoint to this framework's format.

Reads either reference on-disk format — the ``best_model.pt`` training
blob (train.py:309-316) or an N-diff ``save_pretrained`` file
(Ndiff_transformer.py:251-265) — infers the model family and shapes from
the state_dict, maps the weights onto this framework's param pytree
(utils/torch_import.py), and writes a ``save_pretrained`` directory that
``sample.py`` and ``from_pretrained`` consume directly:

    python tools/import_reference_checkpoint.py best_model.pt imported/
    python sample.py --checkpoint imported/ --tokenizer tokenizer

Cross-implementation parity of the mapping (same logits/loss as the
reference's own forward) is pinned by tests/test_torch_import.py.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", help="reference best_model.pt or save_pretrained file")
    p.add_argument("out", help="output save_pretrained directory")
    args = p.parse_args()

    from differential_transformer_replication_tpu.models import param_count
    from differential_transformer_replication_tpu.train.checkpoint import (
        save_pretrained,
    )
    from differential_transformer_replication_tpu.utils.torch_import import (
        load_reference_checkpoint,
    )

    params, cfg = load_reference_checkpoint(args.checkpoint)
    save_pretrained(args.out, params, cfg)
    print(
        f"imported {args.checkpoint} -> {args.out}: model={cfg.model} "
        f"{cfg.n_layer}L/{cfg.n_embd}d/{cfg.n_head}-head block={cfg.block_size} "
        f"vocab={cfg.vocab_size} ({param_count(params):,} params)"
    )
    print(
        f"note: dropout={cfg.dropout} — best_model.pt blobs carry no "
        f"training hyperparameters, so this is the reference's training "
        f"default (train.py:64) unless the checkpoint's model_args said "
        f"otherwise; it only matters if you fine-tune from the import"
    )


if __name__ == "__main__":
    main()
