#!/usr/bin/env python
"""Render the lambda-evolution figure from a run's metrics.jsonl.

The Differential Transformer paper tracks the per-layer effective
lambda — the learned weight on the subtracted attention map — as it
drifts from its ``0.8 - 0.6*exp(-0.3*(l-1))`` init schedule over
training. The trainer logs exactly that every eval interval
(``{"record": "introspection", "iter": N, "lambda_l<k>[...]": v}``
records, obs/introspect.py), so the figure is reproducible from ANY
run's metrics.jsonl::

    python tools/lambda_report.py metrics.jsonl --out lambda_evolution.png

Diff runs plot one curve per layer; ndiff runs one per (layer, term);
control runs carry no lambdas (the tool says so and exits 0 — absence
is the expected answer there, not an error). With matplotlib missing
(or ``--ascii``) the series print as a text table instead, so the tool
works on bare metal.

``--serving`` additionally accepts the SERVING engine's
``{"record": "quality", ...}`` rows (obs/quality.py:quality_row, same
``lambda_l<k>`` / ``lambda_l<k>_t<j>`` key schema), so a live fleet's
λ view renders beside — or instead of — training introspection rows
from one stream with one flag.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

_LAMBDA_KEY = re.compile(r"^lambda_l(\d+)(?:_t(\d+))?$")


def load_series(path: str, records: tuple = ("introspection",)):
    """{(layer, term|None): [(iter, value), ...]} plus the init values
    {(layer, term|None): lambda_init}; term is None for diff runs.
    ``records`` selects which record kinds contribute rows — the
    ``--serving`` flag adds the engine's ``"quality"`` rows, which
    share the lambda key schema (obs/quality.py:quality_row)."""
    series = defaultdict(list)
    inits = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed run
            if rec.get("record") not in records:
                continue
            it = rec.get("iter", 0)
            for key, val in rec.items():
                m = _LAMBDA_KEY.match(key)
                if not m:
                    continue
                layer = int(m.group(1))
                term = int(m.group(2)) if m.group(2) is not None else None
                series[(layer, term)].append((it, float(val)))
                init = rec.get(f"lambda_init_l{layer}")
                if init is not None:
                    inits[(layer, term)] = float(init)
    return dict(series), inits


def _label(layer: int, term) -> str:
    return f"L{layer}" if term is None else f"L{layer} t{term}"


def render_ascii(series, inits, width: int = 64) -> str:
    lines = ["lambda evolution (rows: layer[/term]; columns: eval points)"]
    for key in sorted(series):
        pts = sorted(series[key])
        vals = [v for _, v in pts]
        init = inits.get(key)
        head = f"{_label(*key):>8s} init={init:.4f}" if init is not None \
            else f"{_label(*key):>8s}"
        shown = vals[-12:]
        lines.append(
            head + " | " + " ".join(f"{v:.4f}" for v in shown)
            + (f"  (last iter {pts[-1][0]})" if pts else "")
        )
    return "\n".join(lines)


def render_png(series, inits, out: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for key in sorted(series):
        pts = sorted(series[key])
        xs = [i for i, _ in pts]
        ys = [v for _, v in pts]
        (line,) = ax.plot(xs, ys, marker="o", markersize=2.5,
                          linewidth=1.2, label=_label(*key))
        init = inits.get(key)
        if init is not None:
            ax.axhline(init, color=line.get_color(), linestyle=":",
                       linewidth=0.7, alpha=0.5)
    ax.set_xlabel("iteration")
    ax.set_ylabel("effective λ (head mean)")
    ax.set_title("λ evolution (dotted: init schedule)")
    ax.legend(fontsize=7, ncols=2)
    fig.tight_layout()
    fig.savefig(out, dpi=150)
    print(f"wrote {out}")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics", help="path to a run's metrics.jsonl")
    p.add_argument("--out", default=None,
                   help="output PNG path (default: <metrics>.lambda.png)")
    p.add_argument("--ascii", action="store_true",
                   help="print a text table instead of writing a PNG")
    p.add_argument("--serving", action="store_true",
                   help='also render the serving engine\'s {"record": '
                        '"quality"} λ rows (obs/quality.py; shared '
                        "lambda_l<k> schema) beside training ones")
    args = p.parse_args()

    records = ("introspection", "quality") if args.serving \
        else ("introspection",)
    series, inits = load_series(args.metrics, records=records)
    if not series:
        print(
            "no lambda records found — a control-family run logs none "
            "(no differential attention), or the run predates the "
            "introspection records (obs/introspect.py)"
            + ("" if args.serving
               else "; serving quality rows need --serving")
        )
        return 0
    if args.ascii:
        print(render_ascii(series, inits))
        return 0
    try:
        render_png(series, inits, args.out or f"{args.metrics}.lambda.png")
    except ImportError:
        print("matplotlib unavailable; falling back to --ascii output\n")
        print(render_ascii(series, inits))
    return 0


if __name__ == "__main__":
    sys.exit(main())
