"""Per-op profile of the flagship train step — the one-shot CLI.

Captures a few steps under ``jax.profiler.trace`` and prints the
device-side XLA op breakdown (grouped + top ops). This is the workflow
that produced the step decompositions in BASELINE.md; the xplane
parsing itself lives in ``obs/xprof.py`` (a stdlib wire-format reader,
shared with the CONTINUOUS sampler ``obs/device_profile.py`` — this
tool is now a thin capture+report shell over that library).

    python tools/profile_step.py [--steps 5] [--attn pallas] [--top 25]
    python tools/profile_step.py --json          # one machine-readable line

``--json`` emits the grouped breakdown as ONE JSON line (grouped op
families, the custom-kernel buckets, device-busy ms/step, compile count)
so before/after MFU deltas are diffable in CI instead of eyeballed from
text. The fused Pallas kernels get their own buckets
(obs/xprof.py:KERNEL_BUCKETS): ``flash_attention`` (ops/flash.py),
``fused_ffn`` (ops/fused_ffn.py + ops/fused_norm_residual.py),
``decode_attention`` (ops/decode_attention.py ``_dattn_*`` kernels) and
``collectives`` (HLO communication ops). Without a TPU the breakdown
degrades to the host plane (plumbing-grade) or an explicit ``error``
field — never a crash.

The capture window runs inside ``RecompileSentinel(budget=0)`` exactly
like bench.py's measured window: a profile of a RETRACING step would
produce a misleading breakdown (compile time and duplicate programs in
the trace), so it fails loudly instead. ``--allow-recompiles N`` loosens
the pin (-1 disables), mirroring BENCH_ALLOW_RECOMPILES.

The reference has no profiling at all (SURVEY.md section 5.1 — its only
instrument is GPU-memory prints); this plus utils/profiling.py
(ProfilerWindow, Throughput) is the TPU-native observability stack.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def capture(args):
    import jax
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.analysis.sanitizers import (
        RecompileSentinel,
    )
    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.step import (
        create_train_state,
        make_train_step,
    )

    model = ModelConfig(
        model=args.model, vocab_size=args.vocab_size, n_embd=args.n_embd,
        n_head=args.n_head, n_layer=args.n_layer,
        block_size=args.block_size, dropout=0.0, compute_dtype=args.dtype,
        attention_impl=args.attn, ffn_impl=args.ffn,
    )
    cfg = TrainConfig(
        model=model, micro_batch_size=args.micro_batch, grad_acc_steps=1
    )
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    x = jax.random.randint(
        jax.random.PRNGKey(1), (1, args.micro_batch, model.block_size), 0,
        model.vocab_size,
    )
    batch = {"x": x, "y": jnp.roll(x, -1, -1)}
    for _ in range(3):  # compile + warm
        state, m = step(state, batch)
    _ = float(m["loss"])  # sync (block_until_ready lies on axon; BASELINE.md)

    out_dir = args.out or tempfile.mkdtemp(prefix="profile_step_")
    # a retracing step inside the capture window = a misleading profile;
    # fail loudly like bench.py's measured window (budget configurable)
    budget = None if args.allow_recompiles < 0 else args.allow_recompiles
    sentinel = RecompileSentinel(budget=budget, name="profile-capture-window")
    with sentinel:
        with jax.profiler.trace(out_dir):
            for _ in range(args.steps):
                state, m = step(state, batch)
            _ = float(m["loss"])
    return out_dir, sentinel.count


def report(out_dir: str, steps: int, top: int, compiles: int,
           as_json: bool) -> None:
    from differential_transformer_replication_tpu.obs.xprof import (
        summarize_trace,
    )

    parsed = summarize_trace(out_dir, steps=steps)
    if as_json:
        doc = {
            "metric": "profile_step_breakdown",
            "steps": steps,
            "compiles_in_window": compiles,
            "trace_dir": out_dir,
        }
        if isinstance(parsed, str):
            doc["error"] = parsed
        else:
            # which plane the numbers came from: plane_kind == "host"
            # means the plumbing-grade fallback (no device plane in
            # the capture — nested host events overcount), never to be
            # diffed against real device telemetry
            doc["plane"] = parsed["plane"]
            doc["plane_kind"] = parsed["plane_kind"]
            doc["device_busy_ms_per_step"] = round(
                parsed["busy_ms_per_step"], 3
            )
            doc["groups_ms_per_step"] = {
                k: round(v, 4) for k, v in sorted(
                    parsed["groups"].items(), key=lambda kv: -kv[1]
                )
            }
            doc["kernel_buckets_ms_per_step"] = {
                k: round(v, 4) for k, v in parsed["kernel_buckets"].items()
            }
        print(json.dumps(doc))
        return
    if isinstance(parsed, str):
        print(f"trace written to {out_dir} — {parsed}; open it in "
              "TensorBoard instead")
        return
    print(
        f"device busy: {parsed['busy_ms_per_step']:.2f} ms/step over "
        f"{steps} steps ({compiles} compiles in window; "
        f"{parsed['plane']} plane"
        + (" — HOST fallback, plumbing-grade numbers"
           if parsed["plane_kind"] == "host" else "")
        + ")\n"
    )
    print("grouped by op family (ms/step):")
    for k, ms in sorted(parsed["groups"].items(), key=lambda kv: -kv[1])[:15]:
        print(f"  {ms:8.3f}  {k}")
    if parsed["kernel_buckets"]:
        print("\ncustom-kernel buckets (ms/step):")
        for k, ms in sorted(
            parsed["kernel_buckets"].items(), key=lambda kv: -kv[1]
        ):
            print(f"  {ms:8.3f}  {k}")
    print(f"\ntop {top} ops (ms/step):")
    for name, ms in sorted(
        parsed["totals"].items(), key=lambda kv: -kv[1]
    )[:top]:
        print(
            f"  {ms / steps:7.3f} x{parsed['counts'][name] // steps:3d}  "
            f"{name[:110]}"
        )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--micro-batch", type=int, default=32)
    p.add_argument("--block-size", type=int, default=512)
    p.add_argument("--model", default="diff", choices=["control", "diff", "ndiff"])
    p.add_argument("--attn", default="pallas", choices=["xla", "pallas"])
    p.add_argument("--ffn", default="pallas", choices=["xla", "pallas"])
    p.add_argument("--dtype", default="bfloat16")
    # recipe-shape overrides so CI can profile a tiny model quickly
    p.add_argument("--n-embd", type=int, default=768)
    p.add_argument("--n-head", type=int, default=4)
    p.add_argument("--n-layer", type=int, default=8)
    p.add_argument("--vocab-size", type=int, default=12000)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--out", default=None, help="trace dir (default: temp)")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON line instead of text")
    p.add_argument("--allow-recompiles", type=int, default=0,
                   help="compile budget for the capture window "
                        "(default 0 = any retrace fails; -1 disables)")
    args = p.parse_args()
    out_dir, compiles = capture(args)
    report(out_dir, args.steps, args.top, compiles, args.json)


if __name__ == "__main__":
    main()
