"""Per-op profile of the flagship train step on the real TPU.

Captures a few steps under ``jax.profiler.trace`` and prints the
device-side XLA op breakdown (grouped + top ops) by parsing the xplane
protobuf with tensorflow's bundled proto (present in this image). This
is the workflow that produced the step decompositions in BASELINE.md.

    python tools/profile_step.py [--steps 5] [--attn pallas] [--top 25]

The reference has no profiling at all (SURVEY.md section 5.1 — its only
instrument is GPU-memory prints); this plus utils/profiling.py
(ProfilerWindow, Throughput) is the TPU-native observability stack.
"""

from __future__ import annotations

import argparse
import glob
import re
import sys
import tempfile
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def capture(args) -> str:
    import jax
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.step import (
        create_train_state,
        make_train_step,
    )

    model = ModelConfig(
        model=args.model, vocab_size=12000, n_embd=768, n_head=4, n_layer=8,
        block_size=args.block_size, dropout=0.0, compute_dtype="bfloat16",
        attention_impl=args.attn,
    )
    cfg = TrainConfig(
        model=model, micro_batch_size=args.micro_batch, grad_acc_steps=1
    )
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    x = jax.random.randint(
        jax.random.PRNGKey(1), (1, args.micro_batch, model.block_size), 0,
        model.vocab_size,
    )
    batch = {"x": x, "y": jnp.roll(x, -1, -1)}
    for _ in range(3):  # compile + warm
        state, m = step(state, batch)
    _ = float(m["loss"])  # sync (block_until_ready lies on axon; BASELINE.md)

    out_dir = args.out or tempfile.mkdtemp(prefix="profile_step_")
    with jax.profiler.trace(out_dir):
        for _ in range(args.steps):
            state, m = step(state, batch)
        _ = float(m["loss"])
    return out_dir


def report(out_dir: str, steps: int, top: int) -> None:
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        print(
            f"trace written to {out_dir} — tensorflow's xplane proto is not "
            f"importable here; open the trace in TensorBoard instead"
        )
        return

    paths = glob.glob(f"{out_dir}/plugins/profile/*/*.xplane.pb")
    if not paths:
        print(f"no xplane.pb under {out_dir}")
        return
    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())
    tpu = [p for p in xs.planes if p.name.startswith("/device:TPU")]
    if not tpu:
        print(f"no TPU plane in the trace (planes: {[p.name for p in xs.planes]})")
        return
    plane = tpu[0]
    meta = plane.event_metadata
    line = max(
        (l for l in plane.lines if l.name == "XLA Ops"),
        key=lambda l: len(l.events),
        default=None,
    )
    if line is None:
        print("no 'XLA Ops' line in the TPU plane")
        return

    totals: dict = defaultdict(float)
    counts: dict = defaultdict(int)
    groups: dict = defaultdict(float)
    for ev in line.events:
        name = meta[ev.metadata_id].name
        ms = ev.duration_ps / 1e9
        totals[name] += ms
        counts[name] += 1
        m = re.match(r"%([a-zA-Z_\.]+)", name)
        groups[m.group(1) if m else name[:24]] += ms

    total = sum(totals.values())
    print(f"device busy: {total / steps:.2f} ms/step over {steps} steps\n")
    print("grouped by op family (ms/step):")
    for k, ms in sorted(groups.items(), key=lambda kv: -kv[1])[:15]:
        print(f"  {ms / steps:8.3f}  {k}")
    print(f"\ntop {top} ops (ms/step):")
    for name, ms in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {ms / steps:7.3f} x{counts[name] // steps:3d}  {name[:110]}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--micro-batch", type=int, default=32)
    p.add_argument("--block-size", type=int, default=512)
    p.add_argument("--model", default="diff", choices=["control", "diff", "ndiff"])
    p.add_argument("--attn", default="pallas", choices=["xla", "pallas"])
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--out", default=None, help="trace dir (default: temp)")
    args = p.parse_args()
    out_dir = capture(args)
    report(out_dir, args.steps, args.top)


if __name__ == "__main__":
    main()
