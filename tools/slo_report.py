#!/usr/bin/env python
"""SLO burn-rate report + CI gate over a metrics source (``--check``).

The sibling of tools/metrics_report.py: where that tool judges a
TRAINING run from its metrics.jsonl, this one judges a SERVING target
(or the same training stream) against explicit objectives and prints
ONE JSON summary line — availability and TTFT/ITL burn rates — so a
bench script or CI job can gate on "are we inside the error budget"
with an exit code::

    # live endpoint (a replica's /metrics or the router's
    # /fleet/metrics — the fleet-wide gate):
    python tools/slo_report.py --url http://127.0.0.1:8000/fleet/metrics \
        --check --ttft 0.5 --target 0.99
    # a saved exposition snapshot (curl > metrics.txt):
    python tools/slo_report.py metrics.txt --check
    # the trainer's stream, same flag metrics_report.py takes:
    python tools/slo_report.py --from-metrics-jsonl metrics.jsonl \
        --check --step-time-ms 500

Burn-rate semantics (obs/slo.py): ``error_ratio / (1 - target)``;
1.0 = spending the budget exactly as provisioned, >1 = the objective
is being missed. ``--check`` exits non-zero when any evaluated
objective burns past ``--max-burn`` (default 1.0), listing each
violation on stderr — the same contract as ``metrics_report.py
--check`` and ``ckpt_doctor.py --check``.

Inputs:

- an exposition source (``--url`` or a file): latency objectives read
  the ``serving_ttft_seconds`` / ``serving_itl_seconds`` histograms,
  availability reads the completed/rejected/deadline counters — all
  summed fleet-wide when pointed at ``/fleet/metrics``;
- ``--from-metrics-jsonl``: the trainer's JSONL (shared input path
  with metrics_report.py) — the latency objective applies to
  ``step_time_ms`` against ``--step-time-ms``, availability to
  anomaly-guard skips (a skipped step is a failed step).

Objectives whose metric has no observations report null burn and do
NOT fail the gate by themselves (no traffic is not an outage) unless
``--require-traffic`` is set. Stdlib only, no jax.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import urllib.request

from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from differential_transformer_replication_tpu.obs.registry import (  # noqa: E402
    parse_exposition,
)
from differential_transformer_replication_tpu.obs.slo import (  # noqa: E402
    burn_rate,
    histogram_from_samples,
    latency_error_ratio,
)


def _counter_value(samples, name: str) -> float:
    return sum(v for n, labels, v in samples if n == name)


def report_from_exposition(text: str, args) -> dict:
    """Objectives over a scraped/saved text exposition."""
    _, samples = parse_exposition(text)
    out = {}
    # --class narrows the latency objectives to ONE priority class by
    # reading the engine's per-class histograms instead of the
    # aggregates (match= filters the labeled children before summing)
    cls = getattr(args, "priority_class", None)
    match = {"priority": cls} if cls else None
    latency_sources = (
        ("ttft", "serving_class_ttft_seconds", args.ttft),
        ("itl", "serving_class_itl_seconds", args.itl),
    ) if cls else (
        ("ttft", "serving_ttft_seconds", args.ttft),
        ("itl", "serving_itl_seconds", args.itl),
    )
    for objective, hist_name, threshold in latency_sources:
        bounds, cumulative, count = histogram_from_samples(
            samples, hist_name, match=match
        )
        err = latency_error_ratio(bounds, cumulative, count, threshold)
        out[objective] = {
            "threshold_s": threshold,
            "target": args.target,
            "count": count,
            "error_ratio": err,
            "burn_rate": burn_rate(err, args.target),
        }
        if cls:
            out[objective]["priority_class"] = cls
    good = _counter_value(samples, "serving_requests_completed_total")
    bad = (
        _counter_value(samples, "serving_requests_rejected_total")
        + _counter_value(
            samples, "serving_requests_deadline_expired_total"
        )
    )
    total = good + bad
    err = None if total <= 0 else bad / total
    out["availability"] = {
        "target": args.availability_target,
        "count": total,
        "error_ratio": err,
        "burn_rate": burn_rate(err, args.availability_target),
    }
    # staleness honesty: a fleet body stamps each replica's last-scrape
    # age (router probe loop, fleet_scrape_age_seconds). Replicas whose
    # stamp exceeds --max-scrape-age are reported as STALE and their
    # server-reported burn gauges dropped — judging a blackholed
    # replica by its last good scrape is how outages hide
    ages = {
        labels.get("replica", "unknown"): v
        for n, labels, v in samples
        if n == "fleet_scrape_age_seconds"
    }
    max_age = getattr(args, "max_scrape_age", 0.0) or 0.0
    stale = sorted(r for r, age in ages.items()
                   if max_age > 0 and age > max_age)
    if ages:
        out["scrape_age_seconds"] = {
            r: round(age, 3) for r, age in sorted(ages.items())
        }
    if stale:
        out["stale_replicas"] = stale
    # pre-computed burn gauges (obs/slo.py via each server) ride along
    # verbatim when present, so the report shows the servers' own view
    # — keyed per replica on a fleet body (aggregate_fleet_metrics
    # labels gauges `replica=`), so one hot replica cannot be hidden
    # behind a healthy one that happens to render later
    live = {}
    for n, labels, v in samples:
        if n != "slo_burn_rate":
            continue
        key = labels.get("objective", "unknown")
        if labels.get("replica"):
            if labels["replica"] in stale:
                continue  # stale body: treat its gauges as missing
            key = f'{key}@{labels["replica"]}'
        live[key] = v
    if live:
        out["server_reported_burn_rates"] = live
    # model-quality plane (obs/quality.py; replicas running
    # --quality-telemetry): PSI drift gauges keep per-replica identity
    # on a fleet body — drift_max is what --max-drift gates on —
    # entropy/margin means come from the cumulative histograms, and
    # validity is the WORST replica's constraint validity rate
    drifts = {}
    validity = None
    for n, labels, v in samples:
        if n == "serving_quality_drift":
            if labels.get("replica") in stale:
                continue
            drifts[labels.get("replica", "local")] = v
        elif n == "serving_constraint_validity_rate":
            if labels.get("replica") in stale:
                continue
            if math.isfinite(v):
                validity = v if validity is None else min(validity, v)
    if drifts:
        finite = [v for v in drifts.values() if not math.isnan(v)]
        quality = {
            "drift": {r: round(v, 6) for r, v in sorted(drifts.items())},
            "drift_max": max(finite) if finite else None,
        }
        for key, hist in (("entropy_mean", "serving_token_entropy"),
                          ("margin_mean", "serving_logit_margin")):
            s = _counter_value(samples, f"{hist}_sum")
            c = _counter_value(samples, f"{hist}_count")
            quality[key] = round(s / c, 6) if c else None
        if validity is not None:
            quality["constraint_validity_rate"] = round(validity, 6)
        out["quality"] = quality
    # live-migration / resume-by-replay plane (serving/migrate.py): the
    # router's per-outcome ladder counters plus the engines' transfer
    # volume — an operator judging a rolling restart wants "how many
    # requests moved, how many fell to replay, how much shipped" in the
    # same report that shows the availability burn it protected
    migrations = {}
    for n, labels, v in samples:
        if n == "router_migrations_total":
            migrations[labels.get("outcome", "unknown")] = (
                migrations.get(labels.get("outcome", "unknown"), 0) + v
            )
    if migrations:
        mig = {"outcomes": {k: migrations[k]
                            for k in sorted(migrations)}}
        for key, name in (
            ("pages_shipped", "serving_migrate_pages_shipped_total"),
            ("pages_deduped", "serving_migrate_pages_deduped_total"),
            ("bytes", "serving_migrate_bytes_total"),
            ("journal_bytes", "router_replay_journal_bytes"),
        ):
            val = _counter_value(samples, name)
            if val:
                mig[key] = val
        drain_count = _counter_value(samples,
                                     "router_drain_seconds_count")
        drain_sum = _counter_value(samples, "router_drain_seconds_sum")
        if drain_count:
            mig["drains"] = drain_count
            mig["drain_seconds_mean"] = round(
                drain_sum / drain_count, 3
            )
        out["migration"] = mig
    return out


def report_from_jsonl(path: str, args) -> dict:
    """Training-stream objectives (shared --from-metrics-jsonl input
    with metrics_report.py): step-latency + anomaly availability."""
    steps = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed run
            if "loss" in rec and "val_loss" not in rec:
                steps.append(rec)
    step_ms = [r["step_time_ms"] for r in steps if "step_time_ms" in r]
    out = {}
    err = (
        None if not step_ms
        else sum(1 for v in step_ms if v > args.step_time_ms)
        / len(step_ms)
    )
    out["step_time"] = {
        "threshold_ms": args.step_time_ms,
        "target": args.target,
        "count": len(step_ms),
        "error_ratio": err,
        "burn_rate": burn_rate(err, args.target),
    }
    iters = len(steps)
    skipped = max(
        (r.get("skipped_steps", 0) for r in steps), default=0
    )
    err = None if iters == 0 else min(1.0, skipped / iters)
    out["step_availability"] = {
        "target": args.availability_target,
        "count": iters,
        "error_ratio": err,
        "burn_rate": burn_rate(err, args.availability_target),
    }
    return out


def check(objectives: dict, args) -> list:
    """Gate violations; empty = inside every error budget."""
    bad = []
    stale = objectives.get("stale_replicas")
    if stale:
        bad.append(
            "stale replica metrics (scrape age > "
            f"{getattr(args, 'max_scrape_age', 0.0)}s): "
            + ", ".join(stale)
        )
    for name, o in objectives.items():
        if not isinstance(o, dict) or "burn_rate" not in o:
            continue
        burn = o["burn_rate"]
        if burn is None:
            if args.require_traffic:
                bad.append(f"objective {name}: no observations")
            continue
        if burn > args.max_burn:
            bad.append(
                f"objective {name}: burn rate {round(burn, 3)} > "
                f"{args.max_burn} (error ratio "
                f"{round(o['error_ratio'], 5)} vs target {o['target']})"
            )
    # quality drift gate (--max-drift; getattr because the autoscaler's
    # _GateArgs shim predates the flag and sets only max_burn)
    max_drift = getattr(args, "max_drift", None)
    quality = objectives.get("quality")
    if max_drift and isinstance(quality, dict):
        d = quality.get("drift_max")
        if d is not None and not math.isnan(d) and d > max_drift:
            bad.append(
                f"quality drift {round(d, 4)} > {max_drift} (PSI vs "
                "reference fingerprint; see obs/quality.py)"
            )
    return bad


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("exposition", nargs="?", default=None,
                   help="path to a saved Prometheus text exposition")
    p.add_argument("--url", default=None,
                   help="scrape this /metrics or /fleet/metrics URL")
    p.add_argument("--from-metrics-jsonl", default=None,
                   help="judge a trainer metrics.jsonl instead (same "
                        "input path as tools/metrics_report.py)")
    p.add_argument("--ttft", type=float, default=1.0,
                   help="TTFT objective bound in seconds")
    p.add_argument("--itl", type=float, default=0.25,
                   help="inter-token latency objective bound in seconds")
    p.add_argument("--target", type=float, default=0.99,
                   help="latency objectives' target fraction under "
                        "the bound")
    p.add_argument("--availability-target", type=float, default=0.999)
    p.add_argument("--class", dest="priority_class", default=None,
                   choices=("high", "normal", "batch"),
                   help="judge ONE priority class's latency objectives "
                        "(reads the serving_class_* histograms instead "
                        "of the aggregates)")
    p.add_argument("--step-time-ms", type=float, default=1000.0,
                   help="step-latency bound for --from-metrics-jsonl")
    p.add_argument("--max-scrape-age", type=float, default=0.0,
                   help="treat fleet replicas whose last /metrics "
                        "scrape is older than this (seconds, per the "
                        "router's fleet_scrape_age_seconds stamps) as "
                        "MISSING: list them as stale_replicas, drop "
                        "their burn gauges, and fail --check "
                        "(0 = off)")
    p.add_argument("--max-burn", type=float, default=1.0,
                   help="gate: fail --check when any burn rate "
                        "exceeds this")
    p.add_argument("--max-drift", type=float, default=0.0,
                   help="gate: fail --check when any replica's "
                        "serving_quality_drift (PSI vs reference "
                        "fingerprint, obs/quality.py) exceeds this "
                        "(0 = off)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when any objective burns past "
                        "--max-burn")
    p.add_argument("--require-traffic", action="store_true",
                   help="gate: an objective with zero observations "
                        "also fails --check")
    args = p.parse_args()

    sources = [
        s for s in (args.exposition, args.url, args.from_metrics_jsonl)
        if s
    ]
    if len(sources) != 1:
        p.error("give exactly one of: an exposition file, --url, "
                "--from-metrics-jsonl")
    if args.from_metrics_jsonl:
        objectives = report_from_jsonl(args.from_metrics_jsonl, args)
        source = args.from_metrics_jsonl
    else:
        if args.url:
            with urllib.request.urlopen(args.url, timeout=30) as r:
                text = r.read().decode("utf-8", "replace")
            source = args.url
        else:
            text = open(args.exposition, encoding="utf-8").read()
            source = args.exposition
        objectives = report_from_exposition(text, args)

    violations = check(objectives, args) if args.check else []
    summary = {
        "metric": "slo_report",
        "source": source,
        "ok": not violations,
        **objectives,
    }
    print(json.dumps(summary))
    for v in violations:
        print(f"CHECK FAILED: {v}", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
