#!/usr/bin/env python
"""One-shot CI gate: lint + tier-1 tests + perf gate, one entry point.

The README used to tell contributors to run three commands before
pushing (graftlint, the tier-1 pytest pass, and perf_gate over the
bench trajectory); this wraps them into one::

    python tools/ci_check.py                 # full tier-1 gate
    python tools/ci_check.py --quick         # smoke-tier tests instead
    python tools/ci_check.py --changed origin/main   # pre-commit form
    python tools/ci_check.py --skip-tests    # lint + perf only

Gates, in order (fail-fast is deliberately NOT used — one run reports
every broken gate):

1. **graftlint** over the package and ``tools/fleet.py`` (the same
   surfaces ``tests/test_lint_clean.py`` pins), ``--changed REF``
   passed through so pre-commit latency stays flat.
2. **tier-1 tests**: ``pytest tests/ -m 'not slow'`` (``--quick``
   swaps in the <3-minute smoke tier) on the forced-CPU platform.
3. **perf_gate** over the committed ``BENCH_r*.json`` trajectory —
   *if history exists*: the bootstrap state (no bench rounds yet, or
   perf_gate's exit 2 "insufficient history") is reported as
   ``skipped_bootstrap`` and does NOT fail the gate; run a bench round
   (see the README's Continuous-profiling runbook) to arm it. A real
   regression (exit 1) fails.

Output: per-gate one-liners on stderr while running, then ONE JSON
summary line (``slo_report``-style). Exit 0 = every gate passed (or
was legitimately skipped), 1 = some gate failed, 2 = usage error.
Stdlib only.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "differential_transformer_replication_tpu")
PERF_KEYS = ("value", "mfu_6nd")


def _run(cmd, env=None, label=""):
    t0 = time.time()
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=ROOT)
    dt = round(time.time() - t0, 1)
    print(f"[ci_check] {label}: rc={proc.returncode} ({dt}s)",
          file=sys.stderr)
    return proc, dt


def _tail(text: str, n: int = 30) -> str:
    return "\n".join(text.strip().splitlines()[-n:])


def gate_lint(changed) -> dict:
    cmd = [sys.executable, os.path.join(ROOT, "tools", "graftlint.py"),
           "--json"]
    if changed:
        cmd += ["--changed", changed]
    cmd += [PKG, os.path.join(ROOT, "tools", "fleet.py")]
    proc, dt = _run(cmd, label="graftlint")
    out: dict = {"gate": "lint", "rc": proc.returncode, "seconds": dt,
                 "ok": proc.returncode == 0}
    try:
        doc = json.loads(proc.stdout)
        out["findings"] = [
            f"{f['path']}:{f['line']}: {f['rule']} {f['message']}"
            for f in doc.get("findings", []) if not f.get("suppressed")
        ]
        out["files_scanned"] = doc.get("files_scanned")
    except (json.JSONDecodeError, TypeError):
        out["error"] = _tail(proc.stderr, 5)
    if not out["ok"]:
        print(_tail(proc.stderr, 10), file=sys.stderr)
    return out


def gate_tests(quick: bool) -> dict:
    marker = "quick" if quick else "not slow"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "pytest", os.path.join(ROOT, "tests"),
           "-q", "-m", marker, "--continue-on-collection-errors",
           "-p", "no:cacheprovider"]
    proc, dt = _run(cmd, env=env, label=f"pytest -m '{marker}'")
    ok = proc.returncode == 0
    out = {"gate": "tests", "tier": marker, "rc": proc.returncode,
           "seconds": dt, "ok": ok}
    summary = _tail(proc.stdout, 1)
    out["summary"] = summary
    if not ok:
        print(_tail(proc.stdout, 40), file=sys.stderr)
    return out


def gate_perf() -> dict:
    files = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    if not files:
        print("[ci_check] perf_gate: skipped (no BENCH history — run a "
              "bench round to arm it)", file=sys.stderr)
        return {"gate": "perf", "status": "skipped_bootstrap",
                "ok": True,
                "hint": "no BENCH_r*.json history; run a bench round"}
    cmd = [sys.executable, os.path.join(ROOT, "tools", "perf_gate.py"),
           *files]
    for k in PERF_KEYS:
        cmd += ["--key", k]
    proc, dt = _run(cmd, label="perf_gate")
    out = {"gate": "perf", "rc": proc.returncode, "seconds": dt}
    try:
        out["summary"] = json.loads(proc.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        out["summary"] = None
    summary = out["summary"] or {}
    if proc.returncode == 2 and (
        summary.get("status") == "insufficient_history"
        or summary.get("insufficient")
    ):
        # perf_gate's TYPED bootstrap state: not a failure, an unarmed
        # gate — the README runbook's "run a bench round" case. Other
        # exit-2 causes (corrupt/unreadable history that EXISTS) must
        # fail loudly, not masquerade as bootstrap.
        out["status"] = "skipped_bootstrap"
        out["ok"] = True
    else:
        out["status"] = (
            "ok" if proc.returncode == 0
            else "regressed" if proc.returncode == 1
            else "error"
        )
        out["ok"] = proc.returncode == 0
        if proc.returncode:
            print(_tail(proc.stderr, 10), file=sys.stderr)
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--changed", default=None, metavar="REF",
                   help="lint only files changed vs this git ref "
                        "(graftlint --changed; tests/perf unaffected)")
    p.add_argument("--quick", action="store_true",
                   help="run the <3-minute smoke test tier instead of "
                        "the full tier-1 pass")
    p.add_argument("--skip-tests", action="store_true",
                   help="lint + perf gates only")
    args = p.parse_args()

    gates = [gate_lint(args.changed)]
    if not args.skip_tests:
        gates.append(gate_tests(args.quick))
    gates.append(gate_perf())

    ok = all(g["ok"] for g in gates)
    print(json.dumps({
        "metric": "ci_check",
        "gates": gates,
        "ok": ok,
    }))
    for g in gates:
        if not g["ok"]:
            print(f"CHECK FAILED: {g['gate']} gate", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
