"""Benchmark the BASELINE.json target configs (full train step, 1 chip).

The five configs in BASELINE.json name the capability points the
framework must cover (control parity scale, diff parity scale, mid-scale
diff, GPT-2-small-scale ndiff, long-context diff). This tool times each
one's END-TO-END optimizer step — forward + backward + clip + AdamW in
one jitted program — with bench.py's exact methodology: scalar-readback
sync (block_until_ready lies on the axon platform) and best + median
over BENCH_WINDOWS measurement windows (the shared chip shows ±30%
contention noise; the fastest window is the least-contended estimate).

The mesh aspects of configs 3/5 (v4-8 DP, v4-32) cannot be timed on one
chip; their sharded compile+execution is validated by
__graft_entry__.dryrun_multichip and tests/test_parallel.py every round.

    python tools/bench_configs.py --out results/bench_configs_r5.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

CONFIGS = [
    # (name, model kind, overrides, micro_batch)
    ("control 2L/128d T=256", "control",
     dict(n_embd=128, n_head=4, n_layer=2, block_size=256), 64),
    ("diff 2L/128d T=256", "diff",
     dict(n_embd=128, n_head=4, n_layer=2, block_size=256), 64),
    ("diff 6L/512d T=512", "diff",
     dict(n_embd=512, n_head=4, n_layer=6, block_size=512), 32),
    ("ndiff(n=4) 12L/768d T=512", "ndiff",
     dict(n_embd=768, n_head=4, n_layer=12, block_size=512, n_terms=4), 32),
    ("diff 20L/1024d T=4096 remat", "diff",
     dict(n_embd=1024, n_head=8, n_layer=20, block_size=4096, remat=True,
          loss_chunk=512), 2),
]


def _sync(metrics) -> float:
    """Device->host scalar readback (block_until_ready lies on axon)."""
    import jax.numpy as jnp

    return float(jnp.asarray(metrics["loss"]).reshape(-1)[-1])


def bench_one(kind: str, overrides: dict, micro_batch: int, *,
              steps: int, warmup: int, windows: int, attn: str) -> dict:
    import jax
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.models import param_count
    from differential_transformer_replication_tpu.train import (
        create_train_state,
        make_train_step,
    )

    model = ModelConfig(
        model=kind, vocab_size=12000, dropout=0.0,
        compute_dtype="bfloat16", attention_impl=attn, **overrides,
    )
    cfg = TrainConfig(model=model, micro_batch_size=micro_batch,
                      grad_acc_steps=1)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    T = model.block_size
    x = jax.random.randint(
        jax.random.PRNGKey(1), (1, micro_batch, T), 0, model.vocab_size
    )
    batch = {"x": x, "y": jnp.roll(x, -1, axis=-1)}

    for _ in range(max(warmup, 1)):
        state, metrics = step(state, batch)
    _ = _sync(metrics)

    window_secs = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        _ = _sync(metrics)
        window_secs.append(time.perf_counter() - t0)
    best = min(window_secs)
    med = statistics.median(window_secs)
    toks = steps * micro_batch * T
    return {
        "params": param_count(state["params"]),
        "micro_batch": micro_batch,
        "ms_per_step_best": round(best / steps * 1e3, 1),
        "tokens_per_sec_best": round(toks / best, 1),
        "tokens_per_sec_median": round(toks / med, 1),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--windows", type=int,
               default=int(os.environ.get("BENCH_WINDOWS", "3")))
    p.add_argument("--attention-impl", default="pallas",
                   choices=["xla", "pallas"])
    p.add_argument("--only", type=int, default=None,
                   help="run just config N (1-based)")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    results = {}
    for i, (name, kind, overrides, mb) in enumerate(CONFIGS, 1):
        if args.only is not None and i != args.only:
            continue
        r = bench_one(kind, overrides, mb, steps=args.steps,
                      warmup=args.warmup, windows=args.windows,
                      attn=args.attention_impl)
        results[name] = r
        print(f"{i}. {name}: {r['params']/1e6:.1f}M params, "
              f"{r['ms_per_step_best']} ms/step, "
              f"{r['tokens_per_sec_best']/1e3:.1f}k tok/s best "
              f"({r['tokens_per_sec_median']/1e3:.1f}k median)",
              flush=True)
    if args.out:
        payload = {
            "config": vars(args),
            "results": results,
        }
        Path(args.out).write_text(json.dumps(payload, indent=1))
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
