#!/usr/bin/env python
"""Crash supervisor: keep a training run alive across crashes.

The trainer recovers from bad BATCHES in-process (train/anomaly.py) and
writes a resumable rescue checkpoint on catchable exits (trainer.py),
but a hard crash — SIGKILL preemption, OOM kill, a segfaulting runtime —
needs an outside process to relaunch it. This wrapper is that process:

  python tools/train_supervisor.py --resume-ckpt runs/exp.last.ckpt \
      --max-restarts 5 --restart-log runs/restarts.json -- \
      python train.py --checkpoint-path runs/exp.ckpt ...

Behavior:
  - Runs the child command verbatim first. On an ABNORMAL exit it
    relaunches with ``--resume-from <resume-ckpt>`` injected (replacing
    any existing ``--resume-from``) when that checkpoint VERIFIES,
    after an exponential backoff (``backoff_base * 2^restart``, capped),
    up to ``--max-restarts`` relaunches.
  - Verified resume: ``--resume-ckpt`` may be a checkpoint dir or the
    root of a rotating ``step-*`` tree. Integrity manifests
    (train/ckpt_writer.py, spec-loaded by file path so no jax is
    imported) are checked before injecting: a tree resolves to the
    NEWEST step checkpoint whose digests verify, falling back to older
    ones; a single dir must verify (a corrupt or manifest-less one is
    skipped and logged — the child may still resolve its own via
    ``--resume-from auto``, and a pre-manifest dir can be certified
    with ``tools/ckpt_doctor.py --adopt-legacy``). A crash mid-save
    can therefore never wedge the restart loop on a half-written
    checkpoint.
  - Exit classification: rc 0 is a CLEAN exit (done — this includes the
    trainer's SIGTERM graceful stop, which exits 0 after its rescue
    save); rc ``HANG_EXIT_CODE`` (113) is a step-deadline watchdog
    fire (train/watchdog.py) — a HANG, restartable like a crash but
    against its own ``--max-hang-restarts`` budget; death BY SIGTERM
    without the graceful handler is a preemption — the supervisor
    stops by default (the scheduler is taking the host;
    ``--restart-on-sigterm`` opts into relaunching); anything else is
    a CRASH and is restarted.
  - Elastic relaunch (``--elastic``): before each relaunch the
    surviving accelerator count is probed (a jax subprocess, or the
    ``--elastic-probe`` command) and the child's ``--data-parallel``
    is resized so the mesh fits it — the Cloud-TPU preemption that
    returns a smaller slice resumes on what came back instead of
    waiting forever. Pair with the child's ``--resume-from auto``:
    checkpoints are host-canonical, so the resume reshards exactly
    (train/checkpoint.py:elastic_resume_info).
  - SIGTERM/SIGINT to the supervisor are forwarded to the child and end
    the loop after the child exits (no restart).
  - Every launch appends one JSON record to ``--restart-log``
    (JSON-lines: time, attempt, argv, rc, outcome, duration, what it
    resumed from), the audit trail for flaky-host forensics.
  - Fault-injection specs (utils/faults.py) in the child's DTX_FAULTS
    env are stripped on restarts unless ``--keep-faults``: the harness
    injects a fault ONCE to test this very supervisor; replaying it on
    the resumed run would kill every relaunch at the same step.

No jax import here — the supervisor must stay alive when the runtime it
babysits is the thing crashing.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

FAULTS_ENV = "DTX_FAULTS"
# Exit status of a step-deadline watchdog fire — kept in sync with
# train/watchdog.py:HANG_EXIT_CODE (not imported: that module lives in
# the jax-importing package this supervisor must outlive; the value is
# part of the trainer<->supervisor contract like a signal number).
HANG_EXIT_CODE = 113

# mesh-axis flags train.py understands; --elastic rewrites the data
# axis so the product fits the surviving device count
_MESH_FLAGS = ("--data-parallel", "--fsdp", "--tensor-parallel",
               "--sequence-parallel", "--pipeline-parallel")


def _ckpt_tools():
    """train/ckpt_writer.py loaded BY FILE PATH: its module scope is
    stdlib-only, so manifest verification works here without importing
    the package (whose __init__ chain would pull jax — the runtime this
    supervisor must outlive). None when the file is missing (repo
    layout changed): callers degrade to the legacy existence check."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "differential_transformer_replication_tpu", "train",
        "ckpt_writer.py",
    )
    try:
        spec = importlib.util.spec_from_file_location(
            "_supervisor_ckpt_writer", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception as e:  # noqa: BLE001
        print(f"train_supervisor: checkpoint verification unavailable "
              f"({e!r}); falling back to existence checks",
              file=sys.stderr)
        return None


def resolve_resume_ckpt(path: Optional[str], ckpt=None) -> Optional[str]:
    """The checkpoint dir to inject as ``--resume-from``, or None.

    ``path`` is a checkpoint dir or a rotating-tree root; ``ckpt`` is
    the (possibly None) ckpt_writer module. Only a checkpoint that
    passes manifest verification is injected — newest-first with
    fallback across a tree — so the child never restarts into a
    half-written or bit-rotted save."""
    if not path:
        return None
    if ckpt is None:
        ckpt = _ckpt_tools()
    if ckpt is None:  # degraded mode: the pre-manifest behavior
        return path if os.path.isfile(
            os.path.join(path, "state.msgpack")
        ) else None
    if ckpt.list_step_checkpoints(path):
        resolved, skipped = ckpt.latest_verified_checkpoint(path)
        for p, why in skipped:
            print(f"train_supervisor: skipping unverified checkpoint "
                  f"{p}: {why}", file=sys.stderr)
        return resolved
    if os.path.exists(os.path.join(path, ckpt.MANIFEST_NAME)):
        if ckpt.is_verified(path):
            return path
        print(f"train_supervisor: checkpoint {path} fails integrity "
              "verification; not injecting --resume-from",
              file=sys.stderr)
        return None
    if os.path.isfile(os.path.join(path, "state.msgpack")):
        # manifest-less legacy dir: the trainer's verified load would
        # reject it on every relaunch — injecting it would wedge the
        # restart loop on a CheckpointError, the exact failure this
        # resolution exists to prevent
        print(f"train_supervisor: checkpoint {path} has no integrity "
              "manifest; not injecting --resume-from (certify it with "
              "tools/ckpt_doctor.py --adopt-legacy)", file=sys.stderr)
    return None


def classify_exit(rc: int) -> str:
    """clean / hang / sigterm / sigkill / crash from a subprocess
    returncode (negative rc = death by that signal; 128+N covers
    shells that re-report signal deaths as exit codes). ``hang`` is
    the step-deadline watchdog's distinct exit (train/watchdog.py): a
    wedged step, restartable like a crash but budgeted separately —
    a flaky host that hangs repeatedly must not eat the crash budget
    a genuinely flaky run needs (and vice versa)."""
    if rc == 0:
        return "clean"
    if rc == HANG_EXIT_CODE:
        return "hang"
    sig = -rc if rc < 0 else (rc - 128 if 128 < rc < 160 else None)
    if sig == signal.SIGTERM:
        return "sigterm"
    if sig == signal.SIGKILL:
        return "sigkill"
    return "crash"


def _strip_flag(cmd: List[str], flag: str) -> List[str]:
    """Drop ``flag X`` / ``flag=X`` occurrences from an argv list."""
    out = []
    skip = False
    for a in cmd:
        if skip:
            skip = False
            continue
        if a == flag:
            skip = True
            continue
        if a.startswith(flag + "="):
            continue
        out.append(a)
    return out


def with_resume(cmd: List[str], ckpt: str) -> List[str]:
    """Inject ``--resume-from <ckpt>``, replacing an existing flag (both
    ``--resume-from X`` and ``--resume-from=X`` forms)."""
    return _strip_flag(cmd, "--resume-from") + ["--resume-from", ckpt]


def _flag_value(cmd: List[str], flag: str, default: int = 1) -> int:
    """Last value of an integer ``flag X`` / ``flag=X`` in an argv list
    (train.py semantics: argparse keeps the last occurrence)."""
    val = default
    for i, a in enumerate(cmd):
        if a == flag and i + 1 < len(cmd):
            try:
                val = int(cmd[i + 1])
            except ValueError:
                pass
        elif a.startswith(flag + "="):
            try:
                val = int(a.split("=", 1)[1])
            except ValueError:
                pass
    return val


def probe_device_count(probe_cmd: Optional[List[str]] = None,
                       env: Optional[dict] = None,
                       timeout: float = 300.0) -> Optional[int]:
    """The accelerator count a relaunched child would see, probed in a
    SUBPROCESS (this supervisor never imports jax itself — the runtime
    it babysits is the thing that crashes). The default probe asks jax
    in the child's environment; ``--elastic-probe`` overrides it (and
    makes chaos tests deterministic). None on any failure — the caller
    then relaunches with the mesh flags untouched."""
    cmd = probe_cmd or [
        sys.executable, "-c", "import jax; print(jax.device_count())"
    ]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, env=env)
        return int(out.stdout.strip().splitlines()[-1])
    except (OSError, ValueError, IndexError,
            subprocess.TimeoutExpired):
        return None


def with_elastic_mesh(cmd: List[str], n_devices: int) -> List[str]:
    """SHRINK the child's ``--data-parallel`` so the mesh-axis product
    fits ``n_devices`` — the elastic relaunch after a preemption
    returned a smaller slice. Only the data axis is resized (it is the
    one axis whose extent never changes parameter shapes, so the
    host-canonical checkpoint reshards exactly; shrinking fsdp/tensor/
    sequence/pipeline re-partitions math the operator chose
    deliberately). A mesh that ALREADY fits is returned unchanged —
    elastic means "run on what survived", never "grab every device":
    an operator who under-subscribed on purpose (batch divisibility,
    devices reserved for something else) must not be silently
    retopologized by a restart. When the non-data axes alone exceed
    the surviving devices the argv is also unchanged — the child
    fails loudly with create_mesh's clear error rather than silently
    training a different topology than asked."""
    other = 1
    for flag in _MESH_FLAGS:
        if flag != "--data-parallel":
            other *= _flag_value(cmd, flag)
    if other > n_devices:
        return cmd
    if _flag_value(cmd, "--data-parallel") * other <= n_devices:
        return cmd  # already fits: never upsize
    new_data = max(1, n_devices // other)
    return _strip_flag(cmd, "--data-parallel") + [
        "--data-parallel", str(new_data)
    ]


def backoff_s(restart: int, base: float, cap: float) -> float:
    return min(base * (2 ** restart), cap)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    p.add_argument("--resume-ckpt", default=None,
                   help="checkpoint dir — or root of a rotating step-* "
                        "tree — to resume from on restarts (point it at "
                        "the run's last/rescue checkpoint or its .steps "
                        "dir); only a checkpoint passing integrity "
                        "verification is injected, newest first")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="restart budget for crash-class exits; "
                        "exhausted -> exit with the child's last "
                        "returncode")
    p.add_argument("--max-hang-restarts", type=int, default=None,
                   help="separate restart budget for watchdog hang "
                        f"exits (rc {HANG_EXIT_CODE}, "
                        "train/watchdog.py); default: same value as "
                        "--max-restarts, counted independently")
    p.add_argument("--elastic", action="store_true",
                   help="before each relaunch, probe the surviving "
                        "accelerator count and rewrite the child's "
                        "--data-parallel so the mesh fits it — the "
                        "preemption-returned-a-smaller-slice case; "
                        "pair with the child's --resume-from auto "
                        "(checkpoints are host-canonical, so the "
                        "resume reshards exactly)")
    p.add_argument("--elastic-probe", default=None, metavar="CMD",
                   help="override the device-count probe command "
                        "(default: ask jax in a subprocess with the "
                        "child's env); the command's last stdout line "
                        "must be an integer")
    p.add_argument("--backoff-base", type=float, default=2.0,
                   help="first-restart backoff seconds (doubles per "
                        "restart)")
    p.add_argument("--backoff-max", type=float, default=120.0,
                   help="backoff cap in seconds")
    p.add_argument("--restart-log", default=None,
                   help="append one JSON record per launch to this file")
    p.add_argument("--restart-on-sigterm", action="store_true",
                   help="also restart after a SIGTERM death (default: a "
                        "preemption means stop)")
    p.add_argument("--keep-faults", action="store_true",
                   help="keep DTX_FAULTS in the child env on restarts "
                        "(default: first launch only)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- then the training command to supervise")
    return p


def _log(path: Optional[str], record: dict) -> None:
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def supervise(args: argparse.Namespace) -> int:
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("train_supervisor: no command given (put it after --)",
              file=sys.stderr)
        return 2

    child: dict = {"proc": None}
    got_signal: dict = {"sig": None}

    def forward(signum, frame):
        del frame
        got_signal["sig"] = signum
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            proc.send_signal(signum)

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, forward)

    restarts = 0
    # hang (watchdog) restarts are budgeted separately from crash-class
    # ones: a host that keeps wedging and a run that keeps crashing are
    # different pathologies with different budgets
    class_restarts = {"hang": 0, "crash": 0}
    hang_budget = (
        args.max_hang_restarts if args.max_hang_restarts is not None
        else args.max_restarts
    )
    rc = 1
    while True:
        launch_cmd = cmd
        resumed_from = None
        elastic_devices = None
        env = None  # inherit
        if restarts > 0:
            ckpt = resolve_resume_ckpt(args.resume_ckpt)
            if ckpt:
                launch_cmd = with_resume(cmd, ckpt)
                resumed_from = ckpt
            if not args.keep_faults:
                # faults are first-launch-only through BOTH channels —
                # a --faults flag left in argv would re-fire the same
                # kill on every relaunch, exhausting the budget on the
                # exact replay hazard the env-strip exists to prevent
                launch_cmd = _strip_flag(launch_cmd, "--faults")
                if FAULTS_ENV in os.environ:
                    env = dict(os.environ)
                    del env[FAULTS_ENV]
            if args.elastic:
                # elastic relaunch: the slice that comes back after a
                # preemption may be smaller — resize the data axis to
                # the surviving device count so the relaunch runs
                # instead of waiting for hardware that will not return
                import shlex

                probe = (
                    shlex.split(args.elastic_probe)
                    if args.elastic_probe else None
                )
                elastic_devices = probe_device_count(probe, env=env)
                if elastic_devices:
                    resized = with_elastic_mesh(launch_cmd,
                                                elastic_devices)
                    if resized != launch_cmd:
                        print(f"train_supervisor: elastic relaunch on "
                              f"{elastic_devices} device(s): "
                              f"--data-parallel -> "
                              f"{_flag_value(resized, '--data-parallel')}",
                              file=sys.stderr)
                    launch_cmd = resized
                else:
                    print("train_supervisor: elastic device probe "
                          "failed; relaunching with the original mesh",
                          file=sys.stderr)
        t0 = time.time()
        child["proc"] = subprocess.Popen(launch_cmd, env=env)
        rc = child["proc"].wait()
        child["proc"] = None
        outcome = classify_exit(rc)
        _log(args.restart_log, {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "attempt": restarts,
            "argv": launch_cmd,
            "rc": rc,
            "outcome": outcome,
            "duration_s": round(time.time() - t0, 3),
            "resumed_from": resumed_from,
            "elastic_devices": elastic_devices,
        })
        if outcome == "clean":
            return 0
        if got_signal["sig"] is not None:
            print(f"train_supervisor: stopping (received signal "
                  f"{got_signal['sig']}; child exited {rc})", file=sys.stderr)
            return 128 + got_signal["sig"]
        if outcome == "sigterm" and not args.restart_on_sigterm:
            print("train_supervisor: child died by SIGTERM (preemption); "
                  "not restarting (use --restart-on-sigterm to override)",
                  file=sys.stderr)
            return 128 + signal.SIGTERM
        restart_class = "hang" if outcome == "hang" else "crash"
        budget = hang_budget if restart_class == "hang" else args.max_restarts
        if class_restarts[restart_class] >= budget:
            print(f"train_supervisor: {restart_class} restart budget "
                  f"exhausted ({budget}); last outcome {outcome} (rc {rc})",
                  file=sys.stderr)
            return rc if rc > 0 else 128 + (-rc)
        class_restarts[restart_class] += 1
        delay = backoff_s(restarts, args.backoff_base, args.backoff_max)
        print(f"train_supervisor: child {outcome} (rc {rc}); "
              f"{restart_class} restart "
              f"{class_restarts[restart_class]}/{budget} in {delay:.1f}s",
              file=sys.stderr)
        # interruptible backoff: a SIGTERM/SIGINT arriving here (child
        # gone, nothing to forward to) must stop the supervisor, not be
        # swallowed by a PEP 475-resumed sleep and followed by a fresh
        # hours-long run the operator never gets to signal again
        end = time.time() + delay
        while time.time() < end and got_signal["sig"] is None:
            time.sleep(min(0.1, max(0.0, end - time.time())))
        if got_signal["sig"] is not None:
            print(f"train_supervisor: stopping (received signal "
                  f"{got_signal['sig']} during backoff)", file=sys.stderr)
            return 128 + got_signal["sig"]
        restarts += 1


def main() -> None:
    sys.exit(supervise(build_parser().parse_args()))


if __name__ == "__main__":
    main()
