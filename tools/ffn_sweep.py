"""Kernel-level sweep for the fused FFN/norm path — flash_sweep.py's
sibling for ops/fused_ffn.py + ops/fused_norm_residual.py.

Three sweep axes, each printed as one JSON line per case:

  - impl: the fused Pallas chain vs the reference XLA composition
    (layer_norm + swiglu), fwd and fwd+grad, at several (rows, width)
    shapes — the kernel-level win the ffn_impl switch buys,
  - tiles: (block_m, block_f) candidates for the fused SwiGLU kernel,
  - remat policies: full train-step timings per ModelConfig.remat_policy
    (--remat-policies), because the fused kernels changed the
    recompute-vs-save trade-off the policy controls.

Timing is readback-synced like flash_sweep.py (block_until_ready returns
early on the axon platform, BASELINE.md).

    python tools/ffn_sweep.py [--steps 10] [--tiles 256,512 ...]
    python tools/ffn_sweep.py --remat-policies none,dots --steps 5
    python tools/ffn_sweep.py --smoke     # tier-1 CI gate: tiny shapes,
                                          # interpret-mode kernels, ~seconds
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp


def _sync(out) -> None:
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(x.astype(jnp.float32))), out
    )


def bench_ffn_case(M, E, impl, tiles, steps, mode, dtype):
    """One (rows, width) case: the block's norm+SwiGLU chain, fused
    (pallas) or reference (xla). Returns seconds/step."""
    from differential_transformer_replication_tpu.ops import (
        layer_norm,
        swiglu,
    )
    from differential_transformer_replication_tpu.ops.fused_ffn import (
        fused_swiglu,
    )
    from differential_transformer_replication_tpu.ops.fused_norm_residual import (
        fused_add_norm,
    )

    F = 4 * E
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    x = jax.random.normal(ks[0], (M, E), dtype)
    d = jax.random.normal(ks[1], (M, E), dtype)
    lnw = jnp.ones((E,), jnp.float32)
    lnb = jnp.zeros((E,), jnp.float32)
    wg = jax.random.normal(ks[2], (E, F), jnp.float32) * 0.02
    bg = jnp.zeros((F,), jnp.float32)
    wx = jax.random.normal(ks[3], (E, F), jnp.float32) * 0.02
    bx = jnp.zeros((F,), jnp.float32)

    kw = {}
    if tiles is not None:
        kw = dict(block_m=tiles[0], block_f=tiles[1])

    def fused(x, d, lnw, lnb, wg, bg, wx, bx):
        xn, n = fused_add_norm(x, d, lnw, lnb)
        h = fused_swiglu(n, wg, bg, wx, bx, **kw)
        return jnp.sum(h.astype(jnp.float32)) + jnp.sum(
            xn.astype(jnp.float32)
        )

    def reference(x, d, lnw, lnb, wg, bg, wx, bx):
        xn = x + d
        n = layer_norm(xn, lnw, lnb)
        h = swiglu(
            n, wg.astype(x.dtype), bg.astype(x.dtype),
            wx.astype(x.dtype), bx.astype(x.dtype),
        )
        return jnp.sum(h.astype(jnp.float32)) + jnp.sum(
            xn.astype(jnp.float32)
        )

    base = fused if impl == "pallas" else reference
    fn = jax.jit(base if mode == "fwd" else jax.grad(base, argnums=(0, 4, 6)))
    args = (x, d, lnw, lnb, wg, bg, wx, bx)
    _sync(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / steps


def bench_remat_case(policy, ffn_impl, steps, args):
    """Full train-step seconds/step under remat with one save policy —
    the knob the fused kernels re-opened (cheaper FFN recompute)."""
    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train.step import (
        create_train_state,
        make_train_step,
    )

    model = ModelConfig(
        model=args.model, vocab_size=args.vocab_size, n_embd=args.n_embd,
        n_head=args.n_head, n_layer=args.n_layer, block_size=args.block_size,
        dropout=0.0, compute_dtype=args.dtype, attention_impl=args.attn,
        ffn_impl=ffn_impl, remat=policy != "off", remat_policy=(
            "none" if policy == "off" else policy
        ),
    )
    cfg = TrainConfig(
        model=model, micro_batch_size=args.micro_batch, grad_acc_steps=1
    )
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    x = jax.random.randint(
        jax.random.PRNGKey(1), (1, args.micro_batch, model.block_size), 0,
        model.vocab_size,
    )
    batch = {"x": x, "y": jnp.roll(x, -1, -1)}
    state, m = step(state, batch)  # compile
    _ = float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    _ = float(m["loss"])
    return (time.perf_counter() - t0) / steps


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument(
        "--tiles", nargs="*", default=None,
        help="fused-kernel tile configs as block_m,block_f "
             "(default: library default only)",
    )
    p.add_argument("--rows", default="4096,16384",
                   help="M = B*T row counts for the kernel-level sweep")
    p.add_argument("--width", type=int, default=768, help="E (hidden = 4E)")
    p.add_argument("--modes", default="fwd,grad")
    p.add_argument("--impls", default="xla,pallas")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "--remat-policies", default=None,
        help="comma list from off,none,dots,dots_no_batch,nothing,"
             "everything: time a FULL train step per policy instead of "
             "the bare chain",
    )
    # full-step knobs (remat mode)
    p.add_argument("--model", default="diff",
                   choices=["control", "diff", "ndiff"])
    p.add_argument("--attn", default="pallas", choices=["xla", "pallas"])
    p.add_argument("--ffn", default="pallas", choices=["xla", "pallas"])
    p.add_argument("--micro-batch", type=int, default=32)
    p.add_argument("--block-size", type=int, default=512)
    p.add_argument("--n-embd", type=int, default=768)
    p.add_argument("--n-head", type=int, default=4)
    p.add_argument("--n-layer", type=int, default=8)
    p.add_argument("--vocab-size", type=int, default=12000)
    p.add_argument(
        "--smoke", action="store_true",
        help="CI gate: tiny shapes + 2 steps so the interpret-mode "
             "kernels run end to end in seconds; exit nonzero on any "
             "case failure",
    )
    args = p.parse_args()

    if args.smoke:
        args.rows, args.width, args.steps = "64", 32, 2
        args.n_embd, args.n_head, args.n_layer = 32, 2, 2
        args.vocab_size, args.block_size, args.micro_batch = 64, 16, 2
        if args.remat_policies is None:
            args.remat_policies = "off,none,dots"

    dtype = jnp.dtype(args.dtype)
    failed = 0

    configs = [None]
    if args.tiles:
        configs += [tuple(int(v) for v in t.split(",")) for t in args.tiles]

    for M in (int(s) for s in args.rows.split(",")):
        for mode in args.modes.split(","):
            for impl in args.impls.split(","):
                for tiles in configs if impl == "pallas" else [None]:
                    try:
                        dt = bench_ffn_case(
                            M, args.width, impl, tiles, args.steps, mode,
                            dtype,
                        )
                        print(json.dumps({
                            "case": "ffn_chain", "rows": M,
                            "width": args.width, "mode": mode,
                            "impl": impl, "tiles": tiles,
                            "ms": round(dt * 1e3, 3),
                            "rows_per_s": round(M / dt, 1),
                        }), flush=True)
                    except Exception as e:  # noqa: BLE001
                        failed += 1
                        print(json.dumps({
                            "case": "ffn_chain", "rows": M, "mode": mode,
                            "impl": impl, "tiles": tiles, "failed":
                            f"{type(e).__name__}: {str(e)[:160]}",
                        }), flush=True)

    if args.remat_policies:
        for policy in args.remat_policies.split(","):
            try:
                dt = bench_remat_case(policy, args.ffn, args.steps, args)
                toks = args.micro_batch * args.block_size / dt
                print(json.dumps({
                    "case": "remat_step", "policy": policy,
                    "ffn_impl": args.ffn, "model": args.model,
                    "ms_per_step": round(dt * 1e3, 2),
                    "tokens_per_s": round(toks, 1),
                }), flush=True)
            except Exception as e:  # noqa: BLE001
                failed += 1
                print(json.dumps({
                    "case": "remat_step", "policy": policy, "failed":
                    f"{type(e).__name__}: {str(e)[:160]}",
                }), flush=True)

    if failed:
        print(f"[ffn_sweep] {failed} case(s) FAILED", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
