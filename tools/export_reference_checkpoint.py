"""Convert one of this framework's checkpoints to the reference
(PyTorch) formats — the inverse of tools/import_reference_checkpoint.py.

Reads a ``save_pretrained`` directory (train/checkpoint.py, the
self-describing params+config layout all three families share) and
writes a torch blob the reference code consumes directly
(utils/torch_export.py):

    # the save_pretrained blob ({'model_args', 'model_state'},
    # Ndiff_transformer.py:251-265) — for ndiff this loads via the
    # reference's own AlternatingDiffTransformer.from_pretrained
    python tools/export_reference_checkpoint.py trained/ out.pt

    # the best_model.pt training-blob key layout (train.py:309-316)
    python tools/export_reference_checkpoint.py trained/ out.pt --fmt train

Cross-implementation parity of the mapping (the reference's own forward
on exported weights matches ours) is pinned by tests/test_torch_export.py.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", help="save_pretrained directory to export")
    p.add_argument("out", help="output .pt path")
    p.add_argument(
        "--fmt", choices=["pretrained", "train"], default="pretrained",
        help="torch blob layout: save_pretrained ({'model_args', "
        "'model_state'}) or the best_model.pt training shape "
        "({'model_state_dict'})",
    )
    args = p.parse_args()

    from differential_transformer_replication_tpu.train.checkpoint import (
        from_pretrained,
    )
    from differential_transformer_replication_tpu.utils.torch_export import (
        save_reference_checkpoint,
    )

    params, model_cfg = from_pretrained(args.checkpoint)
    save_reference_checkpoint(args.out, params, model_cfg, fmt=args.fmt)
    print(
        f"exported {model_cfg.model} ({model_cfg.n_layer}L/"
        f"{model_cfg.n_embd}d/{model_cfg.n_head}h) -> {args.out} "
        f"[{args.fmt}]"
    )


if __name__ == "__main__":
    main()
