"""Decode throughput: cached vs windowed generation, batched and batch-1.

VERDICT r3 item 6: the batch-1 ring-cache number (1.6x at recipe width)
understates the cache because batch-1 per-token cost is FFN-dominated; at
B in {8, 32} attention is the dominant per-token term and the O(T^2) ->
O(T) win shows at its real operating point. This tool times, at the
recipe width (8L/768d control — the RoPE family that can decode past
block_size):

  - ``models.generate``      — the reference's windowed recompute
                               (control.py:163-171: full forward per token),
  - ``models.decode.generate_cached`` — the ring KV cache (O(T)/token).

One JSON line per (impl, batch) with tokens/sec (= B * new_tokens /
wall). Sync is a device->host readback (block_until_ready lies on axon,
BASELINE.md).

    python tools/decode_bench.py --batches 1 8 32 --new-tokens 1024
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32])
    p.add_argument("--new-tokens", type=int, default=1024)
    p.add_argument("--prompt-len", type=int, default=256)
    p.add_argument("--model", default="control",
                   choices=["control", "diff", "ndiff"])
    p.add_argument("--n-embd", type=int, default=768)
    p.add_argument("--n-layer", type=int, default=8)
    p.add_argument("--n-head", type=int, default=8,
                   help="control at the reference's head-doubled width")
    p.add_argument("--block-size", type=int, default=512)
    p.add_argument("--decode-attention-impl", default="xla",
                   choices=("xla", "pallas"),
                   help="decode attention backend for the cached path: "
                        "the fused Pallas single-query kernel "
                        "(ops/decode_attention.py) or the plain XLA "
                        "composition")
    p.add_argument("--kv-cache-dtype", default="auto",
                   choices=("auto", "bf16", "int8"),
                   help="KV-cache storage dtype; int8 = per-head-scale "
                        "quantized K/V (half the bf16 bytes)")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.config import ModelConfig
    from differential_transformer_replication_tpu.models import (
        generate,
        init_model,
    )
    from differential_transformer_replication_tpu.models.decode import (
        generate_cached,
    )

    cfg = ModelConfig(
        model=args.model, vocab_size=12000, n_embd=args.n_embd,
        n_head=args.n_head, n_layer=args.n_layer,
        block_size=args.block_size, dropout=0.0,
        compute_dtype="bfloat16",
        decode_attention_impl=args.decode_attention_impl,
        kv_cache_dtype=args.kv_cache_dtype,
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    rows = []
    for B in args.batches:
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (B, args.prompt_len), 0, cfg.vocab_size
        )
        for name, fn in (
            ("windowed", lambda: generate(
                params, prompt, cfg, args.new_tokens, jax.random.PRNGKey(2)
            )),
            ("cached", lambda: generate_cached(
                params, prompt, cfg, args.new_tokens, jax.random.PRNGKey(2)
            )),
        ):
            out = fn()  # compile + warm
            _ = int(out[0, -1])
            t0 = time.perf_counter()
            out = fn()
            _ = int(out[0, -1])
            dt = time.perf_counter() - t0
            tps = B * args.new_tokens / dt
            row = {
                "impl": name, "batch": B, "new_tokens": args.new_tokens,
                "prompt_len": args.prompt_len, "model": args.model,
                "decode_attention_impl": args.decode_attention_impl,
                "kv_cache_dtype": args.kv_cache_dtype,
                "tokens_per_sec": round(tps, 1), "wall_s": round(dt, 2),
            }
            rows.append(row)
            print(json.dumps(row))
    by = {}
    for r in rows:
        by.setdefault(r["batch"], {})[r["impl"]] = r["tokens_per_sec"]
    for b, d in sorted(by.items()):
        if "windowed" in d and "cached" in d:
            print(
                f"# B={b}: cache speedup {d['cached'] / d['windowed']:.2f}x",
                file=sys.stderr,
            )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
