#!/usr/bin/env python
"""Stitch per-process Chrome trace files into one fleet timeline.

"Why was this request slow?" needs ONE picture: the router's
``pick``/``forward``/``retry``/``hedge`` spans and every replica's
``request``/``admit``/``first_token``/``decode`` spans, on a shared
clock, filterable by ``trace_id``. Each process writes its own trace
file (``--trace-path`` on serving/server.py, serving/router.py,
train.py); this tool merges them::

    python tools/trace_stitch.py router.trace.json \
        replica-*.trace.json -o stitched.trace.json
    # one request only:
    python tools/trace_stitch.py ... -o slow.trace.json \
        --trace-id 4bf92f3577b34da6a3ce929d0e0e4736

Open the output at https://ui.perfetto.dev — each input file becomes
its own process lane (pids are reassigned per file, so two processes
that happened to share an OS pid do not collide).

**Clock alignment.** Trace timestamps anchor ``perf_counter`` to each
process's wall clock once at tracer construction, so cross-process
skew (NTP drift, clocks stepped between launches) shows up as replica
spans sliding outside the router span that caused them. The stitcher
re-aligns from the round-trips the traces already contain: a router
``forward`` span (one HTTP round-trip) must ENCLOSE every replica
span parented to it (matched by the propagated ``span_id`` →
``parent_id`` link, obs/trace.py). Each non-reference file's offset is
the median of the per-pair shifts that restore that enclosure —
0 when the clocks already agree. ``--no-align`` keeps raw clocks.

**Device lanes.** The continuous profiler (obs/device_profile.py)
writes ``device-NNNN.trace.json`` files: the captured step's XLA-op
timeline, wall-clock anchored, with one enclosing ``capture_window``
event whose ``capture`` arg matches the ``device_capture`` host span
the sampler emitted around the profiled step. Pass them alongside the
host traces (host/reference trace FIRST) and each becomes its own
process lane, aligned so the capture window sits exactly inside the
host span that wrapped it — one Perfetto view from HTTP request (or
trainer iteration) down to the Pallas kernels::

    python tools/trace_stitch.py server.trace.json \
        device_profiles/device-*.trace.json -o full.trace.json

Also prints one JSON summary line (file count, event count, applied
offsets, distinct trace ids) in the style of the other tools. Stdlib
only; tolerant of truncated inputs (a crashed process's unterminated
JSON array is repaired by dropping the torn tail line).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple


def load_trace(path: str) -> List[dict]:
    """Load one Chrome trace JSON array; repair a missing terminator
    (a process that died before close() leaves ``[`` + event lines)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        events = json.loads(text)
    except json.JSONDecodeError:
        # drop the torn tail line and close the array
        # events stream one per line, comma-separated ("," prefix on
        # every line after the first) — strip both edges
        lines = [
            ln.strip().strip(",") for ln in text.splitlines()
            if ln.strip() and ln.strip() not in ("[", "]")
        ]
        events = []
        for ln in lines:
            try:
                events.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome trace event array")
    return [e for e in events if isinstance(e, dict)]


def _spans_by_span_id(events: List[dict]) -> Dict[str, Tuple[float, float]]:
    """span_id -> (ts, ts+dur) for complete events carrying trace args."""
    out: Dict[str, Tuple[float, float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid:
            ts = float(e.get("ts", 0.0))
            out[sid] = (ts, ts + float(e.get("dur", 0.0)))
    return out


def _capture_anchor_shifts(reference: List[dict],
                           other: List[dict]) -> List[float]:
    """Exact shifts aligning ``other``'s device ``capture_window``
    events to the reference's ``device_capture`` host spans with the
    same ``capture`` arg (the join key obs/device_profile.py stamps on
    both sides of a sampled window)."""
    ref_caps: Dict[object, float] = {}
    for e in reference:
        if e.get("ph") == "X" and e.get("name") == "device_capture":
            cap = (e.get("args") or {}).get("capture")
            if cap is not None:
                ref_caps[cap] = float(e.get("ts", 0.0))
    shifts: List[float] = []
    for e in other:
        if e.get("ph") == "X" and e.get("name") == "capture_window":
            cap = (e.get("args") or {}).get("capture")
            if cap in ref_caps:
                shifts.append(ref_caps[cap] - float(e.get("ts", 0.0)))
    return shifts


def estimate_offset_us(reference: List[dict],
                       other: List[dict]) -> float:
    """Median shift (microseconds, added to ``other``) that places each
    of ``other``'s parented spans inside the reference span that caused
    it. Pairs come from the propagated trace context: an event in
    ``other`` whose ``parent_id`` names a ``span_id`` in ``reference``
    was, by construction, caused DURING that reference span — or, for
    device lanes, from capture-window join keys (exact alignment; see
    :func:`_capture_anchor_shifts`)."""
    ref_spans = _spans_by_span_id(reference)
    shifts: List[float] = _capture_anchor_shifts(reference, other)
    for e in other:
        if e.get("ph") not in ("X", "i"):
            continue
        args = e.get("args") or {}
        parent = args.get("parent_id")
        if not parent or parent not in ref_spans:
            continue
        lo, hi = ref_spans[parent]
        ts = float(e.get("ts", 0.0))
        te = ts + float(e.get("dur", 0.0))
        # feasible offsets keep [ts, te] inside [lo, hi]; pick the
        # smallest-magnitude feasible shift (0 when already inside)
        min_off = lo - ts
        max_off = hi - te
        if min_off > max_off:  # child longer than parent: center it
            shifts.append(((lo + hi) - (ts + te)) / 2.0)
        elif min_off > 0:
            shifts.append(min_off)
        elif max_off < 0:
            shifts.append(max_off)
        else:
            shifts.append(0.0)
    if not shifts:
        return 0.0
    shifts.sort()
    return shifts[len(shifts) // 2]


def _matches_trace(event: dict, trace_id: str) -> bool:
    args = event.get("args") or {}
    if args.get("trace_id") == trace_id:
        return True
    tids = args.get("trace_ids")
    return isinstance(tids, list) and trace_id in tids


def stitch(paths: List[str], align: bool = True,
           trace_id: Optional[str] = None) -> Tuple[List[dict], dict]:
    """Merge trace files; returns ``(events, summary)``. The first
    path is the clock reference (pass the router's trace first)."""
    traces = [load_trace(p) for p in paths]
    offsets = [0.0] * len(traces)
    if align and len(traces) > 1:
        for i in range(1, len(traces)):
            offsets[i] = estimate_offset_us(traces[0], traces[i])
    merged: List[dict] = []
    trace_ids = set()
    device_lanes = 0
    for i, (path, events) in enumerate(zip(paths, traces)):
        if any(e.get("name") == "capture_window" for e in events):
            device_lanes += 1
        for e in events:
            e = dict(e)
            e["pid"] = i  # one lane per input file, collision-free
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    name = (e.get("args") or {}).get("name", "process")
                    e["args"] = {"name": f"{name} [{path}]"}
                    merged.append(e)
                elif e.get("name") == "process_sort_index":
                    e["args"] = {"sort_index": i}
                    merged.append(e)
                elif e.get("name") == "thread_name":
                    # device lanes label their xplane lines per thread
                    merged.append(e)
                continue
            args = e.get("args") or {}
            tid = args.get("trace_id")
            if tid:
                trace_ids.add(tid)
            if trace_id is not None and not _matches_trace(e, trace_id):
                continue
            if "ts" in e:
                e["ts"] = float(e["ts"]) + offsets[i]
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    summary = {
        "metric": "trace_stitch",
        "files": len(paths),
        "events": len(merged),
        "span_events": sum(1 for e in merged if e.get("ph") != "M"),
        "offsets_us": [round(o, 1) for o in offsets],
        "distinct_trace_ids": len(trace_ids),
        "filtered_trace_id": trace_id,
        "device_lanes": device_lanes,
    }
    return merged, summary


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("traces", nargs="+",
                   help="per-process .trace.json files; the FIRST is "
                        "the clock reference (use the router's)")
    p.add_argument("-o", "--out", required=True,
                   help="stitched Chrome trace output path")
    p.add_argument("--trace-id", default=None,
                   help="keep only events belonging to this trace id "
                        "(one request's fleet-wide timeline)")
    p.add_argument("--no-align", action="store_true",
                   help="skip round-trip clock-offset alignment")
    args = p.parse_args()

    merged, summary = stitch(args.traces, align=not args.no_align,
                             trace_id=args.trace_id)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(merged, fh, separators=(",", ":"))
    summary["out"] = args.out
    print(json.dumps(summary))
    if args.trace_id is not None and summary["span_events"] == 0:
        print(f"CHECK FAILED: trace id {args.trace_id} not found in "
              f"{args.traces}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
