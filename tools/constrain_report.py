#!/usr/bin/env python
"""Diff-vs-control schema-validity report for structured decoding.

The paper's serving-side question: does differential attention's
noise-cancellation make outputs *naturally* better-structured, and
what does FSM-constrained decoding (serving/constrain.py) add on top?
This tool answers it as one JSON line: the SAME greedy workload runs
through a diff-family and a control-family engine twice — once
unconstrained (``natural_validity_*``: how often free-running output
happens to match the schema) and once constrained
(``constrained_validity_*``: guaranteed 1.0 by the FSM masks,
model-independent) — alongside the diff checkpoint's effective-lambda
record (obs/introspect.py), so validity and the learned λ drift land
in the same row and can be correlated across checkpoints of a run::

    python tools/constrain_report.py --diff-ckpt runs/diff/best_model.ckpt \
        --control-ckpt runs/control/best_model.ckpt --spec json --check

``--check`` turns the report into a gate: exit 2 unless the
constrained arms are BOTH exactly 1.0 (the subsystem's contract — a
single invalid constrained output means masks leaked). ``--smoke``
substitutes tiny random-init models (validity of the constrained arms
is still 1.0 by construction; the natural arms are then just noise).

Prompts are synthetic over a printable-ASCII char vocabulary — the
same id -> text convention data/tokenizer.vocab_strings feeds the real
server — so the tool needs no tokenizer directory.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

_SPECS = {
    "json": {"json_schema": json.dumps({
        "type": "object",
        "properties": {"ok": {"type": "boolean"}},
        "required": ["ok"],
    })},
    "regex": {"regex": "[ab]{4,8}"},
    "choices": {"choices": ("yes", "no", "maybe")},
}


def _validity(outs, fsm, eos):
    n = 0
    for out in outs:
        toks = list(out.tokens)
        if eos is not None and toks and toks[-1] == eos:
            toks.pop()
        if fsm.matches(toks):
            n += 1
    return n / max(1, len(outs))


def _run_family(params, model_cfg, serving, vocab, prompts, ckw,
                new_tokens, seed):
    """One family, both arms. Returns (natural, constrained) validity
    plus the engine's constraint-cache stats."""
    from differential_transformer_replication_tpu.serving import (
        SamplingParams,
        ServingEngine,
    )
    from differential_transformer_replication_tpu.serving.constrain import (
        compile_constraint,
        spec_key,
    )

    engine = ServingEngine(params, model_cfg, serving, vocab=vocab)
    eos = serving.eos_token_id

    def _arm(constrained):
        ps = [
            SamplingParams(
                max_new_tokens=new_tokens, temperature=0.0,
                seed=seed + i, **(ckw if constrained else {}),
            )
            for i in range(len(prompts))
        ]
        return engine.generate(prompts, params=ps)

    natural = _arm(False)
    constrained = _arm(True)
    fsm = compile_constraint(
        spec_key(
            SamplingParams(max_new_tokens=new_tokens, **ckw), eos
        ),
        vocab,
    )
    return (
        _validity(natural, fsm, eos),
        _validity(constrained, fsm, eos),
        engine.constrain_stats(),
    )


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--diff-ckpt", default=None,
                   help="diff-family checkpoint dir (best_model.ckpt)")
    p.add_argument("--control-ckpt", default=None,
                   help="control-family checkpoint dir")
    p.add_argument("--smoke", action="store_true",
                   help="tiny random-init diff + control instead of "
                        "checkpoints; seconds on CPU")
    p.add_argument("--spec", default="json",
                   choices=tuple(sorted(_SPECS)),
                   help="canned constraint over the ASCII char vocab "
                        "(same set as serve_bench --constrained)")
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--check", action="store_true",
                   help="gate mode: exit 2 unless BOTH constrained "
                        "arms report validity exactly 1.0")
    p.add_argument("--out", default=None,
                   help="also append the JSON line to this file")
    args = p.parse_args()
    if not args.smoke and not (args.diff_ckpt and args.control_ckpt):
        raise SystemExit(
            "pass --diff-ckpt AND --control-ckpt, or --smoke"
        )

    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        ServingConfig,
    )
    from differential_transformer_replication_tpu.obs.introspect import (
        lambda_record,
        make_param_summary,
    )

    if args.smoke:
        families = {}
        for fam in ("diff", "control"):
            cfg = ModelConfig(
                model=fam, vocab_size=128, n_embd=32, n_head=2,
                n_layer=2, block_size=64, dropout=0.0,
                compute_dtype="float32",
            )
            from differential_transformer_replication_tpu.models import (
                init_model,
            )

            families[fam] = (
                init_model(jax.random.PRNGKey(args.seed), cfg), cfg
            )
    else:
        from differential_transformer_replication_tpu.train.checkpoint import (  # noqa: E501
            load_params_for_inference,
        )

        families = {}
        for fam, ck in (("diff", args.diff_ckpt),
                        ("control", args.control_ckpt)):
            params, cfg, _ = load_params_for_inference(ck)
            if (fam == "diff") != (cfg.model in ("diff", "ndiff")):
                raise SystemExit(
                    f"--{fam}-ckpt {ck} is a {cfg.model!r}-family "
                    "checkpoint"
                )
            families[fam] = (params, cfg)

    ckw = _SPECS[args.spec]
    line = {"metric": "constrained_schema_validity",
            "constrained_spec": args.spec,
            "n_requests": args.requests,
            "new_tokens": args.new_tokens,
            "smoke": bool(args.smoke)}
    ok = True
    for fam, (params, cfg) in families.items():
        if cfg.vocab_size < 128:
            raise SystemExit(
                f"{fam} vocab_size {cfg.vocab_size} < 128: the char "
                "vocab must cover printable ASCII for the canned specs"
            )
        vocab = [
            chr(i) if 32 <= i < 127 else ""
            for i in range(cfg.vocab_size)
        ]
        rng = np.random.default_rng(args.seed)
        prompts = [
            rng.integers(
                0, cfg.vocab_size,
                size=int(rng.integers(4, 13)),
            ).tolist()
            for _ in range(args.requests)
        ]
        serving = ServingConfig(
            num_slots=min(8, max(1, args.requests)),
            prefill_chunk=16, prefill_budget=32,
            max_seq_len=(0 if cfg.model == "diff"
                         else cfg.block_size + args.new_tokens),
        )
        nat, con, cstats = _run_family(
            params, cfg, serving, vocab, prompts, ckw,
            args.new_tokens, args.seed,
        )
        line[f"natural_validity_{fam}"] = round(nat, 5)
        line[f"constrained_validity_{fam}"] = round(con, 5)
        line[f"constraint_cache_hits_{fam}"] = cstats["hits_total"]
        ok = ok and con == 1.0
        # λ record for the differential family: the paper's per-layer
        # effective lambda lands in the SAME row as the validity split
        if cfg.model in ("diff", "ndiff"):
            summary = make_param_summary(cfg)(params)
            rec = lambda_record(jax.device_get(summary), cfg)
            lams = [v for k, v in rec.items()
                    if k.startswith("lambda_l")
                    and not k.startswith("lambda_init")]
            line.update(
                {k: v for k, v in rec.items() if k.startswith("lambda")}
            )
            if lams:
                line["lambda_mean"] = round(
                    float(np.mean(lams)), 6
                )
    line["natural_vs_constrained_gap_diff"] = round(
        line["constrained_validity_diff"]
        - line["natural_validity_diff"], 5
    )
    line["check"] = bool(args.check)
    line["ok"] = ok
    print(json.dumps(line))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(line) + "\n")
    if args.check and not ok:
        print(
            "[constrain_report] FAIL: a constrained arm reported "
            "validity < 1.0 — the FSM masks leaked an invalid token",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
